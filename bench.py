"""Benchmark: the five BASELINE.md configs on the local accelerator.

Prints ONE JSON line whose required keys are the headline metric
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
(config #3, the GLMix 2-coordinate sweep — same metric as round 1) plus:
    "backend":  the JAX platform actually measured ("axon"/"tpu" or "cpu"
                when the accelerator tunnel is down — the bench ALWAYS emits
                a valid line, it never hangs on a dead backend),
    "scale":    dataset divisor applied on the cpu fallback,
    "configs":  per-config results for all five BASELINE.md:24-28 configs:
                a1a (LBFGS logistic), sparse1m (1M-feature Poisson TRON),
                glmix2 (fixed+per-user), glmix3 (fixed+per-user+per-item),
                gp_tune (Bayesian L2 auto-tune).  Each carries value/unit,
                vs_baseline, a correctness gate (quality.pass), and a FLOP
                estimate with MFU against the v5e bf16 peak.

The reference publishes no numbers (BASELINE.json published: {}), so
vs_baseline is measured against self-contained numpy/scipy implementations
of the same training semantics run on this machine — the stand-in for the
reference's Spark-CPU execution model (single-node local[*] is also how the
reference's own regression baselines were captured,
GameTrainingDriverIntegTest.scala:79-80).  For the single-coordinate configs
the baseline is *time-to-target*: the wall time scipy needs to first reach
the accelerator's final objective value.  CPU stand-in timings are cached in
.bench_cpu_cache.json (keyed by config+sizes+target) so repeat runs don't
re-pay scipy.

Process layout: EVERY accelerator touch runs in a watchdog subprocess
(`bench.py --config NAME --platform P`), so a wedged device backend (e.g.
the tunnel after an abrupt client kill) costs one timeout instead of hanging
the whole bench; scipy stand-ins run in the parent (no jax).  A fast
`--probe` subprocess picks the platform up front.  NOTE: jax is pre-imported
at interpreter startup in this image, so the JAX_PLATFORMS env var is
ignored — subprocesses select the platform via jax.config.update before
first device use.

Env knobs:
    PHOTON_BENCH_CONFIGS   comma list (default all five)
    PHOTON_BENCH_IMPL      fused|host for the glmix sweeps (default: fused,
                           host retry on failure)
    PHOTON_BENCH_STORAGE   e.g. bfloat16 — mixed-precision design storage
    PHOTON_BENCH_PROBE_TIMEOUT / PHOTON_BENCH_CONFIG_TIMEOUT (seconds)
    PHOTON_BENCH_CPU_SCALE dataset divisor on the cpu fallback (default 8)
    PHOTON_BENCH_CPU_REF   0 skips scipy stand-ins (vs_baseline null)
    PHOTON_BENCH_SELF_TIMEOUT  seconds before a child --probe/--config
                           process SIGALRMs itself; set automatically by the
                           orchestrator (inside the parent's subprocess
                           timeout) so a hung device call dies by clean
                           signal, never by a tunnel-wedging parent SIGKILL
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

OUTER = 2  # coordinate-descent sweeps timed in the glmix configs
SOLVER_ITERS = 30  # inner solver iterations per coordinate update
PEAK_BF16 = 197e12  # TPU v5e (v5 litepod) bf16 peak FLOP/s, for MFU
# A GLM solve is BANDWIDTH-bound, not FLOP-bound (arithmetic intensity of a
# value+grad pass is ~2-4 FLOP/byte vs the v5e ridge ~240), so the honest
# roofline metric is achieved HBM bytes/s against the chip's peak — every
# config reports hbm_bw_util alongside MFU (VERDICT r3 missing #2).
PEAK_HBM = 819e9  # TPU v5e HBM bandwidth, bytes/s
_SYNTH_V = 2  # synthetic-data generation version (keys the scipy cache)
ALL_CONFIGS = ("a1a", "sparse1m", "glmix2", "glmix3", "gp_tune",
               "glmix_chip")
_REPO = os.path.dirname(os.path.abspath(__file__))
_CACHE = os.path.join(_REPO, ".bench_cpu_cache.json")


# --------------------------------------------------------------------------
# host-side helpers (numpy/scipy only — safe with a dead accelerator)
# --------------------------------------------------------------------------

def _np_auc(y: np.ndarray, s: np.ndarray) -> float:
    """Rank AUC (average ranks on ties), matching evaluation/metrics.py."""
    import scipy.stats as st

    y = np.asarray(y, bool)
    r = st.rankdata(s)
    n1 = int(y.sum())
    n0 = len(y) - n1
    if n1 == 0 or n0 == 0:
        return float("nan")
    return float((r[y].sum() - n1 * (n1 + 1) / 2) / (n1 * n0))


def _cache_get(key: str):
    try:
        with open(_CACHE) as f:
            return json.load(f).get(key)
    except (OSError, json.JSONDecodeError):
        return None


def _cache_put(key: str, val) -> None:
    try:
        with open(_CACHE) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        d = {}
    d[key] = val
    with open(_CACHE, "w") as f:
        json.dump(d, f, indent=1, sort_keys=True)


class _TimeToTarget:
    """Wraps a scipy objective; records (elapsed, f) per call so the caller
    can read off the first time the trace reached a target value."""

    def __init__(self, fun):
        self.fun = fun
        self.trace = []
        self.t0 = time.perf_counter()

    def __call__(self, w, *args):
        f = self.fun(w, *args)
        self.trace.append((time.perf_counter() - self.t0, float(f)))
        return f

    def time_to(self, target: float, rel: float = 1e-4):
        bar = target + rel * abs(target)
        for t, f in self.trace:
            if f <= bar:
                return t
        return None


# --------------------------------------------------------------------------
# synthetic datasets — deterministic, regenerated identically in the
# accelerator subprocess and the scipy parent
# --------------------------------------------------------------------------

def synth_a1a():
    """a1a-shaped stand-in (the reference quick-start dataset,
    README.md:240-297, is not redistributable in this image): 30,956 rows x
    123 binary features + intercept, ~14 active features/row."""
    rng = np.random.default_rng(11)
    n, d, k = 30956, 124, 14
    idx = np.empty((n, k), np.int32)
    for i in range(n):  # unique per-row feature ids, like one-hot groups
        idx[i] = rng.choice(d - 1, size=k, replace=False) + 1
    idx[:, 0] = 0  # intercept slot
    vals = np.ones((n, k), np.float32)
    w_true = (rng.normal(size=d) * 0.7).astype(np.float64)
    z = w_true[idx].sum(axis=1)
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
    return idx, vals, y, d


def synth_sparse1m(scale: int):
    """BASELINE #2: 1M-feature sparse Poisson. Feature ids power-law-ish so
    hot columns exist (realistic collision pattern for the scatter-add)."""
    rng = np.random.default_rng(12)
    n, d, k = 131072 // scale, 1_000_000, 32
    # half the slots draw from a hot 4096-id head, half from the 1M tail;
    # each slot samples within its own disjoint id block, so per-row indices
    # are unique by construction (SparseBatch contract) with no dedup pass
    kh = k // 2
    head_block = 4096 // kh
    head = (np.arange(kh) * head_block)[None, :] + rng.integers(
        0, head_block, size=(n, kh))
    kt = k - kh
    tail_block = (d - 4096) // kt
    tail = 4096 + (np.arange(kt) * tail_block)[None, :] + rng.integers(
        0, tail_block, size=(n, kt))
    idx = np.concatenate([head, tail], axis=1).astype(np.int32)
    vals = rng.exponential(0.5, size=(n, k)).astype(np.float32)
    w_true = rng.normal(size=d) * 0.05
    z = np.clip((vals * w_true[idx]).sum(axis=1), -4, 4)
    y = rng.poisson(np.exp(z)).astype(np.float32)
    return idx, vals, y, d


def synth_glmix(scale: int, three: bool):
    """BASELINE #3/#4 GLMix data: 2048 users (+1024 items for #4).

    Round-4 regeneration (VERDICT r3 weak #3): the old coefficients made
    the task nearly separable (gate AUCs 0.9998/1.0 — a subtly broken
    residual fold or reg weight would still pass), so (a) coefficient
    scales put the generative logit std near 1 (measured Bayes AUC 0.73 —
    the AUC gate band is falsifiable at FULL scale; at reduced scales
    per-user overfit still saturates the training AUC, so there the
    coefficient-parity check below is the load-bearing gate) and (b) the
    random-effect shards CORRELATE with the fixed shard's leading columns
    — independent per-coordinate fits then double-count the shared signal,
    so a broken residual fold visibly moves the fixed coefficients even
    when the AUC survives (tests/test_bench.py proves both sabotages fail
    the gate)."""
    rng = np.random.default_rng(42)
    n_users, d_g, d_u = 2048, (128 if three else 256), 16
    per_user = (128 if three else 256) // scale
    n = n_users * per_user
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = (0.6 * xg[:, :d_u]
          + 0.8 * rng.normal(size=(n, d_u))).astype(np.float32)
    uids = np.repeat(np.arange(n_users), per_user)
    wg = (rng.normal(size=d_g) * 0.05).astype(np.float32)
    wu = (rng.normal(size=(n_users, d_u)) * 0.15).astype(np.float32)
    logits = xg @ wg + np.einsum("nd,nd->n", xu, wu[uids])
    out = {"xg": xg, "xu": xu, "uids": uids}
    if three:
        n_items, d_i = 1024, 16
        xi = (0.6 * xg[:, d_u:d_u + d_i]
              + 0.8 * rng.normal(size=(n, d_i))).astype(np.float32)
        iids = rng.integers(0, n_items, size=n)
        wi = (rng.normal(size=(n_items, d_i)) * 0.15).astype(np.float32)
        logits = logits + np.einsum("nd,nd->n", xi, wi[iids])
        out.update(xi=xi, iids=iids)
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    out["y"] = y
    perm = rng.permutation(n)
    return {k: v[perm] for k, v in out.items()}


def synth_tune(scale: int):
    rng = np.random.default_rng(7)
    n_users, per_user, d_g, d_u = 256, 256 // scale, 64, 8
    n = n_users * per_user
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = rng.normal(size=(n, d_u)).astype(np.float32)
    uids = np.repeat(np.arange(n_users), per_user)
    wg = (rng.normal(size=d_g) * 0.5).astype(np.float32)
    wu = (rng.normal(size=(n_users, d_u))).astype(np.float32)
    logits = xg @ wg + np.einsum("nd,nd->n", xu, wu[uids])
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    perm = rng.permutation(n)
    return xg[perm], xu[perm], uids[perm], y[perm]


D_SIG, D_CHIP_G, D_CHIP_U = 16, 512, 4  # glmix_chip feature widths
CHIP_CAP = 32        # per-entity active-sample cap (reference activeDataUpperBound)
_CHIP_P = 8191       # prime phase period of the counter-based signal columns
# shared by run_glmix_chip (device generation) and _chip_design_host (host
# reconstruction): the fold_in chunk boundaries and seed must agree BITWISE
# or the floor-scale parity gate fails for a reason that looks like a
# solver bug
CHIP_CHUNK = 1 << 19
CHIP_SEED = 99


def _chip_sizes(scale: int):
    """(users, per_user) for glmix_chip: scale 1 = 131072 users x 64 =
    8.39M examples (the v5e sizing, VERDICT r3 #2: >=0.5 s/sweep, >=100k
    entities); larger scales shrink per_user first, then the entity count."""
    users = 131072 // max(1, scale // 8)
    # floor 16: fewer active samples per entity than that makes the
    # random-effect fit overfit its 4 params, inflating TRAINING AUC past
    # the gate band the chip-scale sizing calibrates
    per_user = max(16, 64 // min(max(scale, 1), 8))
    return users, per_user


def _chip_signal_cols(i, xp):
    """Counter-based signal columns h[i, j] = sin(2π·((i mod P)·k_j mod P)/P)
    — computable IDENTICALLY on host (labels) and device (the design matrix):
    the phase arithmetic is exact integer math in both, so host f64 and
    device f32 sins agree to f32 precision.  This is what lets the [n, 512]
    design live only in HBM while the host still knows the generative
    logits.  ``xp`` is numpy or jax.numpy."""
    k = 1 + 37 * (xp.arange(D_SIG, dtype=xp.int32) + 1)
    im = (xp.asarray(i) % _CHIP_P).astype(xp.int32)
    ph = (im[:, None] * k[None, :]) % _CHIP_P  # < P*P < 2^31: exact in int32
    return xp.sin(ph.astype(xp.float32) * np.float32(2.0 * np.pi / _CHIP_P))


def synth_glmix_chip(scale: int):
    """Host half of the chip-scale GLMix: labels, RE features and entity ids
    — everything EXCEPT the giant fixed design (device-generated inside
    run_glmix_chip).  Generative logits are moderate (std ~1.3) so the task
    carries REAL label noise — Bayes AUC ~0.8, a falsifiable band, unlike
    the near-separable glmix2/glmix3 synthetics (VERDICT r3 weak #3)."""
    users, per_user = _chip_sizes(scale)
    n = users * per_user
    rng = np.random.default_rng(1234)
    uids = np.repeat(np.arange(users, dtype=np.int64), per_user)
    xu = rng.normal(size=(n, D_CHIP_U)).astype(np.float32)
    wg_sig = rng.normal(size=D_SIG) * 0.4
    wu = (rng.normal(size=(users, D_CHIP_U)) * 0.35).astype(np.float32)
    logits = np.empty(n, np.float64)
    ch = 1 << 20
    for lo in range(0, n, ch):
        hi = min(lo + ch, n)
        i = np.arange(lo, hi, dtype=np.int64)
        h = _chip_signal_cols(i, np).astype(np.float64)
        logits[lo:hi] = h @ wg_sig + np.einsum(
            "nd,nd->n", xu[lo:hi].astype(np.float64),
            wu[uids[lo:hi]].astype(np.float64))
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    return {"y": y, "uids": uids, "xu": xu, "n": n, "users": users,
            "per_user": per_user}


# --------------------------------------------------------------------------
# accelerator-side config runners (subprocess only)
# --------------------------------------------------------------------------

def _select_platform(platform: str | None):
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    return jax.devices()[0].platform


def _measure(thunk, min_repeats=5, max_total=120.0, min_window=0.5):
    """Median-of-repeats timing for an already-warm thunk.

    A single sub-second window is dispatch-jitter noise (a 19ms a1a run
    headlined round 2 — VERDICT r2 weak #5), so every config repeats its
    timed section >=min_repeats times, and — for thunks so fast that five
    repeats still measure mostly dispatch (TPU a1a's whole solve is ~0.1ms)
    — keeps repeating until min_window seconds of samples exist (capped at
    5000 repeats).  Slow full-scale configs stop at max_total seconds; each
    of their repeats is seconds long anyway.  Reports the MEDIAN + spread."""
    dts = []
    total = 0.0
    # 5000-repeat cap: at TPU-a1a's ~0.1ms/solve that still accumulates the
    # full 0.5s window (a 200 cap would stop at ~20ms of samples and leave
    # the median dispatch-jitter-bound — the exact failure mode this guards)
    while (len(dts) < min_repeats or total < min_window) and \
            total < max_total and len(dts) < 5000:
        dt = thunk()
        dts.append(dt)
        total += dt
    med = float(np.median(dts))
    return med, {"n_repeats": len(dts), "dt_median": round(med, 4),
                 "dt_min": round(min(dts), 4), "dt_max": round(max(dts), 4)}


def _sparse_pass_bytes(n: int, k: int, width: int = 4) -> int:
    """HBM bytes one objective pass over a row-padded COO design moves
    (useful-traffic lower bound): the [n, k] index (int32) + value arrays
    stream once, each active slot gathers a coefficient and contributes to
    the scatter-add (~2 coefficient-width touches), and ~4 per-example [n]
    vectors (y/weight/offset + the margin/residual intermediate) stream at
    f32."""
    return n * k * (4 + width + 2 * width) + n * 4 * 4


def _storage_width(storage_dtype: "str | None") -> int:
    """Bytes per design-matrix element under a PHOTON_BENCH_STORAGE name
    (any ml_dtypes-registered dtype), 4 (f32) when unset."""
    if not storage_dtype:
        return 4
    import ml_dtypes  # noqa: F401  (registers bfloat16 etc. with numpy)

    return np.dtype(storage_dtype).itemsize


def _dense_pass_bytes(n: int, d: int, width: int = 4) -> int:
    """HBM bytes one objective pass over a dense [n, d] design moves: X
    streams once at storage width (the pallas kernels make this literal —
    one VMEM pass per value+grad; plain XLA re-reads it, so this is the
    lower bound) plus ~4 [n] f32 vectors."""
    return n * d * width + n * 4 * 4


def _solve_single(idx, vals, y, d, *, loss, optimizer, solver_cfg, l2):
    """jit one make_solver fit over a SparseBatch; returns (dt, result)."""
    import jax

    from photon_ml_tpu.core.batch import sparse_batch
    from photon_ml_tpu.core.losses import logistic_loss, poisson_loss
    from photon_ml_tpu.core.objective import GLMObjective
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.opt.solve import make_solver

    batch = sparse_batch(idx, vals, y, dim=d)
    obj = GLMObjective(loss={"logistic": logistic_loss,
                             "poisson": poisson_loss}[loss],
                       reg=Regularization(l2=l2))
    solve = jax.jit(make_solver(obj, optimizer, solver_cfg))
    w0 = np.zeros(d, np.float32)
    res = solve(w0, batch)
    jax.block_until_ready(res.w)  # warm-up: compile

    def thunk():
        t0 = time.perf_counter()
        r = solve(w0, batch)
        jax.block_until_ready(r.w)
        return time.perf_counter() - t0

    dt, timing = _measure(thunk)
    return dt, timing, res, batch


def run_a1a(platform, scale):
    """BASELINE #1: fixed-effect logistic LBFGS+L2 on a1a-shaped data."""
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import OptimizerType

    backend = _select_platform(platform)
    idx, vals, y, d = synth_a1a()
    dt, timing, res, batch = _solve_single(
        idx, vals, y, d, loss="logistic", optimizer=OptimizerType.LBFGS,
        solver_cfg=SolverConfig(max_iters=100, tolerance=1e-7), l2=1.0)
    import jax.numpy as jnp

    margins = np.asarray(batch.margins(jnp.asarray(res.w)))
    iters = int(res.iterations)
    n = len(y)
    return {
        "backend": backend, "dt": dt, "timing": timing,
        "units": n * iters, "unit": "example_iters/sec",
        # one value+grad pass over a sparse design ~ 4 flops/nnz; LBFGS
        # does ~1 such eval per iteration (line-search extras uncounted)
        "flops_est": iters * 4 * n * idx.shape[1],
        "bytes_est": iters * _sparse_pass_bytes(n, idx.shape[1]),
        "stats": {"final_value": float(res.value), "iters": iters,
                  "auc": _np_auc(y, margins)},
    }


def run_sparse1m(platform, scale):
    """BASELINE #2: 1M-feature sparse Poisson, TRON.

    L2-only: the reference itself rejects TRON with L1/elastic-net
    (OptimizerFactory.scala:71-72), so BASELINE.md's "TRON + elastic-net"
    wording is unattainable in the reference too — this config matches what
    the reference can actually run."""
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import OptimizerType

    backend = _select_platform(platform)
    idx, vals, y, d = synth_sparse1m(scale)
    cfg = SolverConfig.tron_default()
    dt, timing, res, _ = _solve_single(
        idx, vals, y, d, loss="poisson", optimizer=OptimizerType.TRON,
        solver_cfg=cfg, l2=1.0)
    iters = int(res.iterations)
    n = len(y)
    return {
        "backend": backend, "dt": dt, "timing": timing,
        "units": n * iters, "unit": "example_iters/sec",
        # per TRON iteration: 1 value+grad + <=max_cg Hv passes, each
        # ~4 flops/nnz (upper-bound estimate: CG often stops early)
        "flops_est": iters * (1 + cfg.max_cg) * 4 * n * idx.shape[1],
        "bytes_est": iters * (1 + cfg.max_cg)
        * _sparse_pass_bytes(n, idx.shape[1]),
        "stats": {"final_value": float(res.value), "iters": iters,
                  "mean_nll": float(res.value) / n},
    }


def _glmix_coords(data, three: bool):
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    feats = {"g": data["xg"], "u": data["xu"]}
    tags = {"userId": data["uids"]}
    if three:
        feats["i"] = data["xi"]
        tags["itemId"] = data["iids"]
    gd = GameData(y=data["y"], features=feats, id_tags=tags)
    solver = SolverConfig(max_iters=SOLVER_ITERS, tolerance=1e-7)
    task = TaskType.LOGISTIC_REGRESSION
    storage = os.environ.get("PHOTON_BENCH_STORAGE") or None
    coords = {
        "fixed": build_coordinate(
            "fixed", gd, FixedEffectConfig(feature_shard="g", solver=solver,
                                           reg=Regularization(l2=1.0),
                                           storage_dtype=storage), task),
        "per-user": build_coordinate(
            "per-user", gd,
            RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                               solver=solver, reg=Regularization(l2=1.0),
                               storage_dtype=storage), task),
    }
    if three:
        coords["per-item"] = build_coordinate(
            "per-item", gd,
            RandomEffectConfig(random_effect_type="itemId", feature_shard="i",
                               solver=solver, reg=Regularization(l2=1.0),
                               storage_dtype=storage), task)
    return coords


def run_glmix(platform, scale, three: bool):
    """BASELINE #3/#4: GLMix coordinate-descent sweep throughput."""
    backend = _select_platform(platform)
    data = synth_glmix(scale, three)
    # measured default: the fused whole-descent program wins EVERYWHERE now.
    # Round 2's "host ~2x ahead on CPU" and round 3's early "~1.3x at full
    # scale" readings were both artifacts of the same root cause: the
    # gradient's X^T r ran as a transposed matvec, which XLA CPU executes as
    # a cache-hostile column-major walk (20x slower than the equivalent
    # r @ X at [512k, 256] — core/objective._xt_dot).  With the contraction
    # rewritten, clean medians (n_repeats=5, no concurrent load): fallback
    # scale fused 0.48s vs host 0.52s; full scale fused 5.2s vs host 5.6s
    # (down from 54s/40s).  The orchestrator still records BOTH impls
    # (glmix2_{fused,host}) every run so the claim stays measured.
    # Upload the fixed-effect shard (the giant one) ONCE up front; the
    # random-effect shards stay host-side for bucketing.  Both the fused
    # attempt and the in-process host fallback below then share it via the
    # device-array passthrough in chunked_device_put.
    from photon_ml_tpu.utils.transfer import chunked_device_put

    if not os.environ.get("PHOTON_BENCH_STORAGE"):
        # Narrowed-storage (bf16) runs upload host-narrowed bytes inside
        # coords construction — half the wire traffic; pre-uploading f32
        # here would double it AND leave f32+narrow copies in HBM.
        data = dict(data)
        data["xg"] = chunked_device_put(data["xg"])
    impl = os.environ.get("PHOTON_BENCH_IMPL")
    if impl:
        return _glmix_measure(backend, data, three, impl)
    try:
        return _glmix_measure(backend, data, three, "fused")
    except Exception:
        # In-process fallback keeps the already-uploaded design (a fresh
        # child would re-pay the tunnel's upload toll); the parent's
        # fresh-child host retry remains for child-death failures.  The
        # result carries fused_error so the PARENT can log it and skip the
        # pointless fused re-run in the A/B block (child stderr is
        # discarded on rc 0).
        import traceback

        tb = traceback.format_exc()
    # The host measurement runs OUTSIDE the except block: a live exception
    # pins the failed attempt's frames — and with them the fused coords' and
    # sweep's device buffers — for the whole fallback; after a device OOM
    # that would re-OOM the fallback too.
    sys.stderr.write("glmix fused impl failed in-process; host fallback\n"
                     + tb[-2000:] + "\n")
    got = _glmix_measure(backend, data, three, "host")
    got["fused_error"] = tb[-500:]
    return got


def _glmix_measure(backend, data, three: bool, impl: str):
    """Measure one glmix impl over (possibly device-resident) data.

    Split from run_glmix so the --ab-chain child can measure several
    variants over ONE design-matrix upload (the axon tunnel's data plane
    makes per-variant re-uploads the dominant cost)."""
    coords = _glmix_coords(data, three)
    if impl == "fused":
        from photon_ml_tpu.game.fused import FusedSweep

        sweep = FusedSweep(coords, num_iterations=OUTER)
        sweep.run()  # warm-up: compiles the whole program
        out = {}

        def thunk():
            t0 = time.perf_counter()
            out["model"], out["scores"] = sweep.run()
            return time.perf_counter() - t0

        dt, timing = _measure(thunk)
        total = np.sum([np.asarray(s) for s in out["scores"].values()], axis=0)
    else:
        from photon_ml_tpu.game import CoordinateDescent

        descent = CoordinateDescent(coords, num_iterations=OUTER)
        descent.run()
        out = {}

        def thunk():
            t0 = time.perf_counter()
            out["model"], _, _ = descent.run()
            return time.perf_counter() - t0

        dt, timing = _measure(thunk)
        model = out["model"]
        from photon_ml_tpu.game import GameData
        feats = {"g": data["xg"], "u": data["xu"]}
        tags = {"userId": data["uids"]}
        if three:
            feats["i"] = data["xi"]
            tags["itemId"] = data["iids"]
        total = model.score(GameData(y=data["y"], features=feats, id_tags=tags))
    n = len(data["y"])
    d_sum = data["xg"].shape[1] + data["xu"].shape[1] + (
        data["xi"].shape[1] if three else 0)
    width = _storage_width(os.environ.get("PHOTON_BENCH_STORAGE"))
    wg = np.asarray(out["model"]["fixed"].coefficients.means, np.float64)
    return {
        "backend": backend, "dt": dt, "timing": timing, "impl": impl,
        "units": n * OUTER, "unit": "examples/sec/chip",
        # per sweep each coordinate runs <=SOLVER_ITERS solver iterations,
        # each ~1 value+grad pass (4 flops per design-matrix entry)
        "flops_est": OUTER * SOLVER_ITERS * 4 * n * d_sum,
        "bytes_est": OUTER * SOLVER_ITERS
        * _dense_pass_bytes(n, d_sum, width),
        "stats": {"auc": _np_auc(data["y"], np.asarray(total)),
                  # fixed coefficients feed the gate's parity check against
                  # the scipy stand-in's solution at matched regularization
                  "wg": [round(float(v), 6) for v in wg]},
    }


def run_glmix2_ab_chain(platform, scale):
    """One child, ONE design upload, three measurements: glmix2 fused (the
    headline), host-loop, and fused-without-pallas.  Prints one JSON line
    per variant AS IT LANDS (flushed), so a mid-chain device wedge loses
    only the variants after it — the parent parses every line it got.

    Exists for slow transports: the per-variant child layout re-uploads the
    ~550MB full-scale design once per variant (~30min each on the axon
    tunnel); here the fixed-effect shard is chunk-uploaded once and shared
    via the device-array passthrough in utils/transfer.chunked_device_put.
    bf16 storage stays a separate child — its upload is different bytes
    (host-narrowed)."""
    import traceback

    backend = _select_platform(platform)
    data = dict(synth_glmix(scale, three=False))
    from photon_ml_tpu.utils.transfer import chunked_device_put

    data["xg"] = chunked_device_put(data["xg"])  # the giant shard, once
    variants = (("glmix2", "fused", {}),
                ("glmix2_host", "host", {}),
                ("glmix2_xla", "fused", {"PHOTON_GLM_DISABLE_PALLAS": "1"}))
    for name, impl, extra in variants:
        old = {k: os.environ.get(k) for k in extra}
        os.environ.update(extra)
        try:
            got = _glmix_measure(backend, data, False, impl)
            print(json.dumps({"variant": name, **got}), flush=True)
        except Exception:
            print(json.dumps({"variant": name,
                              "error": traceback.format_exc()[-2000:]}),
                  flush=True)
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


def run_glmix_chip(platform, scale):
    """Chip-scale GLMix (VERDICT r3 #2): 8.39M examples x 512 global
    features + 131072 per-user random effects (active cap 32) at scale 1 —
    sized so ONE fused sweep is >=0.5 s on a v5e, where the solve is
    HBM-BANDWIDTH-bound (arithmetic intensity ~2 FLOP/byte vs the v5e
    ridge ~240), making hbm_bw_util the headline roofline number.

    The [n, 512] fixed design NEVER exists on host and never crosses the
    wire (8GB would be a day of upload on the axon tunnel): its 16 signal
    columns are counter-based (_chip_signal_cols — the host computes labels
    from the same exact formula), the remaining 496 are device-generated
    noise, assembled chunk-by-donated-chunk in HBM.  Host uploads are the
    labels + the (capped) random-effect arrays, ~200MB total at scale 1.
    Timing uses FusedSweep.run_device — device outputs only, no [n]-vector
    downloads inside the window."""
    backend = _select_platform(platform)
    if backend == "cpu":
        # full-scale would be a 17GB f32 design on host RAM; the chip
        # config's cpu fallback floor is 1/16 scale (VERDICT allows 1/64)
        scale = max(scale, 16)
    import jax
    import jax.numpy as jnp
    from jax import lax

    host = synth_glmix_chip(scale)
    n = host["n"]
    storage = "bfloat16" if backend != "cpu" else None
    xdt = jnp.bfloat16 if storage else jnp.float32

    ch = min(n, CHIP_CHUNK)
    key = jax.random.PRNGKey(CHIP_SEED)

    def _chunk(key, start, rows: int):
        i = start + jnp.arange(rows, dtype=jnp.int32)
        h = _chip_signal_cols(i, jnp)
        noise = jax.random.normal(key, (rows, D_CHIP_G - D_SIG), jnp.float32)
        return jnp.concatenate([h, noise], axis=1).astype(xdt)

    # rows is static per compile: full chunks share one program, a ragged
    # final chunk (n not a multiple of ch at odd CPU_SCALE values) adds one
    fill = jax.jit(lambda buf, key, start, rows: lax.dynamic_update_slice(
        buf, _chunk(key, start, rows), (start, 0)),
        donate_argnums=0, static_argnums=3)
    xg = jnp.zeros((n, D_CHIP_G), xdt)
    for c, lo in enumerate(range(0, n, ch)):
        xg = fill(xg, jax.random.fold_in(key, c), lo, min(ch, n - lo))
    xg.block_until_ready()

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import (FixedEffectConfig, GameData,
                                    RandomEffectConfig)
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    gd = GameData(y=host["y"], features={"g": xg, "u": host["xu"]},
                  id_tags={"userId": host["uids"]})
    solver = SolverConfig(max_iters=SOLVER_ITERS, tolerance=1e-7)
    task = TaskType.LOGISTIC_REGRESSION
    coords = {
        "fixed": build_coordinate("fixed", gd, FixedEffectConfig(
            feature_shard="g", solver=solver, reg=Regularization(l2=1.0),
            storage_dtype=storage), task),
        "per-user": build_coordinate("per-user", gd, RandomEffectConfig(
            random_effect_type="userId", feature_shard="u", solver=solver,
            reg=Regularization(l2=1.0), active_cap=CHIP_CAP,
            storage_dtype=storage), task),
    }
    sweep = FusedSweep(coords, num_iterations=OUTER)
    jax.block_until_ready(sweep.run_device())  # warm-up: compile
    out = {}

    def thunk():
        t0 = time.perf_counter()
        pub, scores, _, _ = sweep.run_device()
        jax.block_until_ready(scores)
        out["pub"], out["scores"] = pub, scores
        return time.perf_counter() - t0

    dt, timing = _measure(thunk)

    def _profile_thunk():
        # one UNTIMED sweep under jax.profiler: device-side evidence for
        # the single-HBM-pass claim.  Runs AFTER the child has flushed its
        # result line (see main's config branch), so a profiler wedge can
        # never cost the config's numbers.
        prof = os.environ.get("PHOTON_BENCH_PROFILE_DIR")
        if not prof or backend == "cpu":
            return
        try:
            from jax import profiler as _profiler

            with _profiler.trace(prof):
                jax.block_until_ready(sweep.run_device())
            sys.stderr.write(f"profiler trace -> {prof}\n")
        except Exception as e:
            sys.stderr.write(f"profiler trace failed: {e}\n")

    # one-time host export AFTER the timed window (gate only)
    wg = np.asarray(out["pub"][0]).astype(np.float32)
    total = np.sum([np.asarray(s, np.float32) for s in out["scores"]], axis=0)
    act = min(CHIP_CAP, host["per_user"]) * host["users"]
    width = _storage_width(storage)
    return {
        "backend": backend, "dt": dt, "timing": timing, "impl": "fused",
        "_profile_thunk": _profile_thunk,
        "units": n * OUTER, "unit": "examples/sec/chip",
        "flops_est": OUTER * SOLVER_ITERS * 4 * (n * D_CHIP_G
                                                 + act * D_CHIP_U),
        "bytes_est": OUTER * SOLVER_ITERS * (
            _dense_pass_bytes(n, D_CHIP_G, width)
            + _dense_pass_bytes(act, D_CHIP_U, width)),
        "stats": {"auc": _np_auc(host["y"], total),
                  "signal_mean_abs": float(np.abs(wg[:D_SIG]).mean()),
                  "noise_mean_abs": float(np.abs(wg[D_SIG:]).mean()),
                  "n": n, "entities": host["users"],
                  "chip_scale": scale,
                  # coefficient vector for the floor-scale parity gate —
                  # only where the scipy stand-in can run on the same data
                  **({"wg": [round(float(v), 6) for v in wg]}
                     if backend == "cpu" else {})},
    }


def run_gp_tune(platform, scale):
    """BASELINE #5: Bayesian (GP) auto-tune of per-coordinate L2 weights."""
    backend = _select_platform(platform)
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import (FixedEffectConfig, GameData,
                                    GameEstimator, RandomEffectConfig)
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune import tune_game_model
    from photon_ml_tpu.types import TaskType

    from photon_ml_tpu.types import OptimizerType

    xg, xu, uids, y = synth_tune(scale)
    n = len(y)
    cut = int(n * 0.8)
    tr = GameData(y=y[:cut], features={"g": xg[:cut], "u": xu[:cut]},
                  id_tags={"userId": uids[:cut]})
    va = GameData(y=y[cut:], features={"g": xg[cut:], "u": xu[cut:]},
                  id_tags={"userId": uids[cut:]})
    solver = SolverConfig(max_iters=SOLVER_ITERS, tolerance=1e-7)
    # TRON for both coordinates: quadratic local convergence beats the
    # lock-step vmapped L-BFGS line search on these shapes (measured 1.4x,
    # same optimum to 1e-4) — the reference offers the same choice
    # (OptimizerFactory TRON; LIBLINEAR's recommended logistic solver)
    opt = OptimizerType.TRON
    # the prior (base-config) L2s are DELIBERATELY bad — the per-user weight
    # over-shrinks the strong random effects — so the quality gate can demand
    # the tuner actually finds a better config (best_auc > prior_auc), not
    # merely never regresses from an already-optimal prior
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        num_outer_iterations=OUTER,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=10.0),
                                       optimizer=opt),
            "per-user": RandomEffectConfig(random_effect_type="userId",
                                           feature_shard="u", solver=solver,
                                           reg=Regularization(l2=500.0),
                                           optimizer=opt),
        })
    est = GameEstimator(validation_suite=EvaluationSuite.from_specs(["auc"]))
    n_iter = 6
    from photon_ml_tpu.tune.game_tuning import GameEstimatorEvaluationFunction

    fn = GameEstimatorEvaluationFunction(est, config, tr, va, seed=0)
    # Batch Bayesian rounds (tune/search.py top-q EI portfolio): the same
    # 7-fit budget lands in 4 accelerator windows instead of 7 — the prior
    # fit, then three rounds whose 2 candidates ride ONE vmapped grid
    # program (FusedSweep.run_grid; the lanes share the design-matrix
    # streams).  The scipy stand-in stays 7 sequential fits: retraining q
    # candidates at once for the cost of ~one fit is precisely the
    # hardware-parallelism advantage this config exists to measure.
    # Unconditional even on the cpu fallback — same-host A/B: batched is
    # 10% faster at bench scale (halved gp_sec outweighs the lock-step
    # grid) and a wash at full scale (0.916s vs 0.922s, equal best_auc),
    # so one code path serves both backends.  PHOTON_BENCH_GP_BATCH=1
    # reproduces sequential mode for A/Bs.
    batch = int(os.environ.get("PHOTON_BENCH_GP_BATCH", "2"))
    # compile the shared single-fit AND batched grid programs outside the
    # window (grid warmup no-ops at batch=1)
    fn.warmup(grid_sizes=(batch,))
    out = {}

    def thunk():
        fn.results.clear()  # each repeat is a fresh tuning run
        fn.reset_phases()
        t0 = time.perf_counter()
        out["best"], out["search"], out["tuned"] = tune_game_model(
            est, config, tr, va, n_iterations=n_iter, mode="bayesian",
            seed=0, evaluation_function=fn, batch_size=batch)
        return time.perf_counter() - t0

    dt, timing = _measure(thunk)
    best, tuned = out["best"], out["tuned"]
    aucs = [r.evaluation.values["auc"] for r in tuned]
    return {
        "backend": backend, "dt": dt, "timing": timing,
        "units": len(tuned), "unit": "tuning_fits/sec",
        "flops_est": None,  # dominated by many small fits + GP host math
        "stats": {"best_auc": float(best.evaluation.values["auc"]),
                  "prior_auc": float(aucs[0]), "fits": len(tuned),
                  # phase breakdown of the LAST repeat (VERDICT r3 weak #6)
                  "phases": {"fit_sec": round(fn.fit_seconds, 3),
                             "eval_sec": round(fn.eval_seconds, 3),
                             "gp_sec": round(out["search"].gp_seconds, 3)}},
    }


# --------------------------------------------------------------------------
# scipy CPU stand-ins (parent process, cached)
# --------------------------------------------------------------------------

def _scipy_single(idx, vals, y, d, *, loss, l2, target, maxiter=300):
    """scipy L-BFGS time-to-target on a sparse design."""
    import scipy.optimize as sopt
    import scipy.sparse as ssp
    import scipy.special as sp

    n, k = vals.shape
    X = ssp.csr_matrix(
        (vals.ravel().astype(np.float64),
         (np.repeat(np.arange(n), k), idx.ravel())), shape=(n, d))
    yy = y.astype(np.float64)

    if loss == "logistic":
        def raw(w):
            z = X @ w
            return float(np.sum(np.logaddexp(0, z) - yy * z) + 0.5 * l2 * w @ w)

        def grad(w):
            z = X @ w
            return X.T @ (sp.expit(z) - yy) + l2 * w
    else:  # poisson: l = exp(z) - y*z
        def raw(w):
            z = X @ w
            return float(np.sum(np.exp(z) - yy * z) + 0.5 * l2 * w @ w)

        def grad(w):
            z = X @ w
            return X.T @ (np.exp(z) - yy) + l2 * w

    fun = _TimeToTarget(raw)
    t0 = time.perf_counter()
    sopt.minimize(fun, np.zeros(d), jac=grad, method="L-BFGS-B",
                  options={"maxiter": maxiter, "ftol": 1e-12, "gtol": 1e-9})
    total = time.perf_counter() - t0
    tt = fun.time_to(target)
    final = min(f for _, f in fun.trace)
    return {"dt_cpu": tt if tt is not None else total,
            "reached_target": tt is not None, "final_value": final}


def _scipy_glmix(data, three: bool, l2=1.0):
    """Same residual coordinate-descent loop as the accelerator sweep:
    scipy L-BFGS fixed effect + per-entity serial scipy solves."""
    import scipy.optimize as sopt
    import scipy.special as sp

    y = data["y"].astype(np.float64)
    xg = data["xg"].astype(np.float64)
    blocks = [("u", data["xu"].astype(np.float64), data["uids"])]
    if three:
        blocks.append(("i", data["xi"].astype(np.float64), data["iids"]))

    def nll(w, X, yy, off):
        z = X @ w + off
        return np.sum(np.logaddexp(0, z) - yy * z) + 0.5 * l2 * w @ w

    def grad(w, X, yy, off):
        z = X @ w + off
        return X.T @ (sp.expit(z) - yy) + l2 * w

    n = len(y)
    wg = np.zeros(xg.shape[1])
    state = {}
    for name, X, ids in blocks:
        ents = np.unique(ids)
        state[name] = (np.zeros((len(ents), X.shape[1])), ents,
                       {u: np.nonzero(ids == u)[0] for u in ents})
    scores = {name: np.zeros(n) for name, _, _ in blocks}
    fixed_scores = np.zeros(n)
    t0 = time.perf_counter()
    for _ in range(OUTER):
        off = np.sum(list(scores.values()), axis=0)
        r = sopt.minimize(nll, wg, jac=grad, args=(xg, y, off),
                          method="L-BFGS-B", options={"maxiter": SOLVER_ITERS})
        wg = r.x
        fixed_scores = xg @ wg
        for name, X, ids in blocks:
            W, ents, rows_of = state[name]
            other = fixed_scores + np.sum(
                [scores[o] for o in scores if o != name], axis=0)
            for ei, u in enumerate(ents):
                ridx = rows_of[u]
                r = sopt.minimize(nll, W[ei], jac=grad,
                                  args=(X[ridx], y[ridx], other[ridx]),
                                  method="L-BFGS-B",
                                  options={"maxiter": SOLVER_ITERS})
                W[ei] = r.x
            sc = np.einsum("nd,nd->n", X,
                           W[np.searchsorted(ents, ids)])
            scores[name] = sc
    dt = time.perf_counter() - t0
    total = fixed_scores + np.sum(list(scores.values()), axis=0)
    return {"dt_cpu": dt, "auc": _np_auc(data["y"], total),
            "wg": [round(float(v), 6) for v in wg]}


def _chip_design_host(scale: int) -> np.ndarray:
    """Reconstruct run_glmix_chip's device-generated design on host.

    jax's threefry PRNG is bitwise platform-deterministic, and the chunk/
    fold structure here mirrors run_glmix_chip's exactly, so the host f32
    array equals what the accel child trained on (signal columns agree to
    f32 rounding — _chip_signal_cols docstring).  Only reached on the CPU
    fallback path, where no axon child is attached (one-client rule)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    users, per_user = _chip_sizes(scale)
    n = users * per_user
    ch = min(n, CHIP_CHUNK)
    key = jax.random.PRNGKey(CHIP_SEED)
    xg = np.empty((n, D_CHIP_G), np.float32)
    for c, lo in enumerate(range(0, n, ch)):
        rows = min(ch, n - lo)
        i = np.arange(lo, lo + rows, dtype=np.int64)
        xg[lo:lo + rows, :D_SIG] = _chip_signal_cols(i, np)
        xg[lo:lo + rows, D_SIG:] = np.asarray(jax.random.normal(
            jax.random.fold_in(key, c), (rows, D_CHIP_G - D_SIG), jnp.float32))
    return xg


def _scipy_glmix_chip(scale: int):
    """Independent scipy stand-in for glmix_chip at a CPU-feasible scale
    (VERDICT r4 missing #3: the chip config's gate was self-referential).

    The same alternating residual loop as _scipy_glmix, adapted to the chip
    config's shape: the fixed objective streams the [n, 512] f32 design in
    chunks with f64 accumulation (no f64 copy of the whole design), and the
    per-entity solves slice contiguous uid blocks (synth_glmix_chip repeats
    uids, so no per-entity nonzero scans).  At the floor scales per_user
    (16) never exceeds CHIP_CAP (32), so no reservoir logic applies."""
    import scipy.optimize as sopt
    import scipy.special as sp

    host = synth_glmix_chip(scale)
    xg = _chip_design_host(scale)
    y = host["y"].astype(np.float64)
    xu = host["xu"].astype(np.float64)
    users, per_user = host["users"], host["per_user"]
    n = host["n"]
    l2 = 1.0
    ch = CHIP_CHUNK

    def fixed_nll_grad(w, off):
        val = 0.5 * l2 * float(w @ w)
        g = l2 * w
        for lo in range(0, n, ch):
            hi = min(lo + ch, n)
            z = xg[lo:hi] @ w.astype(np.float32) + off[lo:hi]
            z = z.astype(np.float64)
            val += float(np.sum(np.logaddexp(0, z) - y[lo:hi] * z))
            # f32 vec-mat against the design as stored — no transposed f64
            # chunk copies (2.1GB each at the cpu floor); the ~1e-6 relative
            # error is absorbed by the 5% parity band
            r32 = (sp.expit(z) - y[lo:hi]).astype(np.float32)
            g = g + (r32 @ xg[lo:hi]).astype(np.float64)
        return val, g

    def re_nll(w, X, yy, off):
        z = X @ w + off
        return np.sum(np.logaddexp(0, z) - yy * z) + 0.5 * l2 * w @ w

    def re_grad(w, X, yy, off):
        z = X @ w + off
        return X.T @ (sp.expit(z) - yy) + l2 * w

    wg = np.zeros(D_CHIP_G)
    W = np.zeros((users, D_CHIP_U))
    re_scores = np.zeros(n)
    fixed_scores = np.zeros(n)
    t0 = time.perf_counter()
    for _ in range(OUTER):
        r = sopt.minimize(fixed_nll_grad, wg, jac=True, args=(re_scores,),
                          method="L-BFGS-B",
                          options={"maxiter": SOLVER_ITERS})
        wg = r.x
        for lo in range(0, n, ch):
            hi = min(lo + ch, n)
            fixed_scores[lo:hi] = (xg[lo:hi] @ wg.astype(np.float32)
                                   ).astype(np.float64)
        for u in range(users):
            sl = slice(u * per_user, (u + 1) * per_user)
            r = sopt.minimize(re_nll, W[u], jac=re_grad,
                              args=(xu[sl], y[sl], fixed_scores[sl]),
                              method="L-BFGS-B",
                              options={"maxiter": SOLVER_ITERS})
            W[u] = r.x
            re_scores[sl] = xu[sl] @ W[u]
    dt = time.perf_counter() - t0
    total = fixed_scores + re_scores
    return {"dt_cpu": dt, "auc": _np_auc(host["y"], total),
            "wg": [round(float(v), 6) for v in wg]}


def cpu_ref(name: str, scale: int, accel_stats: dict):
    """vs_baseline stand-in for one config; cached on disk.

    Only the time-to-target configs key on the accel's final objective —
    the glmix/tuning loops ignore it, so A/B variants (bf16, pallas-off)
    reuse the same cached baseline instead of re-running scipy."""
    tgt = (round(accel_stats.get("final_value", 0), 2)
           if name in ("a1a", "sparse1m") else 0)
    # _SYNTH_V invalidates cached stand-ins whose generation changed in
    # round 4 (noisy + cross-shard-correlated glmix; gp_tune shares
    # _scipy_glmix's loop but its synth_tune data is unchanged) — scoped to
    # the glmix keys so the untouched a1a/sparse1m/gp_tune cache entries
    # (old 3-element key format) stay valid
    key = (json.dumps([name, scale, tgt, _SYNTH_V])
           if name in ("glmix2", "glmix3", "glmix_chip")
           else json.dumps([name, scale, tgt]))
    hit = _cache_get(key)
    if hit is not None:
        return hit
    if name == "a1a":
        idx, vals, y, d = synth_a1a()
        out = _scipy_single(idx, vals, y, d, loss="logistic", l2=1.0,
                            target=accel_stats["final_value"])
        out["auc"] = _scipy_auc_single(idx, vals, y, d, "logistic", 1.0)
    elif name == "sparse1m":
        idx, vals, y, d = synth_sparse1m(scale)
        out = _scipy_single(idx, vals, y, d, loss="poisson", l2=1.0,
                            target=accel_stats["final_value"])
        out["mean_nll"] = out["final_value"] / len(y)
    elif name in ("glmix2", "glmix3"):
        data = synth_glmix(scale, three=(name == "glmix3"))
        out = _scipy_glmix(data, three=(name == "glmix3"))
    elif name == "gp_tune":
        # one stand-in glmix fit, scaled by the number of tuning fits —
        # every fit retrains the same model at a different L2
        xg, xu, uids, y = synth_tune(scale)
        data = {"xg": xg, "xu": xu, "uids": uids, "y": y}
        one = _scipy_glmix(data, three=False)
        out = {"dt_cpu": one["dt_cpu"] * accel_stats.get("fits", 7),
               "per_fit": one["dt_cpu"]}
    elif name == "glmix_chip":
        # only reachable at a CPU-feasible scale (run_glmix_chip's cpu
        # floor, or the test-tier scales) — the chip-scale run keeps
        # vs_baseline null and inherits the floor-scale coefficient parity
        # as its falsifiable gate
        out = _scipy_glmix_chip(scale)
    else:
        raise KeyError(name)
    _cache_put(key, out)
    return out


def _scipy_auc_single(idx, vals, y, d, loss, l2):
    """Training AUC of a converged scipy solve (quality anchor for a1a)."""
    import scipy.optimize as sopt
    import scipy.sparse as ssp
    import scipy.special as sp

    n, k = vals.shape
    X = ssp.csr_matrix(
        (vals.ravel().astype(np.float64),
         (np.repeat(np.arange(n), k), idx.ravel())), shape=(n, d))
    yy = y.astype(np.float64)

    def raw(w):
        z = X @ w
        return float(np.sum(np.logaddexp(0, z) - yy * z) + 0.5 * l2 * w @ w)

    def grad(w):
        z = X @ w
        return X.T @ (sp.expit(z) - yy) + l2 * w

    r = sopt.minimize(raw, np.zeros(d), jac=grad, method="L-BFGS-B",
                      options={"maxiter": 300})
    return _np_auc(y, X @ r.x)


# --------------------------------------------------------------------------
# quality gates
# --------------------------------------------------------------------------

def quality_gate(name: str, stats: dict, ref: dict | None):
    """Correctness gate per config (BASELINE.md: matching validation
    AUC/RMSE within the reference's own integration-test thresholds)."""
    if name == "a1a":
        if ref is None or ref.get("auc") is None:
            return {"pass": None, "detail": "no cpu reference"}
        d = abs(stats["auc"] - ref["auc"])
        return {"pass": bool(d <= 0.005), "auc": stats["auc"],
                "auc_ref": ref["auc"], "auc_diff": round(d, 5)}
    if name == "sparse1m":
        if ref is None:
            return {"pass": None, "detail": "no cpu reference"}
        rel = abs(stats["mean_nll"] - ref["mean_nll"]) / max(
            abs(ref["mean_nll"]), 1e-12)
        return {"pass": bool(rel <= 1e-2), "mean_nll": stats["mean_nll"],
                "mean_nll_ref": ref["mean_nll"], "rel_diff": round(rel, 5)}
    if name in ("glmix2", "glmix3"):
        if ref is None:
            return {"pass": None, "detail": "no cpu reference"}
        d = abs(stats["auc"] - ref["auc"])
        gate = {"pass": bool(d <= 0.005), "auc": stats["auc"],
                "auc_ref": ref["auc"], "auc_diff": round(d, 5)}
        if stats.get("wg") is not None and ref.get("wg") is not None:
            # coefficient-level parity vs the scipy stand-in at matched
            # regularization (VERDICT r3 weak #3): a mis-set reg weight or
            # broken residual fold moves the fixed coefficients even when
            # the AUC survives
            wa = np.asarray(stats["wg"], np.float64)
            wr = np.asarray(ref["wg"], np.float64)
            rel = float(np.linalg.norm(wa - wr)
                        / max(np.linalg.norm(wr), 1e-12))
            gate["coef_rel_err"] = round(rel, 5)
            gate["pass"] = bool(gate["pass"] and rel <= 0.05)
        return gate
    if name == "gp_tune":
        # the prior config is deliberately mis-regularized (run_gp_tune), so
        # a working tuner MUST beat it — equality fails this gate
        ok = stats["best_auc"] > stats["prior_auc"] + 1e-4
        return {"pass": bool(ok), "best_auc": stats["best_auc"],
                "prior_auc": stats["prior_auc"],
                "improvement": round(stats["best_auc"] - stats["prior_auc"], 5)}
    if name == "glmix_chip":
        # the synthetic carries real label noise (Bayes AUC ~0.8): training
        # AUC must land in the band (a broken residual fold / reg weight
        # visibly moves it — unlike the near-separable glmix2 task), and the
        # fit must place its mass on the 16 signal columns, not the 496
        # noise columns
        ok = (0.70 <= stats["auc"] <= 0.92
              and stats["signal_mean_abs"] > 5 * stats["noise_mean_abs"])
        gate = {"pass": bool(ok), "auc": stats["auc"],
                "signal_mean_abs": round(stats["signal_mean_abs"], 5),
                "noise_mean_abs": round(stats["noise_mean_abs"], 5)}
        if ref is not None and stats.get("wg") is not None \
                and ref.get("wg") is not None:
            # floor-scale anchor (VERDICT r4 missing #3): an independent
            # scipy fit of the SAME data — coefficient parity makes the
            # chip config's gate falsifiable like glmix2's
            d = abs(stats["auc"] - ref["auc"])
            wa = np.asarray(stats["wg"], np.float64)
            wr = np.asarray(ref["wg"], np.float64)
            rel = float(np.linalg.norm(wa - wr)
                        / max(np.linalg.norm(wr), 1e-12))
            gate["auc_ref"] = ref["auc"]
            gate["auc_diff"] = round(d, 5)
            gate["coef_rel_err"] = round(rel, 5)
            gate["pass"] = bool(gate["pass"] and d <= 0.005 and rel <= 0.05)
        return gate
    return {"pass": None}


# --------------------------------------------------------------------------
# orchestration
# --------------------------------------------------------------------------

def _log_child_failure(msg: str) -> None:
    """Child failures must survive the run: the orchestrator's own stderr is
    routinely captured-and-discarded by outer harnesses (tpu_checklist), so
    a silent fused-impl crash would be undiagnosable after the fact."""
    sys.stderr.write(msg)
    try:
        with open(os.path.join(_REPO, ".bench_errors.log"), "a") as f:
            f.write(f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] "
                    f"{msg}\n")
    except OSError:
        pass


def _subprocess_json(args, timeout, env=None):
    try:
        env = dict(env if env is not None else os.environ)
        # child self-timeouts BEFORE the parent's SIGKILL, always: margin of
        # 30s for roomy timeouts, 5s for tight ones; assigned (not
        # setdefault) so a stale value from a manual child run can't leak in
        env["PHOTON_BENCH_SELF_TIMEOUT"] = str(
            max(1, timeout - (30 if timeout > 60 else 5)))
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout, cwd=_REPO,
            env=env)
        if out.returncode != 0:
            _log_child_failure(f"bench {args} failed (rc {out.returncode})\n"
                               f"{out.stderr[-2000:]}\n")
        return _last_json_dict(out.stdout)
    except subprocess.TimeoutExpired as e:
        # the parent timeout also salvages: a child that flushed its full
        # result then WEDGED in post-result work (profiler capture) should
        # count, with the failure logged
        stderr = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
        _log_child_failure(f"bench {args} parent-timeout (TimeoutExpired)\n"
                           f"{stderr[-2000:]}\n")
        stdout = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        return _last_json_dict(stdout)


def _last_json_dict(stdout: str):
    """Last stdout line that parses to a DICT (runner results are dicts;
    a stray library print that happens to be JSON must not reach the
    orchestrator's .get() calls)."""
    for ln in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(ln)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def _subprocess_json_lines(args, timeout, env=None):
    """Run a child that emits one JSON object per stdout line; parse EVERY
    parseable line, even when the child died mid-stream (a wedged device
    call should cost the un-emitted variants, not the finished ones)."""
    env = dict(env if env is not None else os.environ)
    env["PHOTON_BENCH_SELF_TIMEOUT"] = str(
        max(1, timeout - (30 if timeout > 60 else 5)))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout, cwd=_REPO,
            env=env)
        stdout, rc = out.stdout, out.returncode
        if rc != 0:
            _log_child_failure(f"bench {args} died (rc {rc}) after emitting "
                               f"{stdout.count(chr(10))} lines\n"
                               f"{out.stderr[-2000:]}\n")
    except subprocess.TimeoutExpired as e:
        stdout = (e.stdout or b"").decode() if isinstance(
            e.stdout, bytes) else (e.stdout or "")
        stderr = (e.stderr or b"").decode() if isinstance(
            e.stderr, bytes) else (e.stderr or "")
        _log_child_failure(f"bench {args} hard-timeout after {timeout}s\n"
                           f"{stderr[-2000:]}\n")
    lines = []
    for ln in stdout.splitlines():
        try:
            parsed = json.loads(ln)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            lines.append(parsed)
    return lines


def _entry_from(name: str, got: dict, scale: int, want_cpu_ref: bool) -> dict:
    """Per-config result entry: throughput, baseline ratio, quality gate,
    FLOP/MFU estimates."""
    ref = None
    if want_cpu_ref and name in CPU_REF_CONFIGS:
        ref = cpu_ref(name, scale, got["stats"])
    elif want_cpu_ref and name == "glmix_chip" \
            and got["stats"].get("wg") is not None:
        # cpu-floor run: the scipy stand-in trains on the same (host-
        # reconstructible) data, pinning coefficient parity at the floor;
        # chip-scale runs carry no ref (vs_baseline stays null)
        ref = cpu_ref(name, got["stats"]["chip_scale"], got["stats"])
    dt = got["dt"]
    entry = {
        "value": round(got["units"] / dt, 1),
        "unit": got["unit"],
        "dt_sec": round(dt, 3),
        "vs_baseline": (round(ref["dt_cpu"] / dt, 2) if ref else None),
        "quality": quality_gate(name, got["stats"], ref),
        "backend": got["backend"],
    }
    if got.get("timing"):
        entry["timing"] = got["timing"]
    if got["stats"].get("phases"):
        # where the wall-clock went (last repeat): fit vs validation-eval
        # vs host-side GP math — the gp_tune latency story lives here
        entry["phases"] = got["stats"]["phases"]
    if got.get("impl"):
        entry["impl"] = got["impl"]
    if got.get("fused_error"):
        entry["fused_error"] = got["fused_error"]
    on_chip = got["backend"] != "cpu"  # "tpu" or "axon" (the tunnel's name)
    if got.get("flops_est"):
        entry["gflops_per_sec"] = round(got["flops_est"] / dt / 1e9, 1)
        if on_chip:
            entry["mfu_bf16_peak"] = round(got["flops_est"] / dt / PEAK_BF16, 5)
    if got.get("bytes_est"):
        # useful-traffic lower bound (design-matrix streams + per-example
        # vectors per objective pass); the v5e roofline ratio is only
        # emitted when the measurement actually ran on the chip — a CPU
        # wall-clock divided by v5e peak invites misquoting
        entry["gbytes_per_sec"] = round(got["bytes_est"] / dt / 1e9, 1)
        if on_chip:
            entry["hbm_bw_util_v5e"] = round(got["bytes_est"] / dt / PEAK_HBM, 4)
    return entry


def _tpu_evidence_pointer(repo: str | None = None):
    """Pointer at banked accelerator evidence for cpu-fallback lines, or
    None.  Strictly best-effort: malformed/non-dict checklist content must
    never cost the run's own output (tested)."""
    if repo is None:
        repo = _REPO  # resolved at CALL time: tests monkeypatch bench._REPO
    try:
        with open(os.path.join(repo, "TPU_CHECKLIST.json")) as f:
            banked = json.load(f)
        bench_banked = (banked.get("bench")
                        if isinstance(banked, dict) else None)
        if isinstance(bench_banked, dict) \
                and bench_banked.get("backend") == "tpu":
            return {
                "file": "TPU_CHECKLIST.json",
                "captured": banked.get("started"),
                "note": "accelerator measurements banked by an earlier "
                        "healthy tunnel window (provenance: BASELINE.md "
                        "measured-status sections"
                        + (", window_note in the checklist file"
                           if banked.get("window_note") else "") + ")"}
    except (OSError, ValueError):
        return None
    return None


def probe_platform() -> str:
    """Fast backend probe in a subprocess; 'cpu' when the device is dead.

    PHOTON_BENCH_FORCE_PLATFORM skips the probe entirely — e.g. a CPU-floor
    gate run on a host whose accelerator is alive (the probe would win)."""
    forced = os.environ.get("PHOTON_BENCH_FORCE_PLATFORM")
    if forced:
        return forced
    to = int(os.environ.get("PHOTON_BENCH_PROBE_TIMEOUT", 120))
    got = _subprocess_json(["--probe"], timeout=to)
    if got and got.get("platform") and got["platform"] != "cpu":
        return got["platform"]
    sys.stderr.write("accelerator unavailable; falling back to cpu backend\n")
    return "cpu"


RUNNERS = {
    "a1a": lambda p, s: run_a1a(p, s),
    "sparse1m": lambda p, s: run_sparse1m(p, s),
    "glmix2": lambda p, s: run_glmix(p, s, three=False),
    "glmix3": lambda p, s: run_glmix(p, s, three=True),
    "gp_tune": lambda p, s: run_gp_tune(p, s),
    "glmix_chip": lambda p, s: run_glmix_chip(p, s),
}

def _synthetic_serving_engine(rng, n_entities, d, max_batch,
                              device_capacity=None, mesh_shards=0,
                              load_aware_routing=True, replicate_top_k=0):
    """Build the serving benches' in-memory 2-coordinate GLMix engine
    (fixed + per-user effects, no training, no disk).  Consumes from
    ``rng`` in a fixed order, so callers seeding identically get identical
    models.  ``mesh_shards`` > 0 shards the per-user table over the serving
    mesh (device_capacity becomes the PER-SHARD hot-row budget);
    ``load_aware_routing=False`` pins the pre-placement ``slot % N``
    router and ``replicate_top_k`` hot-replicates the traffic head.
    Returns (engine, metrics, feature_names)."""
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.serving.batcher import BucketedBatcher
    from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                         StoreConfig)
    from photon_ml_tpu.serving.engine import ScoringEngine
    from photon_ml_tpu.serving.metrics import ServingMetrics
    from photon_ml_tpu.types import TaskType

    names = [f"f{j}" for j in range(d)]
    imap = IndexMap({feature_key(n): j for j, n in enumerate(names)})
    eidx = EntityIndex()
    for i in range(n_entities):
        eidx.get_or_add(f"user{i}")
    task = TaskType.LOGISTIC_REGRESSION
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=d)),
            feature_shard="all", task=task),
        "per_user": RandomEffectModel(
            w_stack=rng.normal(size=(n_entities, d)) * 0.1,
            slot_of={i: i for i in range(n_entities)},
            random_effect_type="userId", feature_shard="all", task=task),
    })
    metrics = ServingMetrics()
    store = CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=device_capacity,
                           mesh_shards=mesh_shards,
                           load_aware_routing=load_aware_routing,
                           replicate_top_k=replicate_top_k),
        version="synthetic", metrics=metrics)
    engine = ScoringEngine(store, BucketedBatcher(max_batch),
                           metrics=metrics)
    return engine, metrics, names


def run_serving_bench(n_entities=20000, d=16, n_requests=2000, max_batch=64,
                      device_capacity=None, seed=0, out_path=None,
                      zipf=0.0, deadline_us=200.0, rebalance_every=500):
    """`bench.py --serving [--zipf A]`: online-scoring micro-bench.

    Self-contained: builds a synthetic 2-coordinate GLMix model IN MEMORY
    (no training, no disk) at the given entity count, stands up the
    AOT-warmed ScoringEngine, then measures
      - single-request latency (bucket 1): p50 / p99 / mean over a timed
        loop — the user-facing number for the online path;
      - async-batched throughput: requests submitted ONE AT A TIME to the
        deadline AsyncBatcher (the production arrival shape), so occupancy
        comes from coalescing, not caller-side batch formation; a trickle
        sub-phase (arrival gaps > deadline) exercises deadline flushes;
      - hot-set adaptation (``zipf`` > 0): entity ids drawn from a zipf
        rank distribution whose ranks are SHUFFLED across training slots
        (so the initial first-K-slots residency starts ~random), a
        frequency rebalance pass every ``rebalance_every`` requests during
        an adaptation epoch, then a measured epoch — recording
        ``entity_miss_rate``, hot-set hit rate, and the padding-waste
        ratio the ladder actually paid, diffed over the measured epoch
        only;
      - warm cost: executables compiled for the ladder (the number a hot
        swap must pre-pay off the request path), plus the zero-recompile
        check (``compiles`` must not grow after warm).
    In zipf mode an unset device_capacity defaults to n_entities/10 —
    all-hot residency would make the miss/hit numbers trivial.
    Emits one JSON dict (also written to BENCH_SERVING_<backend>.json);
    ``padding_waste_ratio``, ``entity_miss_rate``, and ``p99_s`` are
    top-level so trajectories stay comparable across PRs.
    """
    import jax

    from photon_ml_tpu.serving.batcher import Request

    if zipf and device_capacity is None:
        device_capacity = max(64, n_entities // 10)

    rng = np.random.default_rng(seed)
    engine, metrics, names = _synthetic_serving_engine(
        rng, n_entities, d, max_batch, device_capacity)
    store = engine.store

    t0 = time.perf_counter()
    n_compiled = engine.warm()
    warm_s = time.perf_counter() - t0

    # -- entity id streams: ~5% unknown either way; zipf ranks shuffled over
    # training slots so the initial first-K residency starts uncorrelated
    # with the traffic head (the adaptation the hot set must earn)
    slot_of_rank = rng.permutation(n_entities)

    def draw_users(n):
        unknown = rng.random(n) < 0.05
        if zipf:
            w = (np.arange(n_entities) + 1.0) ** -zipf
            ids = slot_of_rank[rng.choice(n_entities, size=n, p=w / w.sum())]
        else:
            ids = rng.integers(0, n_entities, size=n)
        return np.where(unknown, n_entities + rng.integers(0, n, size=n), ids)

    def mk_request(i, user):
        feats = [{"name": n, "term": "", "value": float(v)}
                 for n, v in zip(names, rng.normal(size=d))]
        return Request(uid=i, features=feats, ids={"userId": f"user{user}"})

    # single-request latency (bucket 1)
    single_users = draw_users(min(500, n_requests))
    single = [mk_request(i, u) for i, u in enumerate(single_users)]
    engine.score_requests(single[:1])  # touch every path once
    lat = []
    for r in single:
        t = time.perf_counter()
        engine.score_requests([r])
        lat.append(time.perf_counter() - t)
    lat = np.asarray(lat)

    # -- async stream: one submit per request, deadline batcher coalesces
    stream = [mk_request(i, u) for i, u in enumerate(draw_users(n_requests))]
    batcher = engine.async_batcher(deadline_s=deadline_us * 1e-6)
    try:
        # adaptation epoch: same trace shape feeds the EWMA counters, with a
        # rebalance pass on the configured cadence
        for start in range(0, n_requests, rebalance_every):
            chunk = stream[start:start + rebalance_every]
            for f in [batcher.submit(r) for r in chunk]:
                f.result(timeout=300)
            store.rebalance()

        # trickle sub-phase: arrival gaps > deadline force deadline flushes
        trickle = [mk_request(i, u) for i, u in enumerate(draw_users(16))]
        trickle_futs = []
        for r in trickle:
            trickle_futs.append(batcher.submit(r))
            time.sleep(2.0 * deadline_us * 1e-6)
        for f in trickle_futs:
            f.result(timeout=300)

        # measured epoch: a FRESH draw from the same arrival distribution
        # (not the adaptation trace — that would grade the hot set on its
        # own training data); counters diffed across it so adaptation and
        # trickle traffic don't blur the steady-state numbers
        measured = [mk_request(i, u)
                    for i, u in enumerate(draw_users(n_requests))]
        before = metrics.snapshot()
        t0 = time.perf_counter()
        futs = [batcher.submit(r) for r in measured]
        for f in futs:
            f.result(timeout=300)
        stream_s = time.perf_counter() - t0
    finally:
        batcher.shutdown(drain=True)
    snap = metrics.snapshot()

    def cdiff(name):
        return (snap["counters"].get(name, 0)
                - before["counters"].get(name, 0))

    padded = snap["padded_rows_launched"] - before["padded_rows_launched"]
    real = snap["real_rows_launched"] - before["real_rows_launched"]
    waste = 1.0 - real / padded if padded else 0.0
    miss_rate = cdiff("entity_misses") / n_requests
    lookups = sum(cdiff(k) for k in ("hot_hits", "lru_hits", "cold_fetches",
                                     "entity_misses"))
    hot_rate = cdiff("hot_hits") / lookups if lookups else 0.0

    out = {
        "metric": "serving_p99_latency", "unit": "s",
        "value": round(float(np.percentile(lat, 99)), 6),
        "backend": jax.default_backend(),
        "n_entities": n_entities, "d": d,
        "device_capacity": device_capacity,
        "zipf": zipf,
        "deadline_us": deadline_us,
        # the three cross-PR trajectory numbers (acceptance gate)
        "p99_s": round(float(np.percentile(lat, 99)), 6),
        "padding_waste_ratio": round(waste, 4),
        "entity_miss_rate": round(miss_rate, 4),
        "single_request": {
            "n": len(lat),
            "p50_s": round(float(np.percentile(lat, 50)), 6),
            "p99_s": round(float(np.percentile(lat, 99)), 6),
            "mean_s": round(float(lat.mean()), 6),
        },
        "stream": {
            "n_requests": n_requests,
            "n_batches": cdiff("batches"),
            "seconds": round(stream_s, 4),
            "qps": round(n_requests / stream_s, 1),
            "padding_waste_ratio": round(waste, 4),
            "entity_miss_rate": round(miss_rate, 4),
        },
        "hot_set": {
            "hit_rate": round(hot_rate, 4),
            "promotions": snap["counters"].get("hot_promotions", 0),
            "demotions": snap["counters"].get("hot_demotions", 0),
            "rebalances": snap["counters"].get("rebalances", 0),
        },
        "flushes": {
            "full": snap["counters"].get("flushes_full", 0),
            "deadline": snap["counters"].get("flushes_deadline", 0),
            "forced": snap["counters"].get("flushes_forced", 0),
        },
        "warm": {"executables": n_compiled, "seconds": round(warm_s, 4)},
        "compiles_after_warm": engine.compile_count - n_compiled,
        "counters": snap["counters"],
    }
    if out_path is None:
        out_path = os.path.join(
            _REPO, f"BENCH_SERVING_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_serving_mesh_bench(shard_counts=(1, 2, 4, 8), n_entities=20000,
                           d=16, n_requests=1000, max_batch=64,
                           per_shard_capacity=None, seed=0, zipf=1.1,
                           out_path=None):
    """`bench.py --serving --mesh`: pod-slice serving sweep ->
    BENCH_SERVING_MESH_<backend>.json.

    For each shard count N, builds the synthetic engine with the per-user
    table sharded over an N-device serving mesh at a FIXED per-shard
    hot-row budget (default n_entities/10 rows — the regime where the hot
    set matters), then measures against the unsharded baseline:
      - correctness: max |score diff| vs the unsharded engine on one fixed
        zipf request set (must sit at fp-reorder noise — the engine psums
        per-shard partial margins, it never all-gathers coefficient rows);
      - single-request p50/p99 latency (bucket 1) and closed-loop scoring
        throughput — on the CPU host mesh the psum is a memcpy-loop, so
        these show the ORCHESTRATION cost of sharding, not ICI reality;
      - aggregate hot capacity (rows resident across the mesh) and the
        hot-set hit rate it buys under the zipf trace — the capacity-
        scaling story: fixed per-chip HBM budget, aggregate grows with N;
      - zero-recompiles-after-warm, ASSERTED across traffic + a rebalance
        pass + a streaming delta (the invariant sharding must not break).
    Shard counts beyond the visible device count are dropped (with a
    note in the output) rather than failed — laptops and 1-chip hosts
    still produce a comparable file.
    """
    import jax

    from photon_ml_tpu.serving.batcher import Request

    if per_shard_capacity is None:
        per_shard_capacity = max(64, n_entities // 10)
    n_dev = len(jax.devices())
    usable = [n for n in shard_counts if n <= n_dev]
    dropped = [n for n in shard_counts if n > n_dev]

    def mk_requests(rng, names, k):
        w = (np.arange(n_entities) + 1.0) ** -zipf
        p = w / w.sum()
        ids = rng.choice(n_entities, size=k, p=p)
        unknown = rng.random(k) < 0.05
        reqs = []
        for i in range(k):
            u = n_entities + i if unknown[i] else int(ids[i])
            feats = [{"name": n, "term": "", "value": float(v)}
                     for n, v in zip(names, rng.normal(size=d))]
            reqs.append(Request(uid=i, features=feats,
                                ids={"userId": f"user{u}"}))
        return reqs

    # unsharded baseline at the SAME aggregate capacity as 1 shard, so the
    # 1-shard row is a pure sharding-overhead read
    rng = np.random.default_rng(seed)
    base_engine, base_metrics, names = _synthetic_serving_engine(
        rng, n_entities, d, max_batch, device_capacity=per_shard_capacity)
    base_engine.warm()
    parity_reqs = mk_requests(np.random.default_rng(seed + 1), names, 256)
    base_scores = base_engine.score_requests(parity_reqs)

    results = {}
    for n_shards in usable:
        rng = np.random.default_rng(seed)  # identical model every round
        engine, metrics, _ = _synthetic_serving_engine(
            rng, n_entities, d, max_batch,
            device_capacity=per_shard_capacity, mesh_shards=n_shards)
        store = engine.store
        coord = store.coordinates["per_user"]
        t0 = time.perf_counter()
        n_compiled = engine.warm()
        warm_s = time.perf_counter() - t0

        scores = engine.score_requests(parity_reqs)
        max_diff = float(np.abs(scores - base_scores).max())

        req_rng = np.random.default_rng(seed + 2)
        # single-request latency (bucket 1)
        single = mk_requests(req_rng, names, 200)
        engine.score_requests(single[:1])
        lat = []
        for r in single:
            t = time.perf_counter()
            engine.score_requests([r])
            lat.append(time.perf_counter() - t)
        lat = np.asarray(lat)

        # closed-loop throughput with a mid-stream rebalance + delta — the
        # mutations the zero-recompile assert must survive
        stream = mk_requests(req_rng, names, n_requests)
        before_hot = metrics.counter("hot_hits")
        t0 = time.perf_counter()
        half = n_requests // 2
        for start in range(0, half, max_batch):
            engine.score_requests(stream[start:start + max_batch])
        store.rebalance()
        store.apply_delta("per_user", "user0",
                          req_rng.normal(size=d) * 0.1)
        for start in range(half, n_requests, max_batch):
            engine.score_requests(stream[start:start + max_batch])
        stream_s = time.perf_counter() - t0
        hot_hits = metrics.counter("hot_hits") - before_hot

        compiles_after_warm = engine.compile_count - n_compiled
        assert compiles_after_warm == 0, (
            f"{n_shards}-shard serving recompiled {compiles_after_warm} "
            "executable(s) after warm — the zero-recompile invariant broke")

        results[str(n_shards)] = {
            "aggregate_hot_rows": coord.hot_capacity,
            "per_shard_rows": (coord.shard_spec.cap
                               if coord.shard_spec else coord.hot_capacity),
            "max_abs_diff_vs_unsharded": max_diff,
            "p50_s": round(float(np.percentile(lat, 50)), 6),
            "p99_s": round(float(np.percentile(lat, 99)), 6),
            "qps": round(n_requests / stream_s, 1),
            "hot_hit_rate": round(hot_hits / max(n_requests, 1), 4),
            "warm_s": round(warm_s, 4),
            "executables": n_compiled,
            "compiles_after_warm": compiles_after_warm,
        }

    out = {
        "metric": "serving_mesh_scaling",
        "backend": jax.default_backend(),
        "n_devices": n_dev,
        "n_entities": n_entities, "d": d,
        "zipf": zipf,
        "per_shard_capacity": per_shard_capacity,
        "n_requests": n_requests,
        "baseline_unsharded": {
            "device_capacity": per_shard_capacity,
        },
        "shards": results,
        "dropped_shard_counts": dropped,
    }
    if out_path is None:
        out_path = os.path.join(
            _REPO, f"BENCH_SERVING_MESH_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_skew_sweep_bench(skews=(0.8, 1.0, 1.2, 1.5), n_shards=4,
                         n_entities=8000, d=16, n_requests=800,
                         max_batch=64, per_shard_capacity=None,
                         replicate_top_k=None, seed=0, out_path=None):
    """`bench.py --serving --skew-sweep`: traffic-skew robustness ->
    BENCH_SKEW_<backend>.json.

    The headline proof for traffic-aware placement: sweep the zipf
    exponent from mild (0.8) to brutal (1.5) skew over a sharded store
    and record, for BOTH routers —
      - the traffic-aware router (load-aware greedy bin-pack over EWMA
        hit counters + hot-row replication; the default), and
      - the pre-placement router (``load_aware_routing=False``:
        ``slot % N`` homes, no replicas) as the comparison curve, not
        asserted —
    the measured-epoch hot-set hit rate and single-request p99 after an
    adaptation epoch with periodic rebalances.  ASSERTS, on the new
    router only:
      - hit rate and p99 at the harshest skew degrade at most 10% from
        the mildest (PHOTON_BENCH_SKEW_TOL / PHOTON_BENCH_SKEW_P99_TOL
        override) — skew concentrates load, it must not crater service;
      - zero recompiles after warm at every point — placement moves
        rows, never shapes;
      - the two bitwise anchors: at 1 shard and under uniform traffic
        the new router's scores equal the old router's EXACTLY
        (max |diff| == 0.0).  Resolution hands the kernels GLOBAL rows
        and the mesh kernels' _localize is placement-agnostic, so
        routing policy can never touch a score.
    """
    import jax

    from photon_ml_tpu.serving.batcher import Request

    if per_shard_capacity is None:
        per_shard_capacity = max(64, n_entities // 10)
    if replicate_top_k is None:
        replicate_top_k = max(8, 4 * n_shards)
    n_dev = len(jax.devices())
    shards = min(n_shards, n_dev)

    # zipf ranks shuffled over training slots (same convention as the
    # serving bench): the initial residency starts uncorrelated with the
    # traffic head, so routing has to EARN its curve
    slot_of_rank = np.random.default_rng(seed + 17).permutation(n_entities)

    def mk_requests(rng, names, k, zipf):
        if zipf > 0.0:
            w = (np.arange(n_entities) + 1.0) ** -zipf
            ids = slot_of_rank[rng.choice(n_entities, size=k, p=w / w.sum())]
        else:
            ids = rng.integers(0, n_entities, size=k)
        unknown = rng.random(k) < 0.05
        reqs = []
        for i in range(k):
            u = n_entities + i if unknown[i] else int(ids[i])
            feats = [{"name": n, "term": "", "value": float(v)}
                     for n, v in zip(names, rng.normal(size=d))]
            reqs.append(Request(uid=i, features=feats,
                                ids={"userId": f"user{u}"}))
        return reqs

    def build_point(zipf, load_aware, top_k):
        rng = np.random.default_rng(seed)  # identical model every point
        engine, metrics, names = _synthetic_serving_engine(
            rng, n_entities, d, max_batch,
            device_capacity=per_shard_capacity, mesh_shards=shards,
            load_aware_routing=load_aware, replicate_top_k=top_k)
        store = engine.store
        n_compiled = engine.warm()
        req_rng = np.random.default_rng(seed + int(zipf * 1000) + 3)
        # adaptation epoch: periodic rebalances chase the observed head
        adapt = mk_requests(req_rng, names, n_requests, zipf)
        for start in range(0, n_requests, max_batch):
            engine.score_requests(adapt[start:start + max_batch])
            if (start // max_batch) % 3 == 2:
                store.rebalance()
        store.rebalance()
        # measured epoch: hit rate over a fresh stream at the same skew
        measured = mk_requests(req_rng, names, n_requests, zipf)
        before_hot = metrics.counter("hot_hits")
        for start in range(0, n_requests, max_batch):
            engine.score_requests(measured[start:start + max_batch])
        hot_hits = metrics.counter("hot_hits") - before_hot
        rec = {"hot_hit_rate": round(hot_hits / max(n_requests, 1), 4)}
        return engine, rec, n_compiled, names, req_rng

    curves = {"traffic_aware": {}, "pre_placement_router": {}}
    points = []
    for router, la, tk in (("traffic_aware", True, replicate_top_k),
                           ("pre_placement_router", False, 0)):
        for s in skews:
            engine, rec, n_compiled, names, req_rng = build_point(s, la, tk)
            curves[router][str(s)] = rec
            points.append((router, s, engine, rec, n_compiled, names,
                           req_rng, []))

    # latency sampling is INTERLEAVED across every point, with the order
    # ROTATED each rep so periodic host stalls (scheduler ticks, flusher
    # threads) cannot phase-align with any one point, and the asserted
    # p99 is the MIN over per-rep p99s — the noise-floor tail, same idea
    # as run_lint_bench's min(times): a rep that dodged the stalls shows
    # what the path actually costs
    n_reps = 5
    for _rep in range(n_reps):
        shift = _rep % len(points)
        for router, zipf, engine, rec, _, names, req_rng, lat in (
                points[shift:] + points[:shift]):
            rep_lat = []
            for r in mk_requests(req_rng, names, 120, zipf):
                t = time.perf_counter()
                engine.score_requests([r])
                rep_lat.append(time.perf_counter() - t)
            lat.append(rep_lat)
    for router, zipf, engine, rec, n_compiled, _, _, lat in points:
        pooled = np.asarray([x for rep in lat for x in rep])
        rec["p50_s"] = round(float(np.percentile(pooled, 50)), 6)
        rec["p99_s"] = round(min(float(np.percentile(np.asarray(rep), 99))
                                 for rep in lat), 6)
        compiles_after_warm = engine.compile_count - n_compiled
        assert compiles_after_warm == 0, (
            f"skew {zipf} ({router}) recompiled {compiles_after_warm} "
            "executable(s) after warm — the zero-recompile invariant "
            "broke")
        rec["compiles_after_warm"] = compiles_after_warm

    new_curve = curves["traffic_aware"]
    old_curve = curves["pre_placement_router"]

    tol = float(os.environ.get("PHOTON_BENCH_SKEW_TOL", "0.10"))
    p99_tol = float(os.environ.get("PHOTON_BENCH_SKEW_P99_TOL", "0.10"))
    lo, hi = str(min(skews)), str(max(skews))
    hit_lo = new_curve[lo]["hot_hit_rate"]
    hit_hi = new_curve[hi]["hot_hit_rate"]
    assert hit_hi >= hit_lo * (1.0 - tol), (
        f"hot-set hit rate degraded past {tol:.0%} under skew: "
        f"s={lo} -> {hit_lo:.4f}, s={hi} -> {hit_hi:.4f}")
    p99_lo = new_curve[lo]["p99_s"]
    p99_hi = new_curve[hi]["p99_s"]
    assert p99_hi <= p99_lo * (1.0 + p99_tol), (
        f"single-request p99 degraded past {p99_tol:.0%} under skew: "
        f"s={lo} -> {p99_lo * 1e6:.0f}us, s={hi} -> {p99_hi * 1e6:.0f}us")

    def parity_diff(mesh_shards, zipf):
        # score the SAME probe stream through both routers after each has
        # observed identical traffic and rebalanced; placement differs,
        # scores must not
        scores = {}
        for la, tk in ((False, 0), (True, replicate_top_k)):
            rng = np.random.default_rng(seed)
            engine, metrics, names = _synthetic_serving_engine(
                rng, n_entities, d, max_batch,
                device_capacity=per_shard_capacity,
                mesh_shards=mesh_shards, load_aware_routing=la,
                replicate_top_k=tk)
            engine.warm()
            req_rng = np.random.default_rng(seed + 99)
            warmup = mk_requests(req_rng, names, 256, zipf)
            for start in range(0, 256, max_batch):
                engine.score_requests(warmup[start:start + max_batch])
            engine.store.rebalance()
            probe = mk_requests(req_rng, names, 256, zipf)
            scores[la] = engine.score_requests(probe)
        return float(np.abs(scores[True] - scores[False]).max())

    one_shard_diff = parity_diff(1, 1.1)
    assert one_shard_diff == 0.0, (
        f"1-shard scores drifted {one_shard_diff} from the old router — "
        "routing policy leaked into scoring")
    uniform_diff = parity_diff(shards, 0.0)
    assert uniform_diff == 0.0, (
        f"uniform-traffic scores drifted {uniform_diff} from the old "
        "router — routing policy leaked into scoring")

    out = {
        "metric": "serving_skew_robustness",
        "backend": jax.default_backend(),
        "n_devices": n_dev,
        "n_shards": shards,
        "n_entities": n_entities, "d": d,
        "n_requests": n_requests,
        "per_shard_capacity": per_shard_capacity,
        "replicate_top_k": replicate_top_k,
        "skews": list(skews),
        "traffic_aware": new_curve,
        "pre_placement_router": old_curve,
        "hit_rate_tolerance": tol,
        "p99_tolerance": p99_tol,
        "one_shard_max_abs_diff_vs_old_router": one_shard_diff,
        "uniform_max_abs_diff_vs_old_router": uniform_diff,
    }
    if out_path is None:
        out_path = os.path.join(
            _REPO, f"BENCH_SKEW_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_open_loop_bench(n_entities=5000, d=16, max_batch=64, seed=0,
                        duration_s=2.5, rates=None, rate_multipliers=None,
                        n_connections=4, budget_ms=25.0, deadline_us=200.0,
                        max_requests_per_rate=20000, out_path=None):
    """`bench.py --serving --open-loop`: latency-under-overload ->
    BENCH_NET_<backend>.json.

    The closed-loop serving bench (``run_serving_bench``) self-throttles:
    when the engine slows down, the submit loop slows with it, so queueing
    never builds and p99 looks flat through saturation (the Spark-perf
    study's critique in PAPERS.md).  This bench drives the full network
    edge — ``serving.frontend.FrontendServer`` on a localhost socket —
    with a POISSON ARRIVAL PROCESS whose rate is fixed in advance
    (``serving.frontend.loadgen``), sweeping rates below, near, and past
    the engine's calibrated saturation point.  Per rate it records client-
    observed p50/p99/p999 and the shed rate.  The acceptance shape: shed
    ≈ 0 below saturation; past saturation the admission controller sheds
    the excess and p99 stays bounded near the deadline budget instead of
    growing with the (unbounded) backlog an open loop would otherwise
    build.

    ``rates``: explicit arrival rates in qps, or ``rate_multipliers``
    (default 0.25/0.7/1.5) times the calibrated capacity.  Calibration:
    the median wall time of a full top-bucket ``score_requests`` launch
    gives the engine's peak qps; the edge saturates below that (wire +
    JSON + event-loop overhead), which is why "near" sits at 0.7.
    """
    import asyncio

    import jax

    from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                                FrontendConfig,
                                                ThreadedFrontend,
                                                run_open_loop)
    from photon_ml_tpu.serving.frontend.loadgen import \
        measure_closed_loop_capacity

    rng = np.random.default_rng(seed)
    engine, metrics, names = _synthetic_serving_engine(
        rng, n_entities, d, max_batch, device_capacity=None)
    t0 = time.perf_counter()
    n_compiled = engine.warm()
    warm_s = time.perf_counter() - t0

    # request pool: assembled up front so the send path (which must hit
    # the Poisson schedule) does no rng work per arrival
    pool = [{"features": [[n, float(v)] for n, v in
             zip(names, rng.normal(size=d))],
             "ids": {"userId": f"user{rng.integers(n_entities)}"}}
            for _ in range(256)]

    def make_request(uid):
        req = dict(pool[uid % len(pool)])
        req["uid"] = uid
        return req

    front = ThreadedFrontend(engine, config=FrontendConfig(
        admission=AdmissionConfig(budget_s=budget_ms * 1e-3),
        batcher_deadline_s=deadline_us * 1e-6,
        flush_threshold=max_batch)).start()
    sweep = []
    try:
        # -- calibrate against the EDGE, closed-loop through the socket
        # (json + wire + loop + batcher + engine); also warms the flush-
        # cost EWMA so admission enters the sweep with real observations
        capacity_qps = asyncio.run(measure_closed_loop_capacity(
            "127.0.0.1", front.port, make_request, window=2 * max_batch))
        print(json.dumps({"capacity_qps": round(capacity_qps, 1)}),
              file=sys.stderr)

        if rates is None:
            mults = rate_multipliers or (0.25, 0.7, 1.5)
            labels = {0: "below", 1: "near", 2: "past"}
            rates = [(labels.get(i, f"x{m}"), m * capacity_qps)
                     for i, m in enumerate(sorted(mults))]
        else:
            rates = [(f"r{int(r)}", float(r)) for r in rates]

        for i, (label, rate) in enumerate(rates):
            dur = min(duration_s, max_requests_per_rate / rate)
            res = asyncio.run(run_open_loop(
                "127.0.0.1", front.port, rate, dur, make_request,
                n_connections=n_connections,
                rng=np.random.default_rng(seed + 1000 + i)))
            point = {"label": label, **res.to_json()}
            sweep.append(point)
            print(json.dumps(point), file=sys.stderr)
    finally:
        front.stop()

    shed_series = metrics.registry.counter_series("requests_shed_total")
    out = {
        "metric": "open_loop_p99_past_saturation", "unit": "ms",
        "value": sweep[-1]["latency_ms"]["p99"] if sweep else 0.0,
        "backend": jax.default_backend(),
        "n_entities": n_entities, "d": d, "max_batch": max_batch,
        "budget_ms": budget_ms, "deadline_us": deadline_us,
        "n_connections": n_connections,
        "capacity_qps": round(capacity_qps, 1),
        "warm": {"executables": n_compiled, "seconds": round(warm_s, 4)},
        "sweep": sweep,
        "shed_counters": {
            ",".join(f"{k}={v}" for k, v in lk) or "total": n
            for lk, n in shed_series.items()},
    }
    if out_path is None:
        out_path = os.path.join(_REPO,
                                f"BENCH_NET_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_online_bench(n_entities=2000, d=8, max_batch=64, seed=0,
                     batches=8, batch_size=64, out_path=None):
    """`bench.py --online`: the photonlearn loop end to end ->
    BENCH_ONLINE_<backend>.json.

    Builds the synthetic serving engine (d=8 keeps the per-user refits
    inside the batched SoA solver's gate), attaches a durable delta log
    (``online.DeltaLog``) and an ``online.IncrementalTrainer``, then:

      - **refit throughput**: streams ``batches`` labeled mini-batches
        through ``consume`` WHILE a serving thread keeps scoring through
        the same engine — entities/sec and rows/sec over the solve+publish
        wall, plus the serving qps sustained during the refits and the
        zero-recompile check (publishes are same-shape scatters);
      - **publish -> visible freshness**: after each batch, a just-refit
        entity is scored through the live engine; freshness is first
        publish -> that score's completion (the end-to-end online-learning
        latency a caller observes), reported p50/p99/max over batches;
      - **catch-up replay**: a fresh replica store (same seed => identical
        pre-refit model) replays the full log — rows/sec, and the replica
        must then serve BITWISE the live engine's score for the probe
        (the replicated-convergence acceptance check).
    """
    import tempfile
    import threading

    import jax

    from photon_ml_tpu.online.catchup import replay_into_store
    from photon_ml_tpu.online.delta_log import DeltaLog
    from photon_ml_tpu.online.trainer import IncrementalTrainer, TrainerConfig
    from photon_ml_tpu.serving.batcher import Request
    from photon_ml_tpu.serving.swap import HotSwapper

    rng = np.random.default_rng(seed)
    engine, metrics, names = _synthetic_serving_engine(
        rng, n_entities, d, max_batch, device_capacity=None)
    t0 = time.perf_counter()
    n_compiled = engine.warm()
    warm_s = time.perf_counter() - t0

    def mk_request(uid, user):
        feats = [{"name": n, "term": "", "value": float(v)}
                 for n, v in zip(names, rng.normal(size=d))]
        return Request(uid=uid, features=feats,
                       ids={"userId": f"user{user}"})

    # labeled mini-batches, assembled up front so the timed loop is pure
    # consume(): hot entities drawn from a small head so every batch
    # actually refits (and re-refits) real entities
    hot = rng.integers(0, min(256, n_entities), size=batches * batch_size)
    feed = []
    for b in range(batches):
        batch = []
        for i in range(batch_size):
            u = int(hot[b * batch_size + i])
            req = mk_request(None, u)
            batch.append({"uid": None, "features": req.features,
                          "ids": req.ids,
                          "label": float(rng.integers(0, 2))})
        feed.append((batch, int(hot[(b + 1) * batch_size - 1])))

    with tempfile.TemporaryDirectory(prefix="photon_online_bench_") as tmp:
        log = DeltaLog(tmp, fsync="rotate",
                       registry=metrics.registry)
        swapper = HotSwapper(engine, delta_log=log)
        trainer = IncrementalTrainer(
            swapper, TrainerConfig(coordinates=("per_user",)))

        # concurrent serving load: single-request scores through the SAME
        # engine for the whole refit phase — publishes must not stall it
        stop = threading.Event()
        served = [0]

        def serve_loop():
            r = np.random.default_rng(seed + 1)
            while not stop.is_set():
                u = int(r.integers(0, n_entities))
                engine.score_requests([mk_request(served[0], u)])
                served[0] += 1

        compile_before = engine.compile_count
        reports, fresh_s = [], []
        t_serve = time.perf_counter()
        loader = threading.Thread(target=serve_loop, daemon=True)
        loader.start()
        try:
            for batch, probe_user in feed:
                rep = trainer.consume(batch)
                # freshness: first publish of this batch -> a live score
                # of a just-refit entity completing
                probe = mk_request("probe", probe_user)
                engine.score_requests([probe])
                if rep.publish_started:
                    fresh_s.append(time.perf_counter() - rep.publish_started)
                reports.append(rep)
        finally:
            stop.set()
            loader.join(timeout=10.0)
        serve_wall = time.perf_counter() - t_serve
        recompiles = engine.compile_count - compile_before

        entities = sum(r.entities for r in reports)
        rows = sum(r.rows for r in reports)
        published = sum(r.published for r in reports)
        refit_wall = sum(r.wall_s for r in reports)

        probe_user = feed[-1][1]
        probe = mk_request("parity", probe_user)
        live_score = float(engine.score_requests([probe])[0])

        # catch-up: identical pre-refit model (same seed, same rng draw
        # order), then the full log replayed into it
        rng2 = np.random.default_rng(seed)
        engine2, _, _ = _synthetic_serving_engine(
            rng2, n_entities, d, max_batch, device_capacity=None)
        t0 = time.perf_counter()
        records = list(log.replay())
        stats = replay_into_store(engine2.store, records)
        catchup_s = time.perf_counter() - t0
        replica_score = float(engine2.score_requests([probe])[0])

        fr = np.asarray(fresh_s) * 1e3 if fresh_s else np.zeros(1)
        out = {
            "metric": "online_refit_entities_per_s", "unit": "entities/s",
            "value": round(entities / refit_wall, 1) if refit_wall else 0.0,
            "backend": jax.default_backend(),
            "n_entities": n_entities, "d": d, "batches": batches,
            "batch_size": batch_size,
            "warm": {"executables": n_compiled, "seconds": round(warm_s, 4)},
            "refit": {
                "entities": entities, "rows": rows, "published": published,
                "rejected": sum(r.rejected for r in reports),
                "wall_s": round(refit_wall, 4),
                "solve_s": round(sum(r.solve_s for r in reports), 4),
                "publish_s": round(sum(r.publish_s for r in reports), 4),
                "rows_per_s": round(rows / refit_wall, 1)
                              if refit_wall else 0.0},
            "freshness_ms": {
                "p50": round(float(np.percentile(fr, 50)), 3),
                "p99": round(float(np.percentile(fr, 99)), 3),
                "max": round(float(fr.max()), 3)},
            "serving_during_refit": {
                "scores": served[0],
                "qps": round(served[0] / serve_wall, 1)},
            "recompiles_during_refit": int(recompiles),
            "catchup": {
                "records": len(records), "applied": stats.applied,
                "rejected": stats.rejected,
                "seconds": round(catchup_s, 4),
                "rows_per_s": round(stats.applied / catchup_s, 1)
                              if catchup_s else 0.0,
                "replica_score_parity": replica_score == live_score},
            "delta_log": {"bytes": log.bytes_written,
                          "records": log.records_written,
                          "segments": len(log.segments())},
        }
        log.close()
    if out_path is None:
        out_path = os.path.join(_REPO,
                                f"BENCH_ONLINE_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_repl_bench(n_entities=256, d=8, max_batch=32, n_replicas=2,
                   batches=8, batch_size=32, seed=0, out_path=None) -> dict:
    """`bench.py --repl`: the photonrepl network replication plane end to
    end -> BENCH_REPL_<backend>.json.

    Saves a real model directory (snapshots pack a dir, so the in-memory
    synthetic engine is not enough), attaches an owning delta log + the
    photonrepl log server, then boots ``n_replicas`` socket subscribers —
    each one the full ``serve.py --subscribe`` wiring: snapshot bootstrap
    over the socket, local mirror log, warmed serving engine, live
    ``LogFollower`` tail.  Measured phases:

      - **bootstrap**: wall time for all replicas to snapshot + warm;
      - **live tail under refit load**: labeled mini-batches stream
        through ``IncrementalTrainer.consume`` on the owner WHILE one
        replica keeps serving scores; after each batch the publish-tail
        identity's propagation to every replica's SERVING STORE is timed
        (publish -> store-visible freshness, p50/p99/max over
        batch x replica samples);
      - **mid-stream reconnect**: one replica is torn down, more refits
        land, and a fresh subscriber on the same warm spool must resume
        via LOG REPLAY (``repl_resume_total{mode="log"}``) — asserted, a
        snapshot fallback here would mean retention broke;
      - **acceptance**: every replica converges BITWISE to the owner's
        probe scores with ZERO engine recompiles after warm — both
        asserted, not just reported.
    """
    import tempfile
    import threading

    import jax

    from photon_ml_tpu.cli.serve import build_server
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.online.catchup import LogFollower
    from photon_ml_tpu.online.delta_log import DeltaLog
    from photon_ml_tpu.online.replication import (ReplicationClient,
                                                  ReplicationClientConfig,
                                                  ReplicationConfig,
                                                  attach_replication)
    from photon_ml_tpu.online.trainer import IncrementalTrainer, TrainerConfig
    from photon_ml_tpu.serving.batcher import Request
    from photon_ml_tpu.serving.metrics import ServingMetrics
    from photon_ml_tpu.storage.model_io import save_game_model
    from photon_ml_tpu.types import TaskType

    assert n_replicas >= 1
    rng = np.random.default_rng(seed)
    names = [f"f{j}" for j in range(d)]
    task = TaskType.LOGISTIC_REGRESSION

    def save_model(path):
        model = GameModel(models={
            "fixed": FixedEffectModel(
                coefficients=Coefficients(means=rng.normal(size=d)),
                feature_shard="all", task=task),
            "user": RandomEffectModel(
                w_stack=rng.normal(size=(n_entities, d)) * 0.1,
                slot_of={i: i for i in range(n_entities)},
                random_effect_type="userId", feature_shard="all",
                task=task),
        })
        imap = IndexMap({feature_key(n): j for j, n in enumerate(names)})
        eidx = EntityIndex()
        for i in range(n_entities):
            eidx.get_or_add(f"user{i}")
        save_game_model(model, path, {"all": imap}, {"userId": eidx},
                        task=task)
        imap.save(os.path.join(path, "all.idx"))
        eidx.save(os.path.join(path, "userId.entities.json"))
        return path

    def mk_request(uid, user, r=None):
        r = r if r is not None else rng
        feats = [{"name": n, "term": "", "value": float(v)}
                 for n, v in zip(names, r.normal(size=d))]
        return Request(uid=uid, features=feats,
                       ids={"userId": f"user{user}"})

    # fixed probe set: owner and every replica score the SAME requests, so
    # parity is a bitwise comparison of floats
    probe_rng = np.random.default_rng(seed + 7)
    probes = [mk_request(i, i % n_entities, probe_rng)
              for i in range(min(max_batch, n_entities))]

    def scores(engine):
        return [float(s) for s in engine.score_requests(probes)]

    def wait_for(pred, timeout=60.0, what="condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.002)
        raise AssertionError(f"repl bench timed out waiting for {what}")

    class Replica:
        """serve.py --subscribe wiring, in-process."""

        def __init__(self, port, spool):
            self.metrics = ServingMetrics()
            self.client = ReplicationClient(
                ReplicationClientConfig(host="127.0.0.1", port=port,
                                        spool_dir=spool, ack_every=8,
                                        ack_interval_s=0.05,
                                        backoff_initial_s=0.05),
                registry=self.metrics.registry).start()
            model_dir = self.client.bootstrap(timeout=60.0)
            self.mirror = DeltaLog(self.client.mirror_path, fsync="never")
            self.engine, self.swapper = build_server(
                model_dir, max_batch=max_batch, warm=True,
                metrics=self.metrics, delta_log=self.mirror,
                log_owner=False)
            self.swapper.set_base(model_dir, self.client.floor or 0)
            self.client.on_snapshot = \
                lambda d, g: self.swapper.swap(d, replay_floor=g)
            if self.client.model_dir != model_dir:
                self.swapper.swap(self.client.model_dir,
                                  replay_floor=self.client.floor)
            self.follower = LogFollower(self.mirror,
                                        lambda: self.engine.store,
                                        poll_interval_s=0.005,
                                        registry=self.metrics.registry)
            self.follower.run_once()
            self.follower.start()

        def at_or_past(self, identity):
            p = self.follower.position
            return p is not None and p >= identity

        def close(self):
            self.follower.stop()
            self.client.stop()
            self.mirror.close()

    with tempfile.TemporaryDirectory(prefix="photon_repl_bench_") as tmp:
        base_dir = save_model(os.path.join(tmp, "base"))
        log = DeltaLog(os.path.join(tmp, "owner-log"), fsync="rotate")
        engine, swapper = build_server(base_dir, max_batch=max_batch,
                                       warm=True, delta_log=log,
                                       log_owner=True)
        registry = engine.metrics.registry
        repl = attach_replication(swapper, ReplicationConfig(),
                                  registry=registry)
        trainer = IncrementalTrainer(
            swapper, TrainerConfig(coordinates=("user",), max_iters=5))

        replicas = []
        try:
            t0 = time.perf_counter()
            replicas = [Replica(repl.port, os.path.join(tmp, f"spool{i}"))
                        for i in range(n_replicas)]
            bootstrap_s = time.perf_counter() - t0

            # settle the compile baselines: one scoring pass each, then
            # every later score must reuse the warmed executables
            scores(engine)
            for r in replicas:
                scores(r.engine)
            compile_base = [r.engine.compile_count for r in replicas]

            # labeled feed assembled up front so the timed loop is pure
            # consume(); +1 batch is published during the reconnect window
            feed = []
            for _ in range(batches + 1):
                fb = []
                for _ in range(batch_size):
                    u = int(rng.integers(0, n_entities))
                    req = mk_request(None, u)
                    fb.append({"uid": None, "features": req.features,
                               "ids": req.ids,
                               "label": float(rng.integers(0, 2))})
                feed.append(fb)

            # concurrent serving load on the LAST replica for the whole
            # refit phase — live tailing must not stall or recompile it
            stop = threading.Event()
            served = [0]

            def serve_loop():
                r = np.random.default_rng(seed + 1)
                while not stop.is_set():
                    u = int(r.integers(0, n_entities))
                    replicas[-1].engine.score_requests(
                        [mk_request(served[0], u, r)])
                    served[0] += 1

            loader = threading.Thread(target=serve_loop, daemon=True)
            loader.start()
            reports, fresh_ms = [], []
            t_load = time.perf_counter()
            try:
                for fb in feed[:batches]:
                    rep = trainer.consume(fb)
                    reports.append(rep)
                    if not rep.published:
                        continue
                    tail = swapper.identity
                    t_pub = time.perf_counter()
                    pending = set(range(n_replicas))
                    while pending:
                        for i in list(pending):
                            if replicas[i].at_or_past(tail):
                                fresh_ms.append(
                                    (time.perf_counter() - t_pub) * 1e3)
                                pending.discard(i)
                        if pending:
                            if time.perf_counter() - t_pub > 60.0:
                                raise AssertionError(
                                    f"replicas {sorted(pending)} never "
                                    f"reached {tail}")
                            time.sleep(0.001)
            finally:
                stop.set()
                loader.join(timeout=10.0)
            load_wall = time.perf_counter() - t_load

            # mid-stream reconnect: tear replica 0 down, land one more
            # refit batch while it is away, then resubscribe on the SAME
            # warm spool — no swap ran, so the log is fully retained and
            # the resume MUST ride log replay, not a snapshot
            spool0 = os.path.join(tmp, "spool0")
            replicas[0].close()
            reports.append(trainer.consume(feed[batches]))
            replicas[0] = Replica(repl.port, spool0)
            r0 = replicas[0]
            wait_for(lambda: r0.client.last_resume_mode is not None,
                     what="reconnect subscribe ack")
            resume_mode = r0.client.last_resume_mode
            assert resume_mode == "log", \
                f"warm-spool reconnect resumed via {resume_mode!r}"
            scores(r0.engine)  # settle the rebuilt engine's baseline
            compile_base[0] = r0.engine.compile_count

            # final convergence + the acceptance checks
            tail = swapper.identity
            for i, r in enumerate(replicas):
                wait_for(lambda r=r: r.at_or_past(tail),
                         what=f"replica {i} store at {tail}")
            owner_scores = scores(engine)
            parity = [scores(r.engine) == owner_scores for r in replicas]
            recompiles = [r.engine.compile_count - compile_base[i]
                          for i, r in enumerate(replicas)]
            assert all(parity), f"owner/replica score divergence: {parity}"
            assert all(c == 0 for c in recompiles), \
                f"replica recompiles after warm: {recompiles}"

            entities = sum(r.entities for r in reports)
            rows = sum(r.rows for r in reports)
            published = sum(r.published for r in reports)
            refit_wall = sum(r.wall_s for r in reports)
            fr = np.asarray(fresh_ms) if fresh_ms else np.zeros(1)
            out = {
                "metric": "repl_store_visible_freshness_ms_p99",
                "unit": "ms",
                "value": round(float(np.percentile(fr, 99)), 3),
                "backend": jax.default_backend(),
                "n_entities": n_entities, "d": d,
                "n_replicas": n_replicas, "batches": batches,
                "batch_size": batch_size,
                "bootstrap": {
                    "seconds": round(bootstrap_s, 4),
                    "snapshots_total":
                        int(registry.counter("repl_snapshots_total"))},
                "refit": {
                    "entities": entities, "rows": rows,
                    "published": published,
                    "wall_s": round(refit_wall, 4),
                    "load_wall_s": round(load_wall, 4),
                    "rows_per_s": round(rows / refit_wall, 1)
                                  if refit_wall else 0.0},
                "freshness_ms": {
                    "samples": len(fresh_ms),
                    "p50": round(float(np.percentile(fr, 50)), 3),
                    "p99": round(float(np.percentile(fr, 99)), 3),
                    "max": round(float(fr.max()), 3)},
                "serving_during_refit": {
                    "scores": served[0],
                    "qps": round(served[0] / load_wall, 1)
                           if load_wall else 0.0},
                "reconnect": {
                    "resume_mode": resume_mode,
                    "resume_log_total": int(registry.counter(
                        "repl_resume_total", mode="log")),
                    "records_replayed": r0.client.records_applied},
                "parity": {"bitwise_equal": parity},
                "replica_recompiles_after_warm": recompiles,
                "delta_log": {"bytes": log.bytes_written,
                              "records": log.records_written,
                              "segments": len(log.segments())},
            }
        finally:
            for r in replicas:
                try:
                    r.close()
                except Exception:
                    pass
            repl.stop()
            log.close()
    if out_path is None:
        out_path = os.path.join(_REPO,
                                f"BENCH_REPL_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_chaos_bench(n_entities=128, d=8, max_batch=16, rounds=9, seed=0,
                    out_path=None) -> dict:
    """`bench.py --chaos`: owner + replica + frontend under a seeded fault
    schedule -> BENCH_CHAOS_<backend>.json.

    The whole fault sequence derives from ``--chaos-seed`` via
    ``chaos.build_schedule`` (same seed -> same schedule, asserted).  Per
    round the schedule's fault point is armed fire-on-next-hit, traffic is
    driven through the seam (trainer publishes, frontend requests, or an
    owner hot swap for the snapshot/activate classes), the fault is
    asserted to have FIRED, then the injector is disarmed and the
    time-to-ready clock runs until the owner's /readyz is green again and
    the replica's serving store reaches the owner's publish tail.

    Asserted, not just reported:
      - identity chain strictly monotone across every fault (log listener
        over the full run);
      - owner/replica probe scores BITWISE equal after the final heal;
      - zero admitted frontend requests lost (every request on a surviving
        connection gets a reply; dropped-before-admission retries are
        counted, never lost);
      - zero engine recompiles after warm, owner and replica;
      - time-to-ready bounded (<30 s) for every fault class;
      - ``GET /readyz`` over real HTTP: 503 while the delta log is
        degraded, 200 after the heal publish.
    """
    import socket as socketlib
    import tempfile

    import jax

    from photon_ml_tpu.chaos import (HealthState, InjectedCrash, Watchdog,
                                     build_schedule, delta_log_check,
                                     follower_staleness_check, get_injector)
    from photon_ml_tpu.cli.serve import build_server
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.online.catchup import LogFollower
    from photon_ml_tpu.online.delta_log import DeltaLog
    from photon_ml_tpu.online.replication import (ReplicationClient,
                                                  ReplicationClientConfig,
                                                  ReplicationConfig,
                                                  attach_replication)
    from photon_ml_tpu.online.trainer import IncrementalTrainer, TrainerConfig
    from photon_ml_tpu.serving.batcher import Request
    from photon_ml_tpu.serving.frontend import (FrontendConfig,
                                                ThreadedFrontend)
    from photon_ml_tpu.serving.frontend.metrics_http import \
        ThreadedMetricsEndpoint
    from photon_ml_tpu.serving.metrics import ServingMetrics
    from photon_ml_tpu.storage.model_io import save_game_model
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(seed)
    names = [f"f{j}" for j in range(d)]
    task = TaskType.LOGISTIC_REGRESSION

    def save_model(path, mseed):
        r = np.random.default_rng(mseed)
        model = GameModel(models={
            "fixed": FixedEffectModel(
                coefficients=Coefficients(means=r.normal(size=d)),
                feature_shard="all", task=task),
            "user": RandomEffectModel(
                w_stack=r.normal(size=(n_entities, d)) * 0.1,
                slot_of={i: i for i in range(n_entities)},
                random_effect_type="userId", feature_shard="all",
                task=task),
        })
        imap = IndexMap({feature_key(n): j for j, n in enumerate(names)})
        eidx = EntityIndex()
        for i in range(n_entities):
            eidx.get_or_add(f"user{i}")
        save_game_model(model, path, {"all": imap}, {"userId": eidx},
                        task=task)
        imap.save(os.path.join(path, "all.idx"))
        eidx.save(os.path.join(path, "userId.entities.json"))
        return path

    def mk_request(uid, user, r=None):
        r = r if r is not None else rng
        feats = [{"name": n, "term": "", "value": float(v)}
                 for n, v in zip(names, r.normal(size=d))]
        return Request(uid=uid, features=feats,
                       ids={"userId": f"user{user}"})

    probe_rng = np.random.default_rng(seed + 7)
    probes = [mk_request(i, i % n_entities, probe_rng)
              for i in range(min(max_batch, n_entities))]

    def scores(engine):
        return [float(s) for s in engine.score_requests(probes)]

    def wait_for(pred, timeout=30.0, what="condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.002)
        raise AssertionError(f"chaos bench timed out waiting for {what}")

    def http_get(port, path):
        with socketlib.create_connection(("127.0.0.1", port),
                                         timeout=10) as s:
            s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            data = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
        status = int(data.split(b" ", 2)[1])
        return status, data.split(b"\r\n\r\n", 1)[1]

    schedule = build_schedule(seed, rounds)
    assert schedule == build_schedule(seed, rounds), \
        "chaos schedule is not a pure function of the seed"

    inj = get_injector()
    inj.reset()

    with tempfile.TemporaryDirectory(prefix="photon_chaos_bench_") as tmp:
        base_dir = save_model(os.path.join(tmp, "base"), seed)
        log = DeltaLog(os.path.join(tmp, "owner-log"), fsync="rotate")
        engine, swapper = build_server(base_dir, max_batch=max_batch,
                                       warm=True, delta_log=log,
                                       log_owner=True)
        registry = engine.metrics.registry
        inj.registry = registry
        repl = attach_replication(swapper, ReplicationConfig(),
                                  registry=registry)
        trainer = IncrementalTrainer(
            swapper, TrainerConfig(coordinates=("user",), max_iters=3))

        # identity-chain witness: every durable append, in order
        chain = []
        log.add_listener(lambda rec: chain.append(rec.identity))

        # owner health surface, served over real HTTP
        owner_health = HealthState(registry=registry)
        owner_health.set_condition("engine_warmed", True, "warmed at build")
        owner_health.add_check("delta_log", delta_log_check(log))
        owner_watch = Watchdog(stall_after_s=20.0, registry=registry)
        owner_health.add_check("workers", owner_watch.check)

        tf = ThreadedFrontend(engine, swapper, FrontendConfig()).start()
        tf.server.batcher.watch = owner_watch.register(
            "batcher", tf.server.batcher.worker_thread)
        scrape = ThreadedMetricsEndpoint(engine.metrics, port=0,
                                         health=owner_health).start()

        # replica: serve.py --subscribe wiring, in-process
        rep_metrics = ServingMetrics()
        client = ReplicationClient(
            ReplicationClientConfig(host="127.0.0.1", port=repl.port,
                                    spool_dir=os.path.join(tmp, "spool"),
                                    ack_every=1, ack_interval_s=0.05,
                                    backoff_initial_s=0.05),
            registry=rep_metrics.registry).start()
        rep_dir = client.bootstrap(timeout=60.0)
        mirror = DeltaLog(client.mirror_path, fsync="never")
        rep_engine, rep_swapper = build_server(
            rep_dir, max_batch=max_batch, warm=True, metrics=rep_metrics,
            delta_log=mirror, log_owner=False)
        rep_swapper.set_base(rep_dir, client.floor or 0)
        client.on_snapshot = \
            lambda dd, g: rep_swapper.swap(dd, replay_floor=g)
        if client.model_dir != rep_dir:
            rep_swapper.swap(client.model_dir, replay_floor=client.floor)
        follower = LogFollower(mirror, lambda: rep_engine.store,
                               poll_interval_s=0.005,
                               registry=rep_metrics.registry)
        follower.run_once()
        follower.start()

        rep_health = HealthState(registry=rep_metrics.registry)
        rep_health.set_condition("engine_warmed", True, "warmed at build")
        rep_health.add_check("catchup",
                             follower_staleness_check(follower, 10.0))
        rep_watch = Watchdog(stall_after_s=20.0,
                             registry=rep_metrics.registry)
        rep_watch.register("follower", follower.worker_thread)
        rep_watch.register("subscriber", client.worker_thread)
        rep_health.add_check("workers", rep_watch.check)

        # admitted-loss ledger.  "attempted" counts logical requests the
        # edge client wants answered; "answered" counts real replies
        # (score or an explicit shed frame).  A connection killed before
        # the server read a byte (the only thing front.conn injects) is a
        # retry — the request was never admitted, so it cannot be lost.
        front_stats = {"attempted": 0, "answered": 0, "shed": 0,
                       "dropped_before_admit": 0}

        def front_round(n=4, uid0=0):
            for i in range(n):
                front_stats["attempted"] += 1
                for _ in range(50):  # retry cap: fail loudly, never spin
                    line = ""
                    try:
                        sock = socketlib.create_connection(
                            ("127.0.0.1", tf.port), timeout=10)
                        try:
                            fh = sock.makefile("rw", encoding="utf-8",
                                               newline="\n")
                            u = int(rng.integers(0, n_entities))
                            fh.write(json.dumps({
                                "uid": uid0 + i,
                                "features": [[n_, 0.5] for n_ in names],
                                "ids": {"userId": f"user{u}"}}) + "\n")
                            fh.flush()
                            line = fh.readline()
                        finally:
                            sock.close()
                    except OSError:
                        line = ""
                    if not line:
                        front_stats["dropped_before_admit"] += 1
                        continue
                    reply = json.loads(line)
                    assert "score" in reply or "error" in reply, \
                        f"unparseable frontend reply {reply!r}"
                    front_stats["answered"] += 1
                    if "score" not in reply:
                        front_stats["shed"] += 1
                    break
                else:
                    raise AssertionError(
                        "frontend request lost: no reply after 50 "
                        "connection attempts")

        def publish_batch(rows=6):
            fb = []
            for _ in range(rows):
                u = int(rng.integers(0, n_entities))
                req = mk_request(None, u)
                fb.append({"uid": None, "features": req.features,
                           "ids": req.ids,
                           "label": float(rng.integers(0, 2))})
            return trainer.consume(fb)

        def heal_tail():
            """One publish that must land durably — the heal witness."""
            dim = engine.store.coordinates["user"].dim
            identity = swapper.publish_delta(
                "user", f"user{int(rng.integers(0, n_entities))}",
                rng.normal(size=dim))
            assert identity is not None, "heal publish blocked"
            return identity

        out = None
        swap_seq = 0
        try:
            # settle compile baselines: everything after this must reuse
            # the warmed executables
            scores(engine)
            scores(rep_engine)
            front_round(n=2, uid0=10_000)
            tail0 = heal_tail()
            wait_for(lambda: follower.position is not None
                     and follower.position >= tail0,
                     what="replica initial convergence")
            owner_compiles0 = engine.compile_count
            rep_compiles0 = rep_engine.compile_count

            status, _ = http_get(scrape.port, "/readyz")
            assert status == 200, f"/readyz {status} on a healthy owner"
            status, _ = http_get(scrape.port, "/healthz")
            assert status == 200

            ttr = {}
            readyz_degraded_seen = 0
            for ev in schedule:
                t0 = time.perf_counter()
                # fire-on-next-hit: hit counters persist across rounds (a
                # point like repl.server.send has been hit hundreds of
                # times by now), so "fire on every hit, at most once" is
                # the right arm, not nth=1
                inj.arm(ev.point, ev.kind, max_fires=1, data=ev.data)
                if ev.fault_class in ("log_enospc", "log_torn"):
                    dim = engine.store.coordinates["user"].dim
                    blocked = swapper.publish_delta(
                        "user", "user1", rng.normal(size=dim))
                    assert blocked is None, \
                        f"{ev.fault_class}: publish survived the fault"
                    assert not log.healthy
                    status, body = http_get(scrape.port, "/readyz")
                    assert status == 503, \
                        f"/readyz {status} while the delta log is degraded"
                    assert b'"ready": false' in body
                    readyz_degraded_seen += 1
                elif ev.fault_class in ("swap_crash",):
                    swap_seq += 1
                    new_dir = save_model(
                        os.path.join(tmp, f"gen{swap_seq}"),
                        seed + swap_seq)
                    before = swapper.identity
                    try:
                        swapper.swap(new_dir)
                        raise AssertionError(
                            "swap survived an armed activate crash")
                    except InjectedCrash:
                        pass
                    assert swapper.identity == before, \
                        "crashed swap moved the serving identity"
                    # the swap lock unwound with the crash: a retry on the
                    # same dir must succeed and mint a fresh generation
                    assert swapper.swap(new_dir) is True, \
                        "swap retry after injected crash failed"
                elif ev.fault_class in ("snapshot_disconnect",):
                    swap_seq += 1
                    new_dir = save_model(
                        os.path.join(tmp, f"gen{swap_seq}"),
                        seed + swap_seq)
                    assert swapper.swap(new_dir) is True
                else:
                    # socket-plane faults: drive replication + edge load
                    publish_batch()
                    front_round(n=3, uid0=ev.round * 100)
                # coverage: the armed point must actually FIRE — socket
                # seams run on event-loop/daemon threads, so wait rather
                # than assert-immediately
                wait_for(lambda: inj.fired(ev.point) >= 1,
                         what=f"round {ev.round}: {ev.point} to fire")
                inj.disarm(ev.point)
                # heal: one durable publish, then the whole topology must
                # be green — owner ready over HTTP, replica converged
                tail = heal_tail()
                wait_for(lambda t=tail: follower.position is not None
                         and follower.position >= t,
                         what=f"replica heal after {ev.fault_class}")
                wait_for(lambda: owner_health.readyz()[0],
                         what=f"owner ready after {ev.fault_class}")
                wait_for(lambda: rep_health.readyz()[0],
                         what=f"replica ready after {ev.fault_class}")
                dt = time.perf_counter() - t0
                ttr.setdefault(ev.fault_class, []).append(dt)

            status, _ = http_get(scrape.port, "/readyz")
            assert status == 200, f"/readyz {status} after final heal"

            # acceptance: one identity chain, strictly monotone
            assert chain == sorted(chain) and \
                len(set(chain)) == len(chain), \
                "identity chain not strictly monotone"
            # bitwise owner/replica parity after heal
            owner_scores = scores(engine)
            parity = scores(rep_engine) == owner_scores
            assert parity, "owner/replica score divergence after heal"
            # zero recompiles after warm
            owner_recompiles = engine.compile_count - owner_compiles0
            rep_recompiles = rep_engine.compile_count - rep_compiles0
            assert owner_recompiles == 0 and rep_recompiles == 0, \
                f"recompiles after warm: owner {owner_recompiles}, " \
                f"replica {rep_recompiles}"
            # zero admitted-request loss: every logical request the edge
            # client attempted got a real reply (front_round raises on a
            # lost one; this closes the ledger)
            assert front_stats["answered"] == front_stats["attempted"], \
                f"admitted frontend requests lost: {front_stats}"
            # bounded time-to-ready per fault class
            worst = {k: max(v) for k, v in ttr.items()}
            assert all(v < 30.0 for v in worst.values()), \
                f"time-to-ready exceeded bound: {worst}"

            out = {
                "metric": "chaos_time_to_ready_s_max",
                "unit": "s",
                "value": round(max(worst.values()), 4),
                "backend": jax.default_backend(),
                "seed": seed, "rounds": rounds,
                "n_entities": n_entities, "d": d,
                "schedule": [ev.fault_class for ev in schedule],
                "time_to_ready_s": {k: [round(x, 4) for x in v]
                                    for k, v in sorted(ttr.items())},
                "faults_fired": {
                    f"{dict(lk).get('point')}|{dict(lk).get('kind')}":
                        int(v)
                    for lk, v in registry.counter_series(
                        "chaos_faults_fired_total").items()},
                "readyz_503_observed": readyz_degraded_seen,
                "frontend": dict(front_stats),
                "identity_chain": {"records": len(chain),
                                   "monotone": True},
                "parity": {"bitwise_equal": True},
                "recompiles_after_warm": {"owner": 0, "replica": 0},
                "delta_log": {
                    "write_errors": log.write_errors,
                    "records": log.records_written,
                    "segments": len(log.segments())},
                "replica": {
                    "reconnects": client.reconnects,
                    "snapshots_received": client.snapshots_received,
                    "records_applied": client.records_applied,
                    "catchup_errors": follower.errors_total},
            }
        finally:
            inj.reset()
            inj.registry = None
            follower.stop()
            client.stop()
            mirror.close()
            scrape.stop()
            tf.stop()
            repl.stop()
            log.close()
    if out_path is None:
        out_path = os.path.join(_REPO,
                                f"BENCH_CHAOS_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_fleet_bench(n_entities=2000, d=8, n_requests=400, max_batch=32,
                    n_models=4, seed=0, out_path=None) -> dict:
    """`bench.py --fleet`: photonfleet multi-model serving micro-bench ->
    BENCH_FLEET_<backend>.json.

    Three numbers the fleet design promises, measured on a synthetic
    same-shape model family:

      - ``compiles_after_warm``: compiles added as the fleet grows from 1
        to ``n_models`` equal-shape models.  The shared ``KernelCache``
        keys executables on ``(signature, bucket)`` and the signature
        carries no per-model state, so every entry past the first must be
        0 — asserted, the fleet's Flare invariant.
      - ``shadow_overhead_ratio``: wall-time ratio of dual-leg shadow
        scoring to single-leg scoring of the same request stream (ideal
        ~2.0; >> 2 would mean the shadow leg is compiling).
      - canary settle times: wall time from episode start to auto-promote
        (clean candidate) and to auto-rollback (drifting candidate under a
        tight gate), plus zero-recompile and zero-loss checks across both
        episodes.
    """
    import jax

    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.serving.batcher import request_from_json
    from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                         StoreConfig)
    from photon_ml_tpu.serving.fleet import (PROMOTED, ROLLED_BACK,
                                             CanaryController, CanaryPolicy,
                                             ModelFleet, ShadowScorer,
                                             shadow_overhead_ratio)
    from photon_ml_tpu.serving.swap import HotSwapper
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(seed)
    engine, metrics, names = _synthetic_serving_engine(
        rng, n_entities, d, max_batch)

    imap = IndexMap({feature_key(n): j for j, n in enumerate(names)})
    eidx = EntityIndex()
    for i in range(n_entities):
        eidx.get_or_add(f"user{i}")
    task = TaskType.LOGISTIC_REGRESSION

    def make_store(version):
        model = GameModel(models={
            "fixed": FixedEffectModel(
                coefficients=Coefficients(means=rng.normal(size=d)),
                feature_shard="all", task=task),
            "per_user": RandomEffectModel(
                w_stack=rng.normal(size=(n_entities, d)) * 0.1,
                slot_of={i: i for i in range(n_entities)},
                random_effect_type="userId", feature_shard="all",
                task=task),
        })
        return CoefficientStore.from_model(
            model, task, {"userId": eidx}, {"all": imap},
            config=StoreConfig(device_capacity=None), version=version,
            metrics=metrics)

    def make_requests(n, uid0=0):
        return [request_from_json({
            "uid": uid0 + i,
            "features": [[nm, float(v)]
                         for nm, v in zip(names, rng.normal(size=d))],
            "ids": {"userId": f"user{int(rng.integers(0, n_entities))}"}})
            for i in range(n)]

    t0 = time.perf_counter()
    engine.warm()
    warm_s = time.perf_counter() - t0
    fleet = ModelFleet(metrics=metrics)
    fleet.adopt("m0", engine, HotSwapper(engine))

    # -- fleet growth: compiles added per same-shape model (must stay 0)
    warm_compiles = fleet.kernels.compile_count
    compiles_after_warm = [0]
    register_s = []
    for k in range(1, n_models):
        before = fleet.kernels.compile_count
        t0 = time.perf_counter()
        fleet.register_store(f"m{k}", make_store(f"synthetic-{k}"))
        register_s.append(time.perf_counter() - t0)
        compiles_after_warm.append(fleet.kernels.compile_count - before)
    assert sum(compiles_after_warm) == 0, (
        f"same-shape fleet growth compiled: {compiles_after_warm}")

    reqs = make_requests(n_requests)
    handle = fleet.handle("m0")

    # -- shadow overhead: dual-leg vs single-leg wall over one stream
    t0 = time.perf_counter()
    baseline = handle.engine.score_requests(reqs)
    single_s = time.perf_counter() - t0
    scorer = ShadowScorer(handle, make_store("shadow"))
    t0 = time.perf_counter()
    served = scorer.score(reqs)
    dual_s = time.perf_counter() - t0
    assert np.array_equal(served, baseline)  # primary leg is what serves
    overhead = shadow_overhead_ratio(dual_s, single_s)

    # -- canary settle: clean promote, then drift rollback
    def episode(candidate, max_drift):
        ctl = CanaryController(handle, CanaryPolicy(
            fraction=0.5, min_observations=max(n_requests // 8, 8),
            max_drift=max_drift))
        ctl.start(candidate)
        scored = 0
        uid0 = 10_000
        while ctl.state == "canary":
            scored += len(ctl.score(make_requests(max_batch, uid0=uid0)))
            uid0 += max_batch
        return ctl, scored

    promote_ctl, promote_scored = episode(make_store("candidate-clean"),
                                          max_drift=float("inf"))
    assert promote_ctl.state == PROMOTED
    rollback_ctl, rollback_scored = episode(make_store("candidate-drift"),
                                            max_drift=1e-9)
    assert rollback_ctl.state == ROLLED_BACK
    assert fleet.kernels.compile_count == warm_compiles  # whole episode

    out = {
        "bench": "fleet_serving",
        "platform": jax.default_backend(),
        "n_entities": n_entities,
        "d": d,
        "max_batch": max_batch,
        "n_models": n_models,
        "warm_s": warm_s,
        "warm_compiles": warm_compiles,
        # the headline: executables compiled as models 1..N registered
        "compiles_after_warm": compiles_after_warm,
        "register_s": register_s,
        "shadow": {
            "single_leg_s": single_s,
            "dual_leg_s": dual_s,
            "overhead_ratio": overhead,
            "pairs": scorer.drift_view()["pairs"],
        },
        "canary": {
            "promote_settle_s": promote_ctl.settle_s,
            "promote_observations": promote_ctl.observations,
            "promote_requests_scored": promote_scored,
            "rollback_settle_s": rollback_ctl.settle_s,
            "rollback_reason": rollback_ctl.rollback_reason,
            "rollback_requests_scored": rollback_scored,
        },
        "recompiles_after_warm": fleet.kernels.compile_count - warm_compiles,
        "shadow_overhead_ratio": overhead,
    }
    if out_path is None:
        out_path = os.path.join(_REPO,
                                f"BENCH_FLEET_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_solve_bench(out_path=None, seed=0, n_users=96, per_user=96,
                    d_user=4, n_iterations=4) -> dict:
    """`bench.py --solve`: per-entity solve-path micro-bench ->
    BENCH_SOLVE_<backend>.json — the solve path's first tracked perf
    trajectory.  Three sections:

      - ``soa_newton``: lanes/sec of the batched SoA Newton bucket solve
        (opt/newton_soa.py) at the glmix_chip-like shape (d=4, cap=32),
        measured per pallas A/B variant: ``auto`` (the pallas Newton-step
        kernel where eligible — TPU) and ``xla`` (PHOTON_SOA_DISABLE_PALLAS
        path).  On cpu both variants run the XLA path; the recorded
        ``pallas_eligible`` flag says which backend the A/B is real on.
      - ``sweeps``: wall time of one VALIDATED multi-iteration GLMix fit,
        three ways over the same coordinates — host-paced validated
        ``CoordinateDescent`` (one dispatch per solve/score/validate
        phase), ``FusedSweep.run`` (training only, the no-validation floor)
        and ``FusedSweep.run_validated`` (held-out scoring + per-update
        losses fused into ONE program).  Each variant is warmed once and
        measured on re-entry; ``compiles_after_warm`` counts jit cache
        growth across the measured window (must be 0).
      - ``compact_scoring``: sparse-compact scoring throughput
        (models/game.score_compact_sparse) per pallas A/B variant —
        ``auto`` (match-dot kernel where eligible) vs ``xla``
        (PHOTON_COMPACT_DISABLE_PALLAS searchsorted chain).

    ``speedup_fused_validated`` (host / fused-validated wall) is the
    acceptance trajectory number.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.core.losses import logistic_loss
    from photon_ml_tpu.data.synthetic import generate_glmix
    from photon_ml_tpu.evaluation.evaluator import EvaluationSuite
    from photon_ml_tpu.game.config import (FixedEffectConfig,
                                           RandomEffectConfig)
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.opt.newton_soa import solve_newton_soa
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(seed)
    backend = jax.default_backend()
    out = {"metric": "solve_path", "backend": backend, "seed": seed}

    # -- 1. SoA Newton bucket solve: lanes/sec, pallas A/B ----------------
    d, cap, lanes = 4, 32, 4096
    x_t = jnp.asarray(rng.normal(size=(cap, d, lanes)), jnp.float32)
    y_t = jnp.asarray((rng.random((cap, lanes)) < 0.5), jnp.float32)
    off_t = jnp.zeros((cap, lanes), jnp.float32)
    wt_t = jnp.asarray(rng.uniform(0.5, 2.0, size=(cap, lanes)), jnp.float32)
    l2 = jnp.full((lanes,), 1.0, jnp.float32)
    w0 = jnp.zeros((d, lanes), jnp.float32)
    cfg = SolverConfig(max_iters=SOLVER_ITERS, tolerance=1e-7)

    from photon_ml_tpu.ops import soa_newton as _soa

    soa = {"d": d, "cap": cap, "lanes": lanes,
           "pallas_eligible": _soa.eligible(d, lanes)}
    for variant, env in (("auto", None), ("xla", "1")):
        prev = os.environ.get("PHOTON_SOA_DISABLE_PALLAS")
        if env is None:
            os.environ.pop("PHOTON_SOA_DISABLE_PALLAS", None)
        else:
            os.environ["PHOTON_SOA_DISABLE_PALLAS"] = env
        try:
            # fresh jit per variant: the gate reads the env at TRACE time
            solve = jax.jit(lambda w, l: solve_newton_soa(
                logistic_loss, w, x_t, y_t, off_t, wt_t, l, cfg))
            jax.block_until_ready(solve(w0, l2).w)  # warm
            t, reps = time.perf_counter(), 0
            while time.perf_counter() - t < 1.0:
                jax.block_until_ready(solve(w0, l2).w)
                reps += 1
            dt = (time.perf_counter() - t) / reps
            soa[variant] = {"seconds": round(dt, 6),
                            "lanes_per_sec": round(lanes / dt, 1)}
        finally:
            if prev is None:
                os.environ.pop("PHOTON_SOA_DISABLE_PALLAS", None)
            else:
                os.environ["PHOTON_SOA_DISABLE_PALLAS"] = prev
    out["soa_newton"] = soa

    # -- 2. validated sweep: host loop vs fused vs fused-validated --------
    data, _ = generate_glmix(n_users=n_users, per_user=per_user,
                             d_global=16, d_user=d_user, seed=seed)
    val, _ = generate_glmix(n_users=n_users, per_user=max(8, per_user // 4),
                            d_global=16, d_user=d_user, seed=seed + 1)
    solver = SolverConfig(max_iters=SOLVER_ITERS, tolerance=1e-7)
    cfgs = {
        "fixed": FixedEffectConfig(feature_shard="global", solver=solver,
                                   reg=Regularization(l2=1.0)),
        "per_user": RandomEffectConfig(random_effect_type="userId",
                                       feature_shard="per_user",
                                       solver=solver,
                                       reg=Regularization(l2=1.0)),
    }
    task = TaskType.LOGISTIC_REGRESSION
    coords = {cid: build_coordinate(cid, data, c, task)
              for cid, c in cfgs.items()}
    suite = EvaluationSuite.from_specs(["auc", "logistic_loss"])

    def _timed(thunk, warm=1, min_window=1.0):
        for _ in range(warm):
            thunk()
        t, reps = time.perf_counter(), 0
        while time.perf_counter() - t < min_window:
            thunk()
            reps += 1
        return (time.perf_counter() - t) / reps

    host = CoordinateDescent(coords, num_iterations=n_iterations,
                             validation=(val, suite))
    host_s = _timed(lambda: host.run())

    sweep = FusedSweep(coords, num_iterations=n_iterations)
    fused_s = _timed(lambda: sweep.run())
    plan = sweep.validation_plan(val, suite)
    fv_s = _timed(lambda: sweep.run_validated(plan))

    def _cache_sizes():
        progs = [sweep._program, sweep._val_program]
        progs += [c._vsolve for c in coords.values() if hasattr(c, "_vsolve")]
        progs += [c._solve for c in coords.values() if hasattr(c, "_solve")]
        return sum(p._cache_size() for p in progs if p is not None)

    before = _cache_sizes()
    sweep.run_validated(plan)
    sweep.run()
    compiles_after_warm = _cache_sizes() - before

    out["sweeps"] = {
        "n_samples": int(data.num_samples),
        "n_val": int(val.num_samples),
        "coordinates": len(coords),
        "iterations": n_iterations,
        "host_validated_s": round(host_s, 4),
        "fused_s": round(fused_s, 4),
        "fused_validated_s": round(fv_s, 4),
        "speedup_fused_validated": round(host_s / fv_s, 2),
        "validation_overhead_vs_fused": round(fv_s / fused_s, 2),
        "compiles_after_warm": int(compiles_after_warm),
    }
    out["value"] = out["sweeps"]["speedup_fused_validated"]
    out["unit"] = "x (host validated / fused validated)"

    # -- 3. sparse-compact scoring throughput, pallas A/B -----------------
    from photon_ml_tpu.models.game import score_compact_sparse
    from photon_ml_tpu.ops import compact_score as _cs

    E, dim, k_m, k_f, n = 20000, 50000, 16, 24, 32768
    w_idx = np.sort(rng.choice(dim, size=(E, k_m), replace=True), axis=1)
    w_idx = w_idx.astype(np.int32)
    w_val = rng.normal(size=(E, k_m)).astype(np.float32)
    slots = rng.integers(-1, E, size=n).astype(np.int32)
    f_idx = rng.integers(0, dim, size=(n, k_f)).astype(np.int32)
    f_val = rng.normal(size=(n, k_f)).astype(np.float32)
    args = tuple(jnp.asarray(a) for a in (w_idx, w_val, slots, f_idx, f_val))
    comp = {"entities": E, "dim": dim, "k_model": k_m, "k_feat": k_f,
            "n_samples": n, "pallas_eligible": _cs.eligible(k_m, k_f)}
    for variant, env in (("auto", None), ("xla", "1")):
        prev = os.environ.get("PHOTON_COMPACT_DISABLE_PALLAS")
        if env is None:
            os.environ.pop("PHOTON_COMPACT_DISABLE_PALLAS", None)
        else:
            os.environ["PHOTON_COMPACT_DISABLE_PALLAS"] = env
        try:
            score = jax.jit(score_compact_sparse)
            jax.block_until_ready(score(*args))  # warm (fresh jit per env)
            t, reps = time.perf_counter(), 0
            while time.perf_counter() - t < 1.0:
                jax.block_until_ready(score(*args))
                reps += 1
            dt = (time.perf_counter() - t) / reps
            comp[variant] = {"seconds": round(dt, 6),
                             "samples_per_sec": round(n / dt, 1)}
        finally:
            if prev is None:
                os.environ.pop("PHOTON_COMPACT_DISABLE_PALLAS", None)
            else:
                os.environ["PHOTON_COMPACT_DISABLE_PALLAS"] = prev
    out["compact_scoring"] = comp

    # -- 4. compact SERVING: the engine end-to-end on a compact store -----
    # (resolve -> AOT execute over device-resident (indices, values) hot
    # rows + compact cold overflow; no .to_dense() anywhere)
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.serving.batcher import BucketedBatcher, Request
    from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                         StoreConfig)
    from photon_ml_tpu.serving.engine import ScoringEngine

    sd, sE, sn = 32, 5000, 1000
    names = [f"f{j}" for j in range(sd)]
    imap = IndexMap({feature_key(nm): j for j, nm in enumerate(names)})
    eidx = EntityIndex()
    for i in range(sE):
        eidx.get_or_add(f"user{i}")
    w = (rng.normal(size=(sE, sd))
         * (rng.random((sE, sd)) < 0.25)).astype(np.float32)
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(
                means=rng.normal(size=sd).astype(np.float32)),
            feature_shard="all", task=TaskType.LOGISTIC_REGRESSION),
        "per_user": RandomEffectModel(
            w_stack=w, slot_of={i: i for i in range(sE)},
            random_effect_type="userId", feature_shard="all",
            task=TaskType.LOGISTIC_REGRESSION).to_compact(),
    })
    store = CoefficientStore.from_model(
        model, TaskType.LOGISTIC_REGRESSION, {"userId": eidx},
        {"all": imap}, config=StoreConfig(device_capacity=sE // 10))
    engine = ScoringEngine(store, BucketedBatcher(64))
    n_exec = engine.warm()
    reqs = [Request(uid=i, features=[
        {"name": nm, "term": "", "value": float(v)}
        for nm, v in zip(names, rng.normal(size=sd))],
        ids={"userId": f"user{int(rng.integers(0, sE + 50))}"})
        for i in range(sn)]
    engine.score_requests(reqs[:1])
    lat = []
    for r in reqs[:300]:
        t = time.perf_counter()
        engine.score_requests([r])
        lat.append(time.perf_counter() - t)
    t0 = time.perf_counter()
    engine.score_requests(reqs)
    stream_s = time.perf_counter() - t0
    store.rebalance()
    engine.score_requests(reqs[:64])
    lat = np.asarray(lat)
    out["compact_serving"] = {
        "entities": sE, "d": sd,
        "k": int(model.models["per_user"].indices.shape[1]),
        "device_capacity": sE // 10,
        "single_p50_s": round(float(np.percentile(lat, 50)), 6),
        "single_p99_s": round(float(np.percentile(lat, 99)), 6),
        "stream_qps": round(sn / stream_s, 1),
        "warm_executables": n_exec,
        "compiles_after_warm": engine.compile_count - n_exec,
    }

    if out_path is None:
        out_path = os.path.join(_REPO, f"BENCH_SOLVE_{backend}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def run_lint_bench(repeats: int = 3, out_path: str = None) -> dict:
    """Time the full whole-program photonlint pass over photon_ml_tpu/.

    Static analysis sits on the tier-1 path (tests/test_photonlint.py) and
    in the pre-commit loop (``tools/photonlint.py --paths``), so its cost is
    tracked like any other hot path: BENCH_LINT.json records wall time per
    run (best + mean), the ProgramIndex build share, the v3 dataflow-pass
    share (CFG fixpoints + call-graph reachability, accounted by
    analysis/dataflow.py), the v4 interprocedural-summary share
    (``summaries_s``), and the finding counts — a lint-time regression
    shows up in the same place a kernel regression would.  ASSERTS the
    full-package wall stays under the 6s budget (PHOTON_BENCH_LINT_BUDGET_S
    overrides).  Pure AST work: no jax import, identical on any backend.
    """
    import time as _time

    from photon_ml_tpu.analysis import run_analysis

    pkg = os.path.join(_REPO, "photon_ml_tpu")
    times, idx_times, flow_times, summ_times, result = [], [], [], [], None
    for _ in range(max(1, repeats)):
        t0 = _time.perf_counter()
        result = run_analysis([pkg], root=_REPO, whole_program=True)
        times.append(_time.perf_counter() - t0)
        idx_times.append(result.index_build_s)
        flow_times.append(result.dataflow_s)
        summ_times.append(result.summaries_s)
    # repeats 2+ hit the digest summary cache (unchanged sources), so the
    # recorded count shows what an incremental --diff run actually skips
    summaries_cached = result.summaries_cached
    budget_s = float(os.environ.get("PHOTON_BENCH_LINT_BUDGET_S", "6.0"))
    assert min(times) < budget_s, (
        f"photonlint full-package wall {min(times):.2f}s exceeds the "
        f"{budget_s:.1f}s budget — profile the rule prechecks and the "
        "dataflow pass (dataflow_s below) before shipping")
    out = {
        "metric": "photonlint_full_package_wall_s",
        "value": round(min(times), 4),
        "unit": "s",
        "budget_s": budget_s,
        "wall_s_mean": round(sum(times) / len(times), 4),
        "wall_s_all": [round(t, 4) for t in times],
        "index_build_s": round(min(idx_times), 4),
        "dataflow_s": round(min(flow_times), 4),
        "summaries_s": round(min(summ_times), 4),
        "summaries_cached": summaries_cached,
        "files_scanned": result.files_scanned,
        "violations": len(result.violations),
        "suppressed": len(result.suppressed),
        "by_rule": result.by_rule(),
        "repeats": max(1, repeats),
    }
    path = out_path or os.path.join(_REPO, "BENCH_LINT.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    return out


def run_obs_bench(n_calls: int = 200_000, budget_ns: float = 1000.0,
                  out_path: str = None) -> dict:
    """`bench.py --obs`: photonscope overhead micro-bench.

    The tracer sits on every serving hot path (submit, flush, resolve,
    execute) and the descent loop, so its DISABLED cost is a hot-path tax
    every request pays — this bench measures the per-call-site overhead of
    the module-level ``span()`` guard with tracing off vs on (ring-buffer
    record + attrs dict), plus ``instant()`` and a labeled registry ``inc``,
    and ASSERTS the disabled-path guard stays under ``budget_ns``
    (default 1µs — the acceptance budget; PHOTON_BENCH_OBS_BUDGET_NS
    overrides).

    photonpulse (ISSUE 15) rides the same call sites, so the same budget
    governs it: the disabled-path span guard is re-measured UNDER A BOUND
    TRACE CONTEXT (propagation wired in — must not add to the disabled
    cost, asserted against the same budget), the enabled stamp tax and the
    wire-decode cost are reported, and the cross-process merge throughput
    (``pulse.merge.merge_traces`` events/s over a synthetic 3-process pod
    slice) is measured for the tracemerge path.  Emits BENCH_OBS.json.
    Pure host work: no jax import.
    """
    from photon_ml_tpu import obs
    from photon_ml_tpu.obs.pulse import context as pulse_ctx
    from photon_ml_tpu.obs.pulse.merge import merge_traces
    from photon_ml_tpu.obs.registry import MetricsRegistry
    from photon_ml_tpu.obs.trace import Tracer, span

    budget_ns = float(os.environ.get("PHOTON_BENCH_OBS_BUDGET_NS", budget_ns))

    def per_call_ns(thunk, n):
        # best of 5 windows: the guard is ns-scale, so one long loop per
        # window amortizes the timer and the min rejects scheduler noise
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter_ns()
            for _ in range(n):
                thunk()
            best = min(best, (time.perf_counter_ns() - t0) / n)
        return best

    prev = obs.set_tracer(Tracer(capacity=4096, enabled=False))
    try:
        def disabled_span():
            with span("bench.op", bucket=64):
                pass

        disabled_ns = per_call_ns(disabled_span, n_calls)
        with pulse_ctx.bind(pulse_ctx.mint()):
            # propagation wired in, tracing off: the bound context must
            # cost nothing on the disabled path (same one-boolean guard)
            disabled_bound_ns = per_call_ns(disabled_span, n_calls)
        obs.get_tracer().enable()
        enabled_ns = per_call_ns(disabled_span, min(n_calls, 50_000))
        with pulse_ctx.bind(pulse_ctx.mint()):
            # the stamp tax: enabled span + trace/origin attrs per record
            enabled_bound_ns = per_call_ns(disabled_span,
                                           min(n_calls, 50_000))
        instant_ns = per_call_ns(
            lambda: obs.instant("bench.tick", k=1), min(n_calls, 50_000))
    finally:
        obs.set_tracer(prev)
    reg = MetricsRegistry()
    inc_ns = per_call_ns(lambda: reg.inc("bench_total", bucket="64"),
                         min(n_calls, 50_000))
    wire = pulse_ctx.to_wire(pulse_ctx.mint())
    from_wire_ns = per_call_ns(lambda: pulse_ctx.from_wire(wire),
                               min(n_calls, 50_000))

    # merge throughput: a synthetic 3-process pod slice, events spread
    # over many trace ids like a real frontend/owner/replica export
    n_merge_events = 30_000
    tids = [f"{i:016x}" for i in range(256)]
    traces = []
    for p, label in enumerate(("frontend", "owner", "replica")):
        evs = [{"name": "op", "ph": "X", "ts": i * 3 + p, "dur": 2,
                "pid": 1000 + p, "tid": 1,
                "args": {"trace": tids[i % len(tids)]}}
               for i in range(n_merge_events // 3)]
        clock = ({"owner": {"offset_ns": 5_000_000, "rtt_ns": 900}}
                 if label == "replica" else {})
        traces.append({"traceEvents": evs, "otherData":
                       {"process_label": label, "pid": 1000 + p,
                        "clock": clock}})
    t0 = time.perf_counter()
    merged = merge_traces(traces)
    merge_s = time.perf_counter() - t0
    assert len(merged["otherData"]["trace_ids"]) == len(tids)
    merge_events_per_s = n_merge_events / merge_s

    out = {
        "metric": "obs_disabled_span_overhead", "unit": "ns",
        "value": round(disabled_ns, 1),
        "disabled_span_ns": round(disabled_ns, 1),
        "disabled_bound_span_ns": round(disabled_bound_ns, 1),
        "enabled_span_ns": round(enabled_ns, 1),
        "enabled_bound_span_ns": round(enabled_bound_ns, 1),
        "instant_ns": round(instant_ns, 1),
        "registry_inc_labeled_ns": round(inc_ns, 1),
        "ctx_from_wire_ns": round(from_wire_ns, 1),
        "merge_events_per_s": round(merge_events_per_s),
        "merge_events": n_merge_events,
        "budget_ns": budget_ns,
        "within_budget": (disabled_ns < budget_ns
                          and disabled_bound_ns < budget_ns),
        "n_calls": n_calls,
    }
    path = out_path or os.path.join(_REPO, "BENCH_OBS.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    assert disabled_ns < budget_ns, (
        f"disabled-tracer span guard costs {disabled_ns:.0f}ns/call — over "
        f"the {budget_ns:.0f}ns budget; the hot paths pay this on EVERY "
        "request")
    assert disabled_bound_ns < budget_ns, (
        f"disabled-path span guard under a bound trace context costs "
        f"{disabled_bound_ns:.0f}ns/call — over the {budget_ns:.0f}ns "
        "budget; photonpulse propagation broke the one-boolean discipline")
    return out


def run_watch_bench(n_entities=128, d=8, max_batch=16, seed=0,
                    out_path=None) -> dict:
    """`bench.py --watch`: photonwatch fleet metrics plane end to end ->
    BENCH_WATCH_<backend>.json.

    Three live "processes" (frontend with a real scoring engine + two
    synthetic peers labeled owner/replica), each serving the federation
    pull on its own ``ThreadedMetricsEndpoint``, are merged by a
    ``FleetView`` over real HTTP.  Asserted, not just reported:

      - the fleet view merges all 3 sources (counters summed, build-info
        gauges per-process) and federation freshness p99 stays bounded
        (<1 s: counter bump -> visible in the merged registry);
      - a seeded ``serve.execute`` stall_dist episode fires EXACTLY the
        expected burn-rate alert — the latency SLO latches (firing edge,
        then resolves after the heal) while the availability SLO stays
        quiet — and the published ``fleet_slo_burn_rate`` gauge drives the
        admission controller's fleet-pressure shed;
      - the SLO firing edge dumped the flight recorder, retrievable over
        ``GET /flightz``;
      - the socket federation stream (``{"cmd": "watch"}``) returns a full
        frame then a smaller delta frame, both ingestible;
      - with photonwatch OFF, the span guard and the attribution guard
        each stay under the photonscope disabled-path budget (default 1µs,
        PHOTON_BENCH_OBS_BUDGET_NS) — watch rides the hot path for free;
      - zero engine recompiles after warm across the whole run.
    """
    import math
    import socket as socketlib
    import tempfile

    import jax

    from photon_ml_tpu import obs
    from photon_ml_tpu.chaos import get_injector
    from photon_ml_tpu.cli.serve import build_server
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.obs import pulse
    from photon_ml_tpu.obs.registry import export_build_info
    from photon_ml_tpu.obs.trace import span
    from photon_ml_tpu.obs.watch import (SLO, FleetView, SLOEngine,
                                         attribute, disable_attribution,
                                         enable_attribution)
    from photon_ml_tpu.serving.batcher import Request
    from photon_ml_tpu.serving.frontend import (FrontendConfig,
                                                ThreadedFrontend)
    from photon_ml_tpu.serving.frontend.admission import (AdmissionConfig,
                                                          AdmissionController)
    from photon_ml_tpu.serving.frontend.metrics_http import \
        ThreadedMetricsEndpoint
    from photon_ml_tpu.serving.metrics import ServingMetrics
    from photon_ml_tpu.storage.model_io import save_game_model
    from photon_ml_tpu.types import TaskType

    budget_ns = float(os.environ.get("PHOTON_BENCH_OBS_BUDGET_NS", 1000.0))
    rng = np.random.default_rng(seed)
    names = [f"f{j}" for j in range(d)]
    task = TaskType.LOGISTIC_REGRESSION

    def save_model(path, mseed):
        r = np.random.default_rng(mseed)
        model = GameModel(models={
            "fixed": FixedEffectModel(
                coefficients=Coefficients(means=r.normal(size=d)),
                feature_shard="all", task=task),
            "user": RandomEffectModel(
                w_stack=r.normal(size=(n_entities, d)) * 0.1,
                slot_of={i: i for i in range(n_entities)},
                random_effect_type="userId", feature_shard="all",
                task=task),
        })
        imap = IndexMap({feature_key(n): j for j, n in enumerate(names)})
        eidx = EntityIndex()
        for i in range(n_entities):
            eidx.get_or_add(f"user{i}")
        save_game_model(model, path, {"all": imap}, {"userId": eidx},
                        task=task)
        imap.save(os.path.join(path, "all.idx"))
        eidx.save(os.path.join(path, "userId.entities.json"))
        return path

    def http_get(port, path):
        with socketlib.create_connection(("127.0.0.1", port),
                                         timeout=10) as s:
            s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            data = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
        status = int(data.split(b" ", 2)[1])
        return status, data.split(b"\r\n\r\n", 1)[1]

    def per_call_ns(thunk, n):
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter_ns()
            for _ in range(n):
                thunk()
            best = min(best, (time.perf_counter_ns() - t0) / n)
        return best

    def pctl(xs, q):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    probes = [Request(uid=i, features=[{"name": n, "term": "",
                                        "value": float(v)}
                                       for n, v in zip(
                                           names, rng.normal(size=d))],
                      ids={"userId": f"user{i % n_entities}"})
              for i in range(max_batch)]

    inj = get_injector()
    inj.reset()
    out = None
    with tempfile.TemporaryDirectory(prefix="photon_watch_bench_") as tmp:
        pulse.set_flight(pulse.FlightRecorder(
            os.path.join(tmp, "flight")))
        # -- the 3-process topology -------------------------------------
        front_metrics = ServingMetrics()
        engine, swapper = build_server(
            save_model(os.path.join(tmp, "base"), seed),
            max_batch=max_batch, warm=True, metrics=front_metrics)
        export_build_info(front_metrics.registry, role="frontend")
        enable_attribution(front_metrics.registry)
        owner_metrics = ServingMetrics()
        export_build_info(owner_metrics.registry, role="owner")
        rep_metrics = ServingMetrics()
        export_build_info(rep_metrics.registry, role="replica")
        endpoints = {}
        tf = None
        try:
            for label, m in (("front", front_metrics),
                             ("owner", owner_metrics),
                             ("replica", rep_metrics)):
                endpoints[label] = ThreadedMetricsEndpoint(m, port=0).start()
            tf = ThreadedFrontend(engine, swapper, FrontendConfig(
                admission=AdmissionConfig(budget_s=5.0))).start()

            view = FleetView(stale_after_s=5.0)

            def poll_all():
                for label, ep in endpoints.items():
                    status, body = http_get(ep.port, "/watchz")
                    assert status == 200, f"/watchz {status} on {label}"
                    assert view.ingest(label, json.loads(body)), \
                        f"fleet ingest rejected a /watchz frame ({label})"

            # settle compile baseline: everything below must reuse it
            [float(s) for s in engine.score_requests(probes)]
            compiles0 = engine.compile_count

            # -- federation freshness: bump -> merged visibility --------
            fresh_s = []
            for i in range(40):
                owner_metrics.registry.inc("train_batches_total")
                rep_metrics.registry.inc("catchup_records_total")
                front_metrics.registry.inc("watch_ping_total")
                t0 = time.perf_counter()
                poll_all()
                got = sum(view.registry.counter_series(
                    "watch_ping_total").values())
                assert got == i + 1, \
                    f"merged counter lagged: {got} != {i + 1}"
                fresh_s.append(time.perf_counter() - t0)
            snap = view.fleet_snapshot()
            assert snap["processes"] == 3
            assert not any(s["stale"] for s in snap["sources"].values())
            build = view.registry.gauge_series("photon_build_info")
            assert len(build) == 3, \
                f"expected 3 per-process build_info gauges, got {build}"
            roles = {dict(lk).get("role") for lk in build}
            assert roles == {"frontend", "owner", "replica"}
            fresh_p99 = pctl(fresh_s, 0.99)
            assert fresh_p99 < 1.0, \
                f"federation freshness p99 {fresh_p99:.3f}s over bound"

            # -- socket federation stream: full frame then delta --------
            sock = socketlib.create_connection(("127.0.0.1", tf.port),
                                               timeout=10)
            stream_view = FleetView()
            try:
                fh = sock.makefile("rw", encoding="utf-8", newline="\n")
                fh.write(json.dumps({"cmd": "watch"}) + "\n")
                fh.flush()
                full = json.loads(fh.readline())["watch"]
                assert full["full"] and full["seq"] == 1
                assert stream_view.ingest("front", full)
                front_metrics.registry.inc("watch_ping_total")
                fh.write(json.dumps({"cmd": "watch"}) + "\n")
                fh.flush()
                delta = json.loads(fh.readline())["watch"]
                assert not delta["full"] and delta["seq"] == 2
                assert stream_view.ingest("front", delta)
                full_series = sum(len(full[k]) for k in
                                  ("counters", "gauges", "histograms"))
                delta_series = sum(len(delta[k]) for k in
                                   ("counters", "gauges", "histograms"))
                assert delta_series < full_series, \
                    "delta frame did not shrink vs the full snapshot"
            finally:
                sock.close()

            # -- SLO episode: stall_dist burns latency, not availability
            slos = [
                SLO(name="availability", objective=0.99,
                    kind="availability", total="front_requests_total",
                    bad=("requests_shed_total",),
                    fast=(0.5, 2.0), slow=(1.0, 4.0),
                    fast_burn=2.0, slow_burn=1.5),
                SLO(name="latency_p99", objective=0.95, kind="latency",
                    histogram="serving_latency_s", threshold_s=0.016,
                    fast=(0.5, 2.0), slow=(1.0, 4.0),
                    fast_burn=2.0, slow_burn=1.5),
            ]
            slo_engine = SLOEngine(slos, publish=front_metrics.registry)

            def front_round(n=3, uid0=0):
                # organic front_requests_total for the availability SLO
                s = socketlib.create_connection(("127.0.0.1", tf.port),
                                                timeout=10)
                try:
                    fh = s.makefile("rw", encoding="utf-8", newline="\n")
                    for i in range(n):
                        u = int(rng.integers(0, n_entities))
                        fh.write(json.dumps({
                            "uid": uid0 + i,
                            "features": [[n_, 0.5] for n_ in names],
                            "ids": {"userId": f"user{u}"}}) + "\n")
                        fh.flush()
                        reply = json.loads(fh.readline())
                        assert "score" in reply, f"shed/err: {reply!r}"
                finally:
                    s.close()

            def tick(drive_front=False):
                if drive_front:
                    front_round(n=2, uid0=int(time.monotonic() * 1e3) % 10**6)
                else:
                    engine.score_requests(probes)
                poll_all()
                slo_engine.evaluate(view.registry)
                time.sleep(0.02)

            warm_t = time.monotonic()
            while time.monotonic() - warm_t < 1.2:  # clean baseline window
                tick(drive_front=True)
            assert slo_engine.events() == [], \
                f"SLO alerts on a healthy fleet: {slo_engine.events()}"

            # the episode: every serve.execute hit holds ~50ms (> the
            # 16ms SLO threshold), sampled from the seeded lognormal;
            # stalls keep coming until the alert latches, so the episode
            # self-scales past whatever good traffic the warm window left
            # in the burn windows
            inj.arm("serve.execute", "stall_dist",
                    data={"mu": math.log(0.05), "sigma": 0.1,
                          "cap_s": 0.08})
            fire_t = time.monotonic()
            while "latency_p99" not in slo_engine.firing():
                assert time.monotonic() - fire_t < 30.0, \
                    "latency SLO never fired under the stall episode"
                tick()
            stall_fires = inj.fired("serve.execute")  # before disarm zeroes
            inj.disarm("serve.execute")
            assert stall_fires >= 3, \
                f"alert latched after only {stall_fires} stalls?"

            # published burn gauge drives the fleet-pressure admission shed
            burn = max(front_metrics.registry.gauge_series(
                "fleet_slo_burn_rate").values())
            assert burn > 2.0, f"published burn gauge too low: {burn}"
            adm = AdmissionController(
                AdmissionConfig(budget_s=5.0, fleet_burn_budget=1.0),
                registry=front_metrics.registry)
            verdict = adm.decide(0.0)
            assert not verdict.admitted \
                and verdict.reason == "fleet_pressure", \
                f"fleet-pressure shed did not engage: {verdict}"

            # heal: good traffic until the alert resolves
            heal_t = time.monotonic()
            while "latency_p99" in slo_engine.firing():
                assert time.monotonic() - heal_t < 30.0, \
                    "latency SLO never resolved after the heal"
                tick()
            events = slo_engine.events()
            fired = [(e["slo"], e["state"]) for e in events]
            assert fired.count(("latency_p99", "firing")) == 1, fired
            assert fired.count(("latency_p99", "resolved")) == 1, fired
            assert not any(s == "availability" for s, _ in fired), \
                f"availability SLO fired spuriously: {fired}"

            # the firing edge dumped the flight recorder -> /flightz
            status, body = http_get(endpoints["front"].port, "/flightz")
            assert status == 200, f"/flightz {status}"
            flight = json.loads(body)
            assert flight["dumps"], "SLO firing edge left no flight dump"
            assert any("slo_burn" in d["reason"]
                       for d in flight["dumps"]), flight["dumps"]

            # fleet endpoint end to end: /fleetz off a FleetView-wired
            # scrape endpoint (the tools/fleetwatch.py serving shape)
            fleet_ep = ThreadedMetricsEndpoint(
                ServingMetrics(registry=view.registry), port=0,
                fleet_view=view).start()
            try:
                status, body = http_get(fleet_ep.port, "/fleetz")
                assert status == 200, f"/fleetz {status}"
                fleetz = json.loads(body)
                assert fleetz["processes"] == 3
            finally:
                fleet_ep.stop()

            # -- disabled-path cost: watch must ride the hot path free --
            disable_attribution()
            prev = obs.set_tracer(obs.Tracer(capacity=64, enabled=False))
            try:
                def guarded():
                    with span("bench.op", bucket=64):
                        pass

                def attributed():
                    with attribute("bench.op"):
                        pass

                disabled_span_ns = per_call_ns(guarded, 100_000)
                disabled_attr_ns = per_call_ns(attributed, 100_000)
            finally:
                obs.set_tracer(prev)
            assert disabled_span_ns < budget_ns, (
                f"disabled span guard {disabled_span_ns:.0f}ns/call over "
                f"the {budget_ns:.0f}ns budget")
            assert disabled_attr_ns < budget_ns, (
                f"disabled attribution guard {disabled_attr_ns:.0f}ns/call "
                f"over the {budget_ns:.0f}ns budget")

            compiles_after_warm = engine.compile_count - compiles0
            assert compiles_after_warm == 0, \
                f"recompiles after warm: {compiles_after_warm}"

            device_sites = {dict(lk).get("site") for lk in
                            front_metrics.registry.gauge_series(
                                "xla_device_seconds")}
            assert "serve.execute" in device_sites, \
                f"attribution left no xla_device_seconds: {device_sites}"

            out = {
                "metric": "watch_federation_freshness_p99_s",
                "unit": "s",
                "value": round(fresh_p99, 4),
                "backend": jax.default_backend(),
                "seed": seed,
                "processes_merged": 3,
                "freshness_s": {"p50": round(pctl(fresh_s, 0.50), 4),
                                "p99": round(fresh_p99, 4),
                                "rounds": len(fresh_s)},
                "socket_stream": {"full_series": full_series,
                                  "delta_series": delta_series},
                "slo": {
                    "events": [(e["slo"], e["state"]) for e in events],
                    "stall_fires": stall_fires,
                    "burn_at_fire": round(burn, 2),
                    "availability_quiet": True,
                    "fleet_pressure_shed": True},
                "flight_dumps": len(flight["dumps"]),
                "disabled_span_ns": round(disabled_span_ns, 1),
                "disabled_attribution_ns": round(disabled_attr_ns, 1),
                "budget_ns": budget_ns,
                "within_budget": (disabled_span_ns < budget_ns
                                  and disabled_attr_ns < budget_ns),
                "recompiles_after_warm": compiles_after_warm,
            }
        finally:
            disable_attribution()
            inj.reset()
            pulse.set_flight(None)
            if tf is not None:
                tf.stop()
            for ep in endpoints.values():
                ep.stop()
    if out_path is None:
        out_path = os.path.join(_REPO,
                                f"BENCH_WATCH_{jax.default_backend()}.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    return out


def run_stream_bench(n_rows: int = 50_000, n_features: int = 64,
                     n_entities: int = 500, batch_rows: int = 1024,
                     workers: int = 2, out_path: str = None) -> dict:
    """`bench.py --stream`: photonstream ingest micro-bench.

    Writes a synthetic TrainingExampleAvro dataset (deflate blocks, several
    files), then measures the out-of-core ingest end to end
    (``stream.stream_game_data``: scan -> bounded parallel decode ->
    fixed-shape batch fill -> double-buffered device upload):

      ingest_mb_per_s        container bytes consumed / wall
      batches_per_s          fixed-shape device-feed batches / wall
      stall_fraction         consumer time blocked on undecoded chunks /
                             wall (0 = decode fully hidden by fill+upload)
      peak_rss_mb            process high-water RSS after the timed pass
      compiles_after_warm    jitted dynamic_update_slice cache growth on a
                             second identical pass — MUST be 0 (fixed batch
                             shapes are the whole point of the feed)

    Emits BENCH_STREAM_<backend>.json.
    """
    import resource
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
    from photon_ml_tpu.obs.probe import get_probe
    from photon_ml_tpu.obs.registry import (MetricsRegistry, get_registry,
                                            set_registry)
    from photon_ml_tpu.stream import stream_game_data
    from photon_ml_tpu.utils import transfer

    backend = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    names = [f"f{j}" for j in range(n_features)]
    n_files = 4
    per_file = max(1, n_rows // n_files)
    k = min(8, n_features)
    tmp = tempfile.mkdtemp(prefix="photonstream_bench_")
    try:
        for fi in range(n_files):
            records = []
            for i in range(per_file):
                idx = rng.choice(n_features, size=k, replace=False)
                vals = rng.normal(size=k)
                records.append({
                    "uid": fi * per_file + i,
                    "response": float(rng.integers(0, 2)),
                    "label": None,
                    "features": [{"name": names[j], "term": "",
                                  "value": float(v)}
                                 for j, v in zip(idx, vals)],
                    "weight": None, "offset": None,
                    "metadataMap":
                        {"userId": f"u{rng.integers(0, n_entities)}"},
                })
            avro_io.write_container(
                os.path.join(tmp, f"part-{fi:05d}.avro"), TRAINING_EXAMPLE,
                records, block_records=1024)
        file_bytes = sum(os.path.getsize(os.path.join(tmp, p))
                         for p in os.listdir(tmp))
        index_maps = {"global": IndexMap.from_features(
            [(nm, "") for nm in names], add_intercept=True)}

        def one_pass():
            data, _ = stream_game_data(
                tmp, index_maps, id_tag_names=["userId"],
                batch_rows=batch_rows, workers=workers)
            jax.block_until_ready(data.features["global"])
            return data

        prev_reg = get_registry()
        try:
            set_registry(MetricsRegistry())
            one_pass()  # warm: compiles the batch + ragged-tail updates
            warm_cache = transfer._UPDATE._cache_size()
            reg = MetricsRegistry()
            set_registry(reg)
            bytes_before = get_probe().transfer_bytes(direction="h2d",
                                                      site="stream_feed")
            t0 = time.perf_counter()
            data = one_pass()
            wall = time.perf_counter() - t0
            compiles_after_warm = transfer._UPDATE._cache_size() - warm_cache
            upload_bytes = get_probe().transfer_bytes(
                direction="h2d", site="stream_feed") - bytes_before
        finally:
            set_registry(prev_reg)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    n = int(data.num_samples)
    batches = -(-n // batch_rows)  # ceil: one feed push per filled batch
    stall_s = float(reg.gauge("stream_stall_seconds") or 0.0)
    # ru_maxrss is the lifetime high-water mark (KB on Linux) — an upper
    # bound on the streaming pass, tight here because the bench never
    # materializes an [n, d] host array to inflate it first
    peak_rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    out = {
        "metric": "stream_ingest_mb_per_s", "unit": "MB/s",
        "backend": backend,
        "value": round(file_bytes / wall / 1e6, 2),
        "ingest_mb_per_s": round(file_bytes / wall / 1e6, 2),
        "batches_per_s": round(batches / wall, 1),
        "stall_fraction": round(stall_s / wall, 4),
        "peak_rss_mb": round(peak_rss_kb / 1024, 1),
        "compiles_after_warm": int(compiles_after_warm),
        "wall_s": round(wall, 4),
        "file_bytes": int(file_bytes),
        "rows": n, "features": n_features + 1, "entities": n_entities,
        "batch_rows": batch_rows, "workers": workers,
        "chunks": int(reg.counter("stream_chunks_total")),
        "chunk_errors": int(reg.counter("stream_chunk_errors_total")),
        "upload_bytes": int(upload_bytes),
    }
    path = out_path or os.path.join(_REPO, f"BENCH_STREAM_{backend}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    assert compiles_after_warm == 0, (
        f"streaming ingest recompiled {compiles_after_warm} update "
        "program(s) on an identically-shaped second pass — the fixed "
        "batch-shape contract is broken")
    return out


# configs with an unconditional scipy stand-in for vs_baseline.  glmix_chip
# is special-cased in _entry_from: at chip scale no host holds its design
# matrix (vs_baseline stays null), but CPU-floor runs reconstruct the
# device-generated design on host and pin coefficient parity vs scipy
# (quality_gate's floor-scale anchor)
CPU_REF_CONFIGS = ("a1a", "sparse1m", "glmix2", "glmix3", "gp_tune")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", action="store_true")
    ap.add_argument("--config", choices=list(RUNNERS))
    ap.add_argument("--platform", default=None)
    ap.add_argument("--ab-chain", action="store_true",
                    help="with --config glmix2: measure fused/host/xla over "
                         "one design upload, one JSON line per variant")
    ap.add_argument("--serving", action="store_true",
                    help="online-scoring micro-bench (p50/p99 per-request "
                         "latency, QPS, padding waste) -> "
                         "BENCH_SERVING_<backend>.json")
    ap.add_argument("--serving-entities", type=int, default=20000)
    ap.add_argument("--serving-requests", type=int, default=2000)
    ap.add_argument("--serving-device-capacity", type=int, default=0,
                    help="hot entity rows on device (0 = all, or "
                         "n_entities/10 in --zipf mode)")
    ap.add_argument("--zipf", type=float, default=0.0,
                    help="with --serving: skew entity traffic by a zipf(a) "
                         "rank distribution (0 = uniform) — exercises the "
                         "frequency-ranked hot set")
    ap.add_argument("--serving-deadline-us", type=float, default=200.0,
                    help="with --serving: async batcher deadline")
    ap.add_argument("--mesh", action="store_true",
                    help="with --serving: pod-slice sweep — shard the "
                         "coefficient store over 1/2/4/8 mesh shards, "
                         "measure throughput/p99/aggregate-capacity vs "
                         "shard count, assert zero recompiles after warm "
                         "-> BENCH_SERVING_MESH_<backend>.json")
    ap.add_argument("--mesh-shard-counts", default="1,2,4,8",
                    help="with --serving --mesh: comma list of shard "
                         "counts to sweep")
    ap.add_argument("--skew-sweep", action="store_true",
                    help="with --serving: traffic-skew robustness sweep — "
                         "zipf s=0.8..1.5 over a sharded store, traffic-"
                         "aware vs pre-placement router curves, asserts "
                         "flat hit rate + p99 and bitwise 1-shard/uniform "
                         "parity -> BENCH_SKEW_<backend>.json")
    ap.add_argument("--skew-values", default="0.8,1.0,1.2,1.5",
                    help="with --serving --skew-sweep: comma list of zipf "
                         "exponents to sweep")
    ap.add_argument("--skew-shards", type=int, default=4,
                    help="with --serving --skew-sweep: mesh shards for the "
                         "sweep")
    ap.add_argument("--open-loop", action="store_true",
                    help="with --serving: open-loop (Poisson arrival-rate "
                         "driven) overload sweep against the network front "
                         "end — p50/p99/p999 + shed rate per arrival rate "
                         "-> BENCH_NET_<backend>.json")
    ap.add_argument("--open-loop-rates", default="",
                    help="comma list of arrival rates in qps (default: "
                         "0.25/0.7/1.5 x the calibrated engine capacity "
                         "= below/near/past saturation)")
    ap.add_argument("--open-loop-duration", type=float, default=2.5,
                    help="seconds of Poisson arrivals per rate point")
    ap.add_argument("--open-loop-connections", type=int, default=4,
                    help="client connections the arrivals spread across")
    ap.add_argument("--open-loop-budget-ms", type=float, default=25.0,
                    help="front-end admission deadline budget")
    ap.add_argument("--online", action="store_true",
                    help="photonlearn loop end to end (incremental refit "
                         "throughput under concurrent serving load, "
                         "publish->visible freshness, delta-log catch-up "
                         "replay rate + replica score parity) -> "
                         "BENCH_ONLINE_<backend>.json")
    ap.add_argument("--online-batches", type=int, default=8,
                    help="with --online: labeled mini-batches streamed "
                         "through the trainer")
    ap.add_argument("--online-batch-size", type=int, default=64,
                    help="with --online: examples per mini-batch")
    ap.add_argument("--repl", action="store_true",
                    help="photonrepl end to end (socket snapshot bootstrap "
                         "+ live delta shipping to N replicas under "
                         "concurrent refit load; bitwise owner/replica "
                         "score parity, zero replica recompiles and "
                         "log-replay reconnect asserted; publish->store-"
                         "visible freshness p50/p99) -> "
                         "BENCH_REPL_<backend>.json")
    ap.add_argument("--repl-replicas", type=int, default=2,
                    help="with --repl: socket subscribers to boot")
    ap.add_argument("--repl-batches", type=int, default=8,
                    help="with --repl: labeled mini-batches streamed "
                         "through the owner's trainer")
    ap.add_argument("--repl-batch-size", type=int, default=32,
                    help="with --repl: examples per mini-batch")
    ap.add_argument("--chaos", action="store_true",
                    help="photonchaos end to end (owner + replica + "
                         "frontend under a seeded fault schedule: log "
                         "ENOSPC/torn writes, replication drop/garbage/"
                         "stall, snapshot disconnect, swap crash, edge "
                         "connection kills; identity-chain monotonicity, "
                         "bitwise parity after heal, zero admitted-request "
                         "loss, zero recompiles and bounded time-to-ready "
                         "asserted) -> BENCH_CHAOS_<backend>.json")
    ap.add_argument("--chaos-seed", type=int, default=0,
                    help="with --chaos: the fault schedule is a pure "
                         "function of this seed")
    ap.add_argument("--chaos-rounds", type=int, default=10,
                    help="with --chaos: fault rounds (first "
                         "len(FAULT_CLASSES) rounds cover every class "
                         "once)")
    ap.add_argument("--fleet", action="store_true",
                    help="photonfleet multi-model serving micro-bench "
                         "(compiles-after-warm stays 0 as same-shape "
                         "models grow 1->N on the shared kernel cache, "
                         "shadow dual-leg overhead ratio, canary "
                         "auto-promote/auto-rollback settle times) -> "
                         "BENCH_FLEET_<backend>.json")
    ap.add_argument("--fleet-models", type=int, default=4,
                    help="with --fleet: same-shape models to grow to")
    ap.add_argument("--fleet-entities", type=int, default=2000,
                    help="with --fleet: entities per model")
    ap.add_argument("--fleet-requests", type=int, default=400,
                    help="with --fleet: scored requests per measurement")
    ap.add_argument("--solve", action="store_true",
                    help="per-entity solve-path micro-bench (SoA Newton "
                         "lanes/sec, host vs fused vs fused-validated sweep "
                         "wall, sparse-compact scoring throughput, pallas "
                         "A/B) -> BENCH_SOLVE_<backend>.json")
    ap.add_argument("--stream", action="store_true",
                    help="photonstream ingest micro-bench (ingest MB/s, "
                         "batches/s, pipeline-stall fraction, peak RSS, "
                         "recompiles-after-warm == 0 asserted) -> "
                         "BENCH_STREAM_<backend>.json")
    ap.add_argument("--stream-rows", type=int, default=50_000,
                    help="with --stream: synthetic dataset rows")
    ap.add_argument("--stream-batch-rows", type=int, default=1024,
                    help="with --stream: fixed device-feed batch shape")
    ap.add_argument("--stream-workers", type=int, default=2,
                    help="with --stream: decode thread-pool size")
    ap.add_argument("--lint", action="store_true",
                    help="photonlint wall-time micro-bench (whole-program "
                         "pass over photon_ml_tpu/) -> BENCH_LINT.json")
    ap.add_argument("--lint-repeats", type=int, default=3)
    ap.add_argument("--obs", action="store_true",
                    help="photonscope overhead micro-bench (disabled-path "
                         "span guard ns/call vs enabled; asserts the "
                         "disabled guard under budget) -> BENCH_OBS.json")
    ap.add_argument("--watch", action="store_true",
                    help="photonwatch fleet metrics plane end to end "
                         "(3 live processes merged over real /watchz "
                         "HTTP with bounded federation freshness p99, "
                         "socket delta stream, seeded stall_dist episode "
                         "firing EXACTLY the latency burn-rate alert — "
                         "availability stays quiet — fleet-pressure "
                         "admission shed, flight dump over /flightz, "
                         "disabled span+attribution guards under the "
                         "photonscope budget, zero recompiles after "
                         "warm) -> BENCH_WATCH_<backend>.json")
    ap.add_argument("--out", default=None,
                    help="with --serving/--lint/--obs/--watch: output "
                         "JSON path override")
    a = ap.parse_args()
    if a.watch:
        print(json.dumps(run_watch_bench(out_path=a.out)))
        return
    if a.stream:
        print(json.dumps(run_stream_bench(
            n_rows=a.stream_rows, batch_rows=a.stream_batch_rows,
            workers=a.stream_workers, out_path=a.out)))
        return
    if a.obs:
        print(json.dumps(run_obs_bench(out_path=a.out)))
        return
    if a.lint:
        print(json.dumps(run_lint_bench(repeats=a.lint_repeats,
                                        out_path=a.out)))
        return
    if a.solve:
        print(json.dumps(run_solve_bench(out_path=a.out)))
        return
    if a.fleet:
        print(json.dumps(run_fleet_bench(
            n_entities=a.fleet_entities,
            n_requests=a.fleet_requests,
            n_models=a.fleet_models,
            out_path=a.out)))
        return
    if a.chaos:
        print(json.dumps(run_chaos_bench(
            seed=a.chaos_seed,
            rounds=a.chaos_rounds,
            out_path=a.out)))
        return
    if a.repl:
        print(json.dumps(run_repl_bench(
            n_replicas=a.repl_replicas,
            batches=a.repl_batches,
            batch_size=a.repl_batch_size,
            out_path=a.out)))
        return
    if a.online:
        print(json.dumps(run_online_bench(
            batches=a.online_batches,
            batch_size=a.online_batch_size,
            out_path=a.out)))
        return
    if a.serving and a.mesh:
        counts = tuple(int(c) for c in a.mesh_shard_counts.split(",")
                       if c.strip())
        # the sweep needs a multi-device view; on a CPU host that means
        # virtual devices, and the flag must land before the backend
        # initializes (it is inert on real accelerator platforms)
        flag = f"--xla_force_host_platform_device_count={max(counts)}"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
        print(json.dumps(run_serving_mesh_bench(
            shard_counts=counts,
            n_entities=a.serving_entities,
            n_requests=a.serving_requests,
            per_shard_capacity=a.serving_device_capacity or None,
            zipf=a.zipf or 1.1,
            out_path=a.out)))
        return
    if a.mesh:
        ap.error("--mesh requires --serving")
    if a.serving and a.skew_sweep:
        skews = tuple(float(s) for s in a.skew_values.split(",")
                      if s.strip())
        # same multi-device trick as --mesh: must land before the backend
        # initializes (inert on real accelerator platforms)
        flag = f"--xla_force_host_platform_device_count={a.skew_shards}"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
        print(json.dumps(run_skew_sweep_bench(
            skews=skews,
            n_shards=a.skew_shards,
            per_shard_capacity=a.serving_device_capacity or None,
            out_path=a.out)))
        return
    if a.skew_sweep:
        ap.error("--skew-sweep requires --serving")
    if a.serving and a.open_loop:
        rates = [float(r) for r in a.open_loop_rates.split(",")
                 if r.strip()] or None
        print(json.dumps(run_open_loop_bench(
            n_entities=a.serving_entities,
            rates=rates, duration_s=a.open_loop_duration,
            n_connections=a.open_loop_connections,
            budget_ms=a.open_loop_budget_ms,
            deadline_us=a.serving_deadline_us,
            out_path=a.out)))
        return
    if a.open_loop:
        ap.error("--open-loop requires --serving")
    if a.serving:
        print(json.dumps(run_serving_bench(
            n_entities=a.serving_entities, n_requests=a.serving_requests,
            device_capacity=a.serving_device_capacity or None,
            zipf=a.zipf, deadline_us=a.serving_deadline_us,
            out_path=a.out)))
        return
    if a.ab_chain and a.config != "glmix2":
        # outside the `if a.config:` branch: a bare --ab-chain must error,
        # not silently fall through to the full orchestrator
        ap.error("--ab-chain requires --config glmix2")

    # Child modes self-timeout via SIGALRM: kernel-delivered even while
    # blocked inside a hung device call, and a normal signal death — the
    # parent's subprocess timeout (SIGKILL, which wedges the axon tunnel
    # mid-op) stays a last resort it should never reach.
    self_to = int(os.environ.get("PHOTON_BENCH_SELF_TIMEOUT", 0))
    if self_to > 0 and (a.probe or a.config):
        import signal

        signal.alarm(self_to)

    if a.probe:
        import jax

        print(json.dumps({"platform": jax.devices()[0].platform}))
        return
    if a.config:
        # Emergency brake for a live run: children execute THIS file fresh,
        # so touching this marker makes the (late-running, full-re-upload)
        # accelerator storage A/B yield its slot instead of pushing the
        # whole bench past an outer harness deadline that would destroy
        # every collected result.  Remove the marker to restore the A/B.
        if os.environ.get("PHOTON_BENCH_STORAGE") and \
                (a.platform or "") != "cpu" and \
                os.path.exists(os.path.join(_REPO,
                                            ".bench_skip_accel_storage_ab")):
            sys.stderr.write("storage A/B skipped: marker file present\n")
            sys.exit(7)
        scale = 1
        if (a.platform or "") == "cpu":
            scale = int(os.environ.get("PHOTON_BENCH_CPU_SCALE", 8))
        if a.ab_chain:
            run_glmix2_ab_chain(a.platform, scale)  # prints its own lines
            return
        got = RUNNERS[a.config](a.platform, scale)
        # post-result work (profiler capture) runs only after the numbers
        # are safely on stdout — the parent parses stdout even when the
        # child later times out, so a profiling wedge costs nothing
        after = got.pop("_profile_thunk", None)
        print(json.dumps(got))
        sys.stdout.flush()
        if after is not None:
            after()
        return

    # ---- orchestrator ----
    platform = probe_platform()
    scale = 1 if platform != "cpu" else int(
        os.environ.get("PHOTON_BENCH_CPU_SCALE", 8))
    names = [c.strip() for c in os.environ.get(
        "PHOTON_BENCH_CONFIGS", ",".join(ALL_CONFIGS)).split(",") if c.strip()]
    # Accelerator children pay a large, variable upload toll first (the axon
    # tunnel has been measured in the hundreds-of-KB/s; glmix2's full-scale
    # design is ~550MB ≈ 30min of transfer), so their timeout default is
    # roomier than the cpu fallback's.
    to = int(os.environ.get("PHOTON_BENCH_CONFIG_TIMEOUT",
                            2400 if platform == "cpu" else 4500))
    want_cpu_ref = os.environ.get("PHOTON_BENCH_CPU_REF", "1") != "0"

    # PHOTON_BENCH_AB=0 skips every A/B block: a recovery window on a flaky
    # accelerator should bank the missing headline configs first, not spend
    # the window re-uploading glmix2's dataset three more times.
    want_ab = os.environ.get("PHOTON_BENCH_AB", "1") != "0"

    configs = {}
    fused_failed = set()
    chain_done = False
    # every child of a cpu-fallback run gets the same platform override
    plat_args = ["--platform", "cpu"] if platform == "cpu" else []
    for name in names:
        if name == "glmix2" and want_ab and platform != "cpu" and \
                not os.environ.get("PHOTON_BENCH_IMPL"):
            # Accelerator A/Bs ride ONE child / ONE design upload
            # (run_glmix2_ab_chain): per-variant children would re-upload
            # the ~550MB design once each over the slow tunnel.  Every
            # variant line the child managed to emit is kept, so a wedge
            # costs only the un-run variants.
            lines = _subprocess_json_lines(
                ["--config", "glmix2", "--ab-chain"], timeout=to + 1800)
            by = {ln.pop("variant"): ln for ln in lines if "variant" in ln}
            fused = by.get("glmix2")
            host = by.get("glmix2_host")
            if fused and "error" not in fused:
                configs["glmix2"] = _entry_from("glmix2", fused, scale,
                                                want_cpu_ref)
                # fused-vs-host stays recorded data even when host failed
                configs["glmix2_host"] = (
                    _entry_from("glmix2", host, scale, want_cpu_ref)
                    if host and "error" not in host else
                    {"error": (host or {}).get(
                        "error", "not emitted (chain died earlier)")[-500:]})
            elif host and "error" not in host:
                sys.stderr.write("glmix2: fused failed in chain; host "
                                 "impl is the headline\n")
                fused_failed.add("glmix2")
                configs["glmix2"] = _entry_from("glmix2", host, scale,
                                                want_cpu_ref)
                configs["glmix2_fused"] = {
                    "error": (fused or {}).get("error", "not emitted")[-500:]}
            else:
                # chain yielded no usable measurement (e.g. child wedged and
                # died before any line): keep the old recovery property —
                # one fresh host-impl child gets a clean shot at a headline
                sys.stderr.write("glmix2: chain yielded nothing; retrying "
                                 "host loop in a fresh child\n")
                fused_failed.add("glmix2")
                env = os.environ.copy()
                env["PHOTON_BENCH_IMPL"] = "host"
                got = _subprocess_json(["--config", "glmix2"] + plat_args,
                                       timeout=to, env=env)
                configs["glmix2"] = (
                    _entry_from("glmix2", got, scale, want_cpu_ref)
                    if got else {"error": "failed or timed out"})
            xla = by.get("glmix2_xla")
            if xla is not None:
                configs["glmix2_xla"] = (
                    _entry_from("glmix2", xla, scale, want_cpu_ref)
                    if "error" not in xla else
                    {"error": xla["error"][-500:]})
            chain_done = True
            continue
        args = ["--config", name] + plat_args
        got = _subprocess_json(args, timeout=to)
        if got is None and name in ("glmix2", "glmix3") and \
                os.environ.get("PHOTON_BENCH_IMPL", "fused") == "fused":
            sys.stderr.write(f"{name}: fused failed; retrying host loop\n")
            fused_failed.add(name)
            env = os.environ.copy()
            env["PHOTON_BENCH_IMPL"] = "host"
            got = _subprocess_json(args, timeout=to, env=env)
        if got is None:
            configs[name] = {"error": "failed or timed out"}
            continue
        if got.get("fused_error"):
            # the child fell back to host IN-PROCESS: surface the fused
            # crash (child stderr is discarded on rc 0) and stop the A/B
            # block from burning a timeout re-confirming the failure
            fused_failed.add(name)
            _log_child_failure(
                f"{name}: fused impl failed in-process (host fallback "
                f"measured): {got['fused_error']}\n")
        configs[name] = _entry_from(name, got, scale, want_cpu_ref)

    # fused-vs-host A/B (EVERY backend, cpu included): the headline glmix2
    # measures the better impl per backend; the other one is recorded too so
    # the gap itself is data, not an unvalidated claim (VERDICT r2 weak #4).
    # (The accelerator chain above already banked host+xla in one child.)
    if want_ab and not chain_done and "value" in configs.get("glmix2", {}) and \
            not os.environ.get("PHOTON_BENCH_IMPL"):
        head_impl = configs["glmix2"].get("impl", "fused")
        alt = "host" if head_impl == "fused" else "fused"
        if alt == "fused" and "glmix2" in fused_failed:
            # the headline already observed fused failing — don't burn
            # another config timeout re-confirming it
            configs["glmix2_fused"] = {"error": "fused impl failed in headline run"}
        else:
            env = os.environ.copy()
            env["PHOTON_BENCH_IMPL"] = alt
            got = _subprocess_json(["--config", "glmix2"] + plat_args,
                                   timeout=to, env=env)
            configs[f"glmix2_{alt}"] = (
                _entry_from("glmix2", got, scale, want_cpu_ref) if got
                else {"error": "failed or timed out"})

    # A/B variants: pallas-fused vs plain-XLA objective (accelerator only —
    # there is no pallas path on cpu) and bf16 design storage (EVERY
    # backend: on cpu it documents the mixed-precision path's quality gate
    # and honest cost — software-emulated bf16 matmuls lose ~2.5x there,
    # while TPU MXUs take bf16 operands natively).  All variants reuse
    # glmix2's data/loop/baseline so the deltas are pure.
    if want_ab and "value" in configs.get("glmix2", {}):
        head_impl = configs["glmix2"].get("impl", "fused")
        variants = [("glmix2_bf16", {"PHOTON_BENCH_STORAGE": "bfloat16"})]
        if head_impl == "fused" and platform != "cpu" and not chain_done:
            # pallas-vs-XLA only makes sense on the impl that actually ran;
            # under the host-loop fallback the A/B would re-fail fused twice
            # (the accelerator chain banks this variant itself)
            variants.insert(0, ("glmix2_xla", {"PHOTON_GLM_DISABLE_PALLAS": "1"}))
        for vname, extra_env in variants:
            if "PHOTON_BENCH_STORAGE" in extra_env and platform != "cpu" and \
                    os.path.exists(os.path.join(
                        _REPO, ".bench_skip_accel_storage_ab")):
                # record the deliberate skip as a skip, not as a child crash
                configs[vname] = {
                    "skipped": "storage A/B brake marker present"}
                continue
            env = os.environ.copy()
            env["PHOTON_BENCH_IMPL"] = head_impl
            env.update(extra_env)
            got = _subprocess_json(["--config", "glmix2"] + plat_args,
                                   timeout=to, env=env)
            if got is None:
                configs[vname] = {"error": "failed or timed out"}
            else:
                configs[vname] = _entry_from("glmix2", got, scale, want_cpu_ref)
                if vname == "glmix2_bf16":
                    # bf16 storage keeps the pallas path on TPU (kernels
                    # take storage-width MXU operands, f32 accumulation) —
                    # compare against the f32 pallas headline for the pure
                    # storage-width delta.  On cpu there is no pallas path
                    # and bf16 matmuls are software-emulated.
                    configs[vname]["note"] = (
                        "software bf16 on cpu; compare vs glmix2 — TPU MXUs "
                        "take bf16 natively" if platform == "cpu" else
                        "bf16-storage pallas kernels; compare vs the f32 "
                        "glmix2 headline (same impl)")

    # headline: config #3 (same metric as round 1), else first success —
    # with the metric RE-LABELED to the substituted config so a fallback
    # never presents another config's number as the GLMix headline
    head = configs.get("glmix2")
    head_name = "glmix2"
    if not head or "value" not in head:
        head_name, head = next(
            ((n, c) for n, c in configs.items() if "value" in c),
            ("glmix2", None))
    line = {
        "metric": ("glmix_2coord_examples_per_sec_per_chip"
                   if head_name == "glmix2" else
                   f"{head_name}_throughput (glmix2 headline unavailable)"),
        "value": head["value"] if head else 0.0,
        "unit": head["unit"] if head else "examples/sec/chip",
        "vs_baseline": head.get("vs_baseline") if head else None,
        "backend": platform,
        "scale": scale,
        "configs": configs,
    }
    if platform == "cpu":
        # a CPU fallback is a statement about the TUNNEL, not the framework:
        # point the reader at the banked accelerator evidence so one sick
        # window at round end cannot hide a healthy window's measurements
        evidence = _tpu_evidence_pointer()
        if evidence:
            line["tpu_evidence"] = evidence
    print(json.dumps(line))


if __name__ == "__main__":
    main()
