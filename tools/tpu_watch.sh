#!/bin/bash
# Tunnel recovery watcher: probe the axon backend in a fresh, self-timing-out
# process every INTERVAL seconds; on the first successful probe, immediately
# run the (failure-logging, roomy-timeout) bench to bank the configs the
# first TPU window lost (glmix2/glmix3/gp_tune + A/B variants), then exit.
# Probe deaths are SIGALRM self-timeouts — never a parent SIGKILL mid-RPC.
set -u
cd "$(dirname "$0")/.."
INTERVAL=${PHOTON_WATCH_INTERVAL:-900}
# Hard stop (epoch seconds): the round driver runs its own bench at round
# end, and two concurrent axon clients hang each other — the watcher must
# be out of the way well before then.  No deadline when unset.
DEADLINE=${PHOTON_WATCH_DEADLINE:-0}
LOG=.tpu_watch.log
echo "[$(date -u +%H:%M:%S)] watcher start (deadline=$DEADLINE)" >> "$LOG"
while true; do
  if [ "$DEADLINE" -gt 0 ] && [ "$(date -u +%s)" -ge "$DEADLINE" ]; then
    echo "[$(date -u +%H:%M:%S)] deadline reached; watcher exits" >> "$LOG"
    exit 0
  fi
  out=$(python - <<'EOF' 2>/dev/null
import signal
signal.alarm(120)
import jax
print(jax.devices()[0].platform)
EOF
)
  echo "[$(date -u +%H:%M:%S)] probe: ${out:-FAIL}" >> "$LOG"
  if [ "$out" = "tpu" ]; then
    # Bank the LOST configs first, with no A/B re-uploads — the recovery
    # window may be short; a full bench (A/Bs re-upload glmix2's ~550MB
    # dataset up to three more times) follows only if this pass lands.
    echo "[$(date -u +%H:%M:%S)] tunnel recovered -> lost-config bench" >> "$LOG"
    PHOTON_BENCH_CONFIGS=glmix2,glmix3,gp_tune PHOTON_BENCH_AB=0 \
      python bench.py > TPU_BENCH_RETRY.json 2>> "$LOG"
    rc=$?
    echo "[$(date -u +%H:%M:%S)] lost-config bench rc=$rc -> TPU_BENCH_RETRY.json" >> "$LOG"
    if [ "$rc" = "0" ]; then
      # full checklist: pallas non-interpret parity (incl. the bf16 storage
      # case) + the full bench with A/B chain AND the chip-scale glmix_chip
      # (its ~200MB upload belongs here, not in the minimal lost-config
      # pass above — a window closing mid-upload must not cost the three
      # headline configs) -> TPU_CHECKLIST.json
      echo "[$(date -u +%H:%M:%S)] full checklist (pallas + bench A/Bs)" >> "$LOG"
      python tools/tpu_checklist.py >> "$LOG" 2>&1
      echo "[$(date -u +%H:%M:%S)] checklist rc=$? -> TPU_CHECKLIST.json" >> "$LOG"
    fi
    exit 0
  fi
  sleep "$INTERVAL"
done
