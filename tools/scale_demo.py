"""At-scale end-to-end demo: a ~1e7-feature vocabulary through the WHOLE
framework stack — the reference's reason to exist ("hundreds of billions of
coefficients ... within Spark's framework", /root/reference/README.md:56),
exercised here at the single-machine scale this image allows:

    1. synthetic sparse Poisson Avro data (vocabulary ~1e7 distinct features)
    2. cli/index.py --format store   -> C++ mmap open-addressing index store
    3. sparse reader (native columnar Avro decoder) -> row-padded COO shard
    4. feature-sharded fixed-effect TRON solve over a (data, feature) mesh
       (parallel/fixed.ShardSparseObjective — w blocked across devices)
    5. model save (sparse NTV triples through the store-backed index map)

Prints one JSON line per stage {stage, seconds, ...} and a final summary with
peak RSS and device-array bytes.  Run:

    python tools/scale_demo.py                  # full scale (~10M features)
    python tools/scale_demo.py --rows 20000 --vocab 100000   # smoke

The driver-recorded evidence for VERDICT r1 weak #6 lives in SCALE_DEMO.md.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def stage(name, t0, **kw):
    rec = {"stage": name, "seconds": round(time.perf_counter() - t0, 2), **kw}
    print(json.dumps(rec), flush=True)
    return rec


def gen_records(rows: int, vocab: int, k: int, seed: int = 7):
    """Streaming generator of sparse Poisson TrainingExampleAvro records."""
    rng = np.random.default_rng(seed)
    # ground-truth weights on a hashed subspace so y correlates with x
    w_hash = (rng.normal(size=4096) * 0.05).astype(np.float64)
    for i in range(rows):
        js = rng.choice(vocab, size=k, replace=False)
        vs = rng.exponential(0.5, size=k)
        z = float(np.clip((vs * w_hash[js % 4096]).sum(), -4.0, 4.0))
        y = float(rng.poisson(np.exp(z)))
        yield {
            "uid": i,
            "response": y,
            "label": None,
            "features": [{"name": f"t{j}", "term": "", "value": float(v)}
                         for j, v in zip(js, vs)],
            "weight": None,
            "offset": None,
            "metadataMap": {},
        }


def re_demo(args):
    """Entity-axis scale demo (VERDICT r3 missing #3): ~1M random-effect
    ENTITIES through sparse bucketing → capacity-class bin-packing → one
    vmapped training sweep → total scoring.  The reference's heaviest
    machinery exists precisely for this regime (per-entity problems RDD,
    RandomEffectOptimizationProblem.scala:42-182; balanced partitioner,
    RandomEffectDatasetPartitioner.scala:30-171).

    The memory table proves the sparse-bucket claim: device-resident bucket
    design blocks are [lanes, cap, d_observed] — HBM ∝ observed columns per
    entity, NOT the vocabulary width a densified [lanes, cap, d_full]
    layout would cost."""
    import jax

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.data import GameData, SparseShard
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    e, per, k, d = args.re_entities, args.re_rows_per_entity, 6, args.re_dim
    n = e * per
    records = []

    # 1. synthetic per-entity sparse logistic data, generated in chunks
    t0 = time.perf_counter()
    rng = np.random.default_rng(11)
    idx = np.empty((n, k), np.int32)
    vals = np.empty((n, k), np.float32)
    y = np.empty(n, np.float32)
    ch = 1 << 20
    for lo in range(0, n, ch):
        hi = min(lo + ch, n)
        m = hi - lo
        idx[lo:hi] = rng.integers(0, d, size=(m, k))
        vals[lo:hi] = rng.normal(size=(m, k))
        # per-entity effect: a cheap hash of the entity id steers the label
        eid = (np.arange(lo, hi) // per).astype(np.int64)
        z = vals[lo:hi, 0] * (((eid * 2654435761) % 97) / 48.0 - 1.0)
        y[lo:hi] = (rng.random(m) < 1.0 / (1.0 + np.exp(-z))).astype(
            np.float32)
    uids = np.repeat(np.arange(e, dtype=np.int64), per)
    records.append(stage("re_generate", t0, entities=e, rows=n, nnz=n * k,
                         vocab=d))

    # 2. coordinate construction: per-entity compaction + capacity-class
    # bin-packing + device layout
    t0 = time.perf_counter()
    gd = GameData(y=y, features={"u": SparseShard(indices=idx, values=vals,
                                                  dim=d)},
                  id_tags={"userId": uids})
    cfg = RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                             solver=SolverConfig(max_iters=15),
                             reg=Regularization(l2=1.0))
    coord = build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION)
    hist = {}
    bucket_bytes = 0
    dense_twin_bytes = 0
    for b in coord.buckets.buckets:
        cap, lanes, d_c = b.x.shape[1], b.x.shape[0], b.x.shape[2]
        hist[f"cap{cap}xd{d_c}"] = hist.get(f"cap{cap}xd{d_c}", 0) + lanes
        bucket_bytes += b.x.nbytes + b.y.nbytes + b.weight.nbytes
        dense_twin_bytes += lanes * cap * d * 4
    records.append(stage(
        "re_bucket_binpack", t0, bucket_classes=len(coord.buckets.buckets),
        lane_histogram=hist,
        bucket_design_mb=round(bucket_bytes / 2**20, 1),
        densified_twin_mb=round(dense_twin_bytes / 2**20, 1),
        compaction_factor=round(dense_twin_bytes / max(bucket_bytes, 1), 1)))

    # 3. ONE training sweep: every entity's problem solved by the vmapped
    # per-capacity-class programs
    t0 = time.perf_counter()
    model, _res = coord.update(np.zeros(n, np.float32))
    records.append(stage("re_train_sweep", t0,
                         entities_trained=len(model.slot_of),
                         w_stack_mb=round(model.w_stack.nbytes / 2**20, 1)))
    assert len(model.slot_of) == e
    assert np.all(np.isfinite(model.w_stack))

    # compact published container (models/game.CompactRandomEffectModel):
    # the wide-vocabulary twin — memory ∝ observed columns per entity
    t0 = time.perf_counter()
    compact = model.to_compact()
    records.append(stage(
        "re_compact_model", t0,
        compact_mb=round((compact.indices.nbytes
                          + compact.values.nbytes) / 2**20, 1),
        dense_mb=round(model.w_stack.nbytes / 2**20, 1),
        per_entity_capacity=int(compact.indices.shape[1])))

    # 4. total scoring (active + passive union)
    t0 = time.perf_counter()
    scores = coord.score(model)
    assert scores.shape == (n,) and np.all(np.isfinite(scores))
    records.append(stage("re_score_total", t0, rows=n))

    summary = {
        "stage": "summary",
        "backend": jax.devices()[0].platform,
        "entities": e, "rows": n, "vocab": d,
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "total_seconds": round(sum(r["seconds"] for r in records), 2),
    }
    print(json.dumps(summary), flush=True)


def combined_demo(args):
    """BOTH scale axes in ONE fused sweep (VERDICT r4 #8 — the reference's
    "hundreds of billions of coefficients" claim multiplies the two axes,
    README.md:56): >=1M random-effect ENTITIES and a WIDE feature-sharded
    sparse fixed shard trained together by the single scanned descent
    program (game/fused.FusedSweep over a (data, entity, feature) mesh;
    the fixed w is blocked across the feature axis — ShardSparseObjective —
    while the entity lanes shard over the whole mesh)."""
    import jax

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game.config import FixedEffectConfig, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.data import GameData, SparseShard
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import TaskType

    e = args.re_entities or 1_048_576
    per = args.re_rows_per_entity
    n = e * per
    dg, kg = args.vocab_fixed, 8
    du, ku = args.re_dim, 6
    records = []

    # 1. synthetic rows carrying BOTH effects (chunked: no [n, d] dense ever)
    t0 = time.perf_counter()
    rng = np.random.default_rng(23)
    idx_g = np.empty((n, kg), np.int32)
    vals_g = np.empty((n, kg), np.float32)
    idx_u = np.empty((n, ku), np.int32)
    vals_u = np.empty((n, ku), np.float32)
    y = np.empty(n, np.float32)
    w_hash = (rng.normal(size=4096) * 0.3).astype(np.float64)
    ch = 1 << 20
    for lo in range(0, n, ch):
        hi = min(lo + ch, n)
        m = hi - lo
        idx_g[lo:hi] = rng.integers(0, dg, size=(m, kg))
        vals_g[lo:hi] = rng.normal(size=(m, kg))
        idx_u[lo:hi] = rng.integers(0, du, size=(m, ku))
        vals_u[lo:hi] = rng.normal(size=(m, ku))
        eid = (np.arange(lo, hi) // per).astype(np.int64)
        z = (np.einsum("nk,nk->n", vals_g[lo:hi].astype(np.float64),
                       w_hash[idx_g[lo:hi] % 4096])
             + vals_u[lo:hi, 0] * (((eid * 2654435761) % 97) / 48.0 - 1.0))
        y[lo:hi] = (rng.random(m) < 1.0 / (1.0 + np.exp(-np.clip(z, -8, 8))))
    uids = np.repeat(np.arange(e, dtype=np.int64), per)
    records.append(stage("combined_generate", t0, entities=e, rows=n,
                         fixed_vocab=dg, fixed_nnz=n * kg,
                         re_vocab=du, re_nnz=n * ku))

    # 2. coordinates over a 3-axis mesh: wide sparse fixed (w feature-
    # blocked) + per-entity compact buckets (entity lanes over all devices).
    # 4 devices, not 8: XLA-CPU collectives rendezvous with a hard 40s
    # arrival window, and this image's ONE core time-slices every virtual
    # device thread — at 8 devices the heavy per-step sparse scatters starve
    # paired participants past the window (rendezvous.cc termination).  The
    # (entity=2, feature=2) axes still exercise both sharded directions.
    t0 = time.perf_counter()
    mesh = make_mesh(n_data=1, n_entity=2, n_feature=2)
    gd = GameData(
        y=y,
        features={"g": SparseShard(indices=idx_g, values=vals_g, dim=dg),
                  "u": SparseShard(indices=idx_u, values=vals_u, dim=du)},
        id_tags={"userId": uids})
    solver = SolverConfig(max_iters=args.max_iter, tolerance=1e-6)
    cfgs = {
        "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                   reg=Regularization(l2=1.0),
                                   feature_sharded=True),
        "per-user": RandomEffectConfig(random_effect_type="userId",
                                       feature_shard="u", solver=solver,
                                       reg=Regularization(l2=1.0)),
    }
    coords = {cid: build_coordinate(cid, gd, cfg,
                                    TaskType.LOGISTIC_REGRESSION, mesh=mesh)
              for cid, cfg in cfgs.items()}
    re_coord = coords["per-user"]
    bucket_bytes = sum(b.x.nbytes + b.y.nbytes + b.weight.nbytes
                       for b in re_coord.buckets.buckets)
    dense_twin = sum(b.x.shape[0] * b.x.shape[1] * du * 4
                     for b in re_coord.buckets.buckets)
    coo_bytes = idx_g.nbytes + vals_g.nbytes + idx_u.nbytes + vals_u.nbytes
    records.append(stage(
        "combined_build", t0,
        bucket_classes=len(re_coord.buckets.buckets),
        re_bucket_design_mb=round(bucket_bytes / 2**20, 1),
        re_densified_twin_mb=round(dense_twin / 2**20, 1),
        fixed_coo_mb=round((idx_g.nbytes + vals_g.nbytes) / 2**20, 1),
        fixed_w_block_mb_per_device=round(
            dg * 4 / mesh.shape["feature"] / 2**20, 1),
        mesh=dict(mesh.shape)))

    # 3. ONE fused sweep trains BOTH coordinates (residual descent inside a
    # single jitted program)
    t0 = time.perf_counter()
    model, scores = FusedSweep(coords, num_iterations=args.outer).run()
    wg = np.asarray(model["fixed"].coefficients.means)
    wre = model["per-user"].w_stack
    assert wg.shape == (dg,) and np.all(np.isfinite(wg))
    assert len(model["per-user"].slot_of) == e
    assert np.all(np.isfinite(np.asarray(wre)))
    assert all(np.all(np.isfinite(np.asarray(s))) for s in scores.values())
    records.append(stage("combined_fused_sweep", t0,
                         outer_iterations=args.outer,
                         fixed_nonzero=int(np.count_nonzero(wg)),
                         entities_trained=len(model["per-user"].slot_of)))

    summary = {
        "stage": "summary",
        "backend": jax.devices()[0].platform,
        "entities": e, "rows": n,
        "fixed_vocab": dg, "re_vocab": du,
        # resident device bytes: both COO shards + per-example vectors +
        # entity bucket blocks + the feature-blocked fixed w (+grad twin)
        "device_mb_estimate": round(
            (coo_bytes + n * 12 + bucket_bytes + 2 * dg * 4) / 2**20, 1),
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "total_seconds": round(sum(r["seconds"] for r in records), 2),
    }
    print(json.dumps(summary), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=409_600)
    ap.add_argument("--vocab", type=int, default=16_777_216)  # 2^24 id space
    ap.add_argument("--k", type=int, default=32)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--mesh", default="data=2,feature=4")
    ap.add_argument("--platform", default="cpu8",
                    help="'cpu8' (default): force an 8-virtual-device CPU "
                         "backend — the multi-chip stand-in this image "
                         "supports; 'native': whatever jax picks (a real "
                         "multi-chip TPU mesh when one exists)")
    ap.add_argument("--re-entities", type=int, default=0,
                    help="run the ENTITY-axis demo instead: this many "
                         "random-effect entities (1048576 = the 1M-entity "
                         "evidence run) through sparse bucketing, one "
                         "vmapped sweep and total scoring")
    ap.add_argument("--re-rows-per-entity", type=int, default=4)
    ap.add_argument("--re-dim", type=int, default=256)
    ap.add_argument("--combined", action="store_true",
                    help="run BOTH axes in one fused sweep: --re-entities "
                         "(default 1048576) per-entity problems + a wide "
                         "(--vocab-fixed) feature-sharded sparse fixed "
                         "effect, trained together (VERDICT r4 #8)")
    ap.add_argument("--vocab-fixed", type=int, default=4_194_304)
    ap.add_argument("--max-iter", type=int, default=10)
    ap.add_argument("--outer", type=int, default=2)
    args = ap.parse_args()

    if args.platform == "cpu8":
        # must land before the first device use; jax is pre-imported in this
        # image, so the env var alone is ignored — set the config too
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.combined:
        combined_demo(args)
        return
    if args.re_entities:
        re_demo(args)
        return

    work = args.workdir or tempfile.mkdtemp(prefix="photon_scale_")
    os.makedirs(work, exist_ok=True)
    records = []

    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE

    # 1. data
    t0 = time.perf_counter()
    data_path = os.path.join(work, "train.avro")
    n = avro_io.write_container(data_path, TRAINING_EXAMPLE,
                                gen_records(args.rows, args.vocab, args.k))
    records.append(stage("generate+write_avro", t0, rows=n,
                         nnz=n * args.k,
                         file_mb=round(os.path.getsize(data_path) / 2**20, 1)))

    # 2. feature indexing -> C++ mmap store (PHIDX002)
    from photon_ml_tpu.cli import index as index_cli

    t0 = time.perf_counter()
    idx_dir = os.path.join(work, "idx")
    rc = index_cli.run(["--data", data_path, "--feature-shards", "all",
                        "--output-dir", idx_dir, "--format", "store"])
    assert rc == 0, "index driver failed"
    from photon_ml_tpu.data.index_map import load_index

    imap = load_index(os.path.join(idx_dir, "all.phidx"))
    store_mb = sum(os.path.getsize(os.path.join(idx_dir, f))
                   for f in os.listdir(idx_dir)) / 2**20
    records.append(stage("index_store_build", t0, distinct_features=imap.size,
                         store_mb=round(store_mb, 1)))

    # 3+4+5. sparse read -> feature-sharded TRON fit -> model save, all
    # through the train driver (the user-facing path)
    from photon_ml_tpu.cli import train as train_cli

    t0 = time.perf_counter()
    out = os.path.join(work, "model")
    rc = train_cli.run([
        "--train-data", data_path,
        "--task", "POISSON_REGRESSION",
        "--feature-shards", "all",
        "--index-map-dir", idx_dir,
        "--sparse-threshold", "1000",
        "--mesh", args.mesh,
        "--coordinate",
        "name=global,feature.shard=all,optimizer=TRON,max.iter=15,"
        "tolerance=1e-5,reg.weights=1.0,feature.sharded=true",
        "--output-dir", out,
    ])
    assert rc == 0, "train driver failed"
    records.append(stage("sparse_read+feature_sharded_tron_fit+save", t0))

    # sanity: the saved model reloads through the same store-backed map and
    # carries finite coefficients
    t0 = time.perf_counter()
    from photon_ml_tpu.storage.model_io import load_game_model

    model, _ = load_game_model(os.path.join(out, "best"), {"all": imap})
    w = model["global"].coefficients.means
    assert w.shape == (imap.size,) and np.all(np.isfinite(w))
    nz = int(np.count_nonzero(w))
    records.append(stage("model_reload_check", t0, nonzero_coeffs=nz))

    import jax

    summary = {
        "stage": "summary",
        "backend": jax.devices()[0].platform,
        "rows": args.rows,
        "vocab_id_space": args.vocab,
        "distinct_features": imap.size,
        "design_nnz": args.rows * args.k,
        # device bytes of the resident problem: COO (idx i32 + val f32) +
        # labels/offset/weight + the blocked coefficient vector
        "device_mb_estimate": round(
            (args.rows * args.k * 8 + args.rows * 12 + imap.size * 4) / 2**20, 1),
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "total_seconds": round(sum(r["seconds"] for r in records), 2),
        "workdir": work,
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
