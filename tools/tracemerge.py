"""tracemerge CLI — merge per-process photonscope exports into one timeline.

Usage:
    python -m tools.tracemerge owner.json replica.json front.json \
        --out merged.json
    python -m tools.tracemerge *.json --reference owner
    python -m tools.tracemerge flight-*.json --flight --out merged.json

Each input is a Chrome ``trace_event`` export from one process (``{"cmd":
"trace"}``, ``--trace-out``, or a flight-recorder dump with ``--flight``,
which unwraps the ``"trace"`` member of the dump payload).  The merge
(``photon_ml_tpu.obs.pulse.merge``) aligns every process onto one clock
using the NTP-style offsets the exports carry in ``otherData.clock``,
re-numbers pids so restarts cannot collide, and emits one Perfetto-loadable
timeline.  ``--reference`` pins the clock every other process is shifted
onto (default: auto-detect the label peers measured against — usually the
owner or frontend).

The summary printed to stderr lists the alignment (per-process shift, in
ns) and the distinct request trace ids found, so "did these three
processes actually see the same request?" is answered without opening
Perfetto at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # direct `python tools/tracemerge.py` runs
    sys.path.insert(0, _REPO_ROOT)

from photon_ml_tpu.obs.pulse.merge import (load_trace,  # noqa: E402
                                           merge_traces, spans_by_trace)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tracemerge",
        description="Merge per-process photonscope Chrome traces into one "
                    "clock-aligned Perfetto timeline")
    p.add_argument("traces", nargs="+", metavar="TRACE.json",
                   help="per-process Chrome trace exports")
    p.add_argument("--out", default="-", metavar="FILE",
                   help="merged trace destination ('-' = stdout)")
    p.add_argument("--reference", default=None, metavar="LABEL",
                   help="process label whose clock the timeline uses "
                        "(default: auto-detect)")
    p.add_argument("--flight", action="store_true",
                   help="inputs are flight-recorder dumps: unwrap the "
                        "'trace' member of each payload")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the alignment/trace-id summary on stderr")
    return p


def run(argv) -> int:
    args = _parser().parse_args(argv)
    traces = []
    for path in args.traces:
        try:
            t = load_trace(path)
        except (OSError, ValueError) as e:
            print(f"tracemerge: cannot read {path}: {e}", file=sys.stderr)
            return 2
        if args.flight:
            t = t.get("trace")
            if not isinstance(t, dict):
                print(f"tracemerge: {path} is not a flight dump "
                      f"(no 'trace' member)", file=sys.stderr)
                return 2
        traces.append(t)
    merged = merge_traces(traces, reference=args.reference)
    # a disconnected clock-offset graph degrades to per-component local
    # references — always worth a warning, even under --quiet: timing is
    # not comparable across the components the merge just interleaved
    for w in merged["otherData"].get("clock_warnings", ()):
        print(f"tracemerge: warning: {w}", file=sys.stderr)
    text = json.dumps(merged)
    if args.out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(args.out, "w") as f:
            f.write(text)
    if not args.quiet:
        other = merged["otherData"]
        print(f"tracemerge: {len(traces)} process(es), reference "
              f"{other['reference']!r}", file=sys.stderr)
        for pid, label in sorted(other["processes"].items(),
                                 key=lambda e: int(e[0])):
            shift = other["offsets_ns"].get(label, 0)
            print(f"  pid {pid}: {label} (shift {shift:+d} ns)",
                  file=sys.stderr)
        by_trace = spans_by_trace(merged)
        print(f"tracemerge: {len(by_trace)} trace id(s) across "
              f"{sum(len(v) for v in by_trace.values())} event(s)",
              file=sys.stderr)
        for tid, evs in sorted(by_trace.items()):
            pids = sorted({ev.get("pid") for ev in evs})
            print(f"  {tid}: {len(evs)} event(s) over pid(s) "
                  f"{','.join(map(str, pids))}", file=sys.stderr)
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
