"""Single-process TPU experiment bank for slow-tunnel iteration.

The axon tunnel moves bytes at roughly hundreds of KB/s (measured by the
bandwidth stage below), so every fresh process pays minutes-to-tens-of-minutes
of upload before the first useful result.  bench.py's one-subprocess-per-config
layout is right for the official artifact (wedge isolation) but hopeless for
iterating: this script instead runs MANY experiments in ONE process so each
dataset uploads once, and appends every stage's result to TPU_EXPERIMENTS.json
as it lands (a later hang can't destroy earlier evidence).

  python tools/tpu_experiment.py              # all stages, scale 8
  PHOTON_EXP_SCALE=4 python tools/tpu_experiment.py bw glmix
  PHOTON_EXP_STAGES=bw,glmix,a1a python tools/tpu_experiment.py

Stages:
  bw     — device_put bandwidth (8MB + 64MB) and tiny-matmul dispatch latency
  a1a    — BASELINE #1 solve, timed (small upload; quick signal the chip works)
  glmix  — glmix2 at 1/PHOTON_EXP_SCALE dataset scale: upload timed, then
           fused vs host vs fused-without-pallas vs bf16-storage, each
           compile-timed and run-timed separately, with full tracebacks on
           failure (the checklist's full-scale fused crash left no message)

A global SIGALRM (PHOTON_EXP_TIMEOUT, default 5400s) uses the DEFAULT signal
action — process death, kernel-delivered even inside a hung device RPC — so a
wedged tunnel costs the timeout, never a hang, and never a parent-side SIGKILL
(which is what wedges the tunnel for everyone else).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
import traceback

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
_OUT = os.path.join(_REPO, "TPU_EXPERIMENTS.json")

signal.alarm(int(os.environ.get("PHOTON_EXP_TIMEOUT", 5400)))

_RESULTS: dict = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                  "scale": int(os.environ.get("PHOTON_EXP_SCALE", 8)),
                  "stages": {}}


def _save() -> None:
    tmp = _OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(_RESULTS, f, indent=1)
    os.replace(tmp, _OUT)


def _stage(name):
    """Decorator: run stage, record result-or-traceback, persist, continue."""
    def wrap(fn):
        def run():
            t0 = time.perf_counter()
            print(f"== {name} ==", flush=True)
            try:
                out = fn()
                out = out if isinstance(out, dict) else {"ok": True}
            except Exception:
                out = {"error": traceback.format_exc()[-4000:]}
            out["stage_sec"] = round(time.perf_counter() - t0, 2)
            _RESULTS["stages"][name] = out
            _save()
            print(json.dumps({name: out})[:600], flush=True)
        return run
    return wrap


def _timed(fn, *args):
    import jax

    t0 = time.perf_counter()
    r = fn(*args)
    jax.block_until_ready(r)
    return r, time.perf_counter() - t0


def _median_time(fn, repeats=3):
    import numpy as np

    ts = []
    for _ in range(repeats):
        _, dt = _timed(fn)
        ts.append(dt)
    return float(np.median(ts)), [round(t, 4) for t in ts]


@_stage("bw")
def stage_bw():
    import jax
    import numpy as np

    platform = jax.devices()[0].platform
    out = {"platform": platform}
    for mb in (8, 64):
        a = np.random.default_rng(0).random((mb * 1024 * 1024 // 4,),
                                            dtype=np.float32)
        _, dt = _timed(jax.device_put, a)
        out[f"upload_{mb}mb_sec"] = round(dt, 2)
        out[f"upload_{mb}mb_mbps"] = round(mb / dt, 2)
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    _, compile_dt = _timed(f, x)
    med, _ = _median_time(lambda: f(x), repeats=10)
    out["tiny_matmul_compile_sec"] = round(compile_dt, 2)
    out["tiny_matmul_dispatch_sec"] = round(med, 4)
    return out


@_stage("a1a")
def stage_a1a():
    sys.path.insert(0, _REPO)
    import bench

    return bench.run_a1a(None, 1)


def _glmix_data_coords(scale: int):
    import bench

    data = bench.synth_glmix(scale, three=False)
    return data, bench._glmix_coords(data, three=False)


@_stage("glmix")
def stage_glmix():
    import jax
    import numpy as np

    import bench

    scale = _RESULTS["scale"]
    out = {"scale": scale}

    t0 = time.perf_counter()
    data = bench.synth_glmix(scale, three=False)
    out["synth_sec"] = round(time.perf_counter() - t0, 2)
    n = len(data["y"])
    mb = sum(v.nbytes for v in data.values()) / 1e6
    out["n"] = n
    out["data_mb"] = round(mb, 1)

    t0 = time.perf_counter()
    dev = {k: jax.device_put(v) for k, v in data.items()}
    jax.block_until_ready(dev)
    dt = time.perf_counter() - t0
    out["upload_sec"] = round(dt, 2)
    out["upload_mbps"] = round(mb / dt, 2)
    del dev  # timing-only upload: free the HBM copy before the variants stage
    data = {k: np.asarray(v) for k, v in data.items()}  # coords re-stage

    variants = [("fused", {}), ("fused_xla", {"PHOTON_GLM_DISABLE_PALLAS": "1"}),
                ("host", {}), ("bf16", {"PHOTON_BENCH_STORAGE": "bfloat16"})]
    for vname, env in variants:
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            coords = bench._glmix_coords(data, three=False)
            v = {}
            if vname == "host":
                from photon_ml_tpu.game import CoordinateDescent

                driver = CoordinateDescent(coords, num_iterations=bench.OUTER)
                t0 = time.perf_counter()
                driver.run()
                v["warmup_sec"] = round(time.perf_counter() - t0, 2)
                med, ts = _median_time(lambda: driver.run(), repeats=3)
            else:
                from photon_ml_tpu.game.fused import FusedSweep

                sweep = FusedSweep(coords, num_iterations=bench.OUTER)
                t0 = time.perf_counter()
                sweep.run()
                v["warmup_sec"] = round(time.perf_counter() - t0, 2)
                med, ts = _median_time(lambda: sweep.run(), repeats=3)
            v["median_sec"] = round(med, 4)
            v["times"] = ts
            v["examples_per_sec"] = round(n * bench.OUTER / med, 1)
            out[vname] = v
        except Exception:
            out[vname] = {"error": traceback.format_exc()[-4000:]}
        finally:
            for k, val in old.items():
                if val is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = val
        _RESULTS["stages"]["glmix"] = out
        _save()
        print(json.dumps({vname: out.get(vname)})[:400], flush=True)
    return out


STAGES = {"bw": stage_bw, "a1a": stage_a1a, "glmix": stage_glmix}


def main() -> int:
    names = sys.argv[1:] or [s.strip() for s in os.environ.get(
        "PHOTON_EXP_STAGES", "bw,a1a,glmix").split(",") if s.strip()]
    for name in names:
        STAGES[name]()
    print(f"done -> {_OUT}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
