"""One-command TPU capture: everything the perf story needs from a real chip.

The axon tunnel has been unavailable for whole build rounds at a time
(VERDICT r2 "Missing #1"), so the moment it answers, ONE command must bank
all accelerator evidence before anything can wedge it again:

  python tools/tpu_checklist.py            # probe -> pallas parity -> bench

Stages (each skipped gracefully when no TPU answers):
  1. probe      — subprocess jax.devices() with a hard timeout (a wedged
                  tunnel costs the timeout, never a hang; the client process
                  always exits cleanly — killing a mid-op TPU process is
                  what wedges the tunnel in the first place).
  2. pallas     — NON-interpret parity of ops/fused_glm's kernels vs the XLA
                  objective math on the real chip (the tests force CPU +
                  interpret mode; this is the only place the kernels run for
                  real).  Run in a subprocess for the same wedge-isolation.
  3. bench      — python bench.py (all five BASELINE configs; on a non-cpu
                  backend it auto-runs the pallas-off and bf16-storage A/B
                  variants and the fused-vs-host A/B).

In-progress state lands in TPU_CHECKLIST.partial.json (refreshed
atomically after every stage so a later wedge can't destroy THIS run's
earlier stages); the canonical TPU_CHECKLIST.json is only replaced at the
end of a COMPLETE run whose bench stage parsed without error.  A run that
dies mid-bench — or finds no accelerator at all — therefore never clobbers
the last banked full-evidence artifact (learned 2026-08-02: a
degraded-window rerun overwrote the banked pass at start and had to be
restored from git).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OUT = os.path.join(_REPO, "TPU_CHECKLIST.json")
_PARTIAL = _OUT.replace(".json", ".partial.json")

_PROBE_SRC = """
import jax
print(jax.devices()[0].platform)
"""

# Runs on the REAL backend (no platform override): builds LANE-aligned f32
# batches, compares the pallas kernels (interpret=False) against the plain
# XLA objective math, prints one JSON line.
_PALLAS_SRC = """
import json
import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.batch import dense_batch
from photon_ml_tpu.core.losses import logistic_loss, poisson_loss, squared_loss
from photon_ml_tpu.ops.fused_glm import (eligible, fused_hvp,
                                         fused_value_and_grad)

platform = jax.devices()[0].platform
out = {"platform": platform, "cases": []}
rng = np.random.default_rng(0)
for name, loss in (("logistic", logistic_loss), ("squared", squared_loss),
                   ("poisson", poisson_loss)):
    n, d = 4096, 256  # LANE-aligned on both axes
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    if name == "poisson":
        y = rng.poisson(1.5, size=n).astype(np.float32)
    b = dense_batch(x, y, offset=rng.normal(size=n).astype(np.float32) * 0.1,
                    weight=(rng.random(n).astype(np.float32) + 0.5))
    w = (rng.normal(size=d) * 0.05).astype(np.float32)
    v = rng.normal(size=d).astype(np.float32)
    assert eligible(b), "checklist batch must be pallas-eligible"

    # pallas, REAL kernels
    val_p, g_p, r_p = fused_value_and_grad(loss, jnp.asarray(w), b)
    hv_p, q_p = fused_hvp(loss, jnp.asarray(w), jnp.asarray(v), b)

    # plain XLA twin
    z = b.x @ w + b.offset
    l, dl, d2l = loss.loss(z, b.y), loss.d1(z, b.y), loss.d2(z, b.y)
    wt = b.weight
    val_x = jnp.sum(wt * l)
    r = wt * dl
    g_x, rsum_x = b.x.T @ r, jnp.sum(r)
    q = wt * d2l * (b.x @ v)
    hv_x, qsum_x = b.x.T @ q, jnp.sum(q)

    def rel(a, bb):
        a, bb = np.asarray(a, np.float64), np.asarray(bb, np.float64)
        return float(np.max(np.abs(a - bb)) / max(1e-12, np.max(np.abs(bb))))

    case = {"loss": name,
            "value_rel": rel(val_p, val_x), "grad_rel": rel(g_p, g_x),
            "rsum_rel": rel(r_p, rsum_x), "hv_rel": rel(hv_p, hv_x),
            "qsum_rel": rel(q_p, qsum_x)}
    case["pass"] = all(case[k] < 2e-4 for k in
                       ("value_rel", "grad_rel", "rsum_rel", "hv_rel",
                        "qsum_rel"))
    out["cases"].append(case)

# bf16 storage: kernels take storage-width MXU operands with f32
# accumulation; parity vs the XLA mixed twin (same operand widths),
# tolerances at bf16 scale.
n, d = 4096, 256
x = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.random(n) > 0.5).astype(np.float32)
b = dense_batch(x, y, weight=(rng.random(n).astype(np.float32) + 0.5))
b = b.replace(x=b.x.astype(jnp.bfloat16))
w = (rng.normal(size=d) * 0.05).astype(np.float32)
w16 = jnp.asarray(w).astype(jnp.bfloat16)
val_p, g_p, r_p = fused_value_and_grad(logistic_loss, w16, b)
z = jnp.matmul(b.x, w16, preferred_element_type=jnp.float32) + b.offset
dl = logistic_loss.d1(z, b.y)
r = b.weight * dl
g_x = jnp.matmul(r.astype(jnp.bfloat16), b.x, preferred_element_type=jnp.float32)
val_x = jnp.sum(b.weight * logistic_loss.loss(z, b.y))


def rel16(a, bb):
    a, bb = np.asarray(a, np.float64), np.asarray(bb, np.float64)
    return float(np.max(np.abs(a - bb)) / max(1e-12, np.max(np.abs(bb))))


case = {"loss": "logistic_bf16", "value_rel": rel16(val_p, val_x),
        "grad_rel": rel16(g_p, g_x), "rsum_rel": rel16(r_p, jnp.sum(r))}
case["pass"] = all(case[k] < 2e-2 for k in
                   ("value_rel", "grad_rel", "rsum_rel"))
out["cases"].append(case)
out["pass"] = all(c["pass"] for c in out["cases"])
print(json.dumps(out))
"""


def _save(results: dict, path: str = _PARTIAL) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=2)
    os.replace(tmp, path)


# SIGALRM self-timeout prepended to every snippet: the KERNEL delivers the
# signal even while the interpreter is blocked inside a hung C call, and the
# default SIGALRM action terminates the process — a normal signal death, not
# the parent-side SIGKILL that wedges the tunnel (axon has no
# abrupt-disconnect recovery).  The parent's subprocess timeout stays as a
# LAST-RESORT backstop, 30s behind the child's own alarm.
_ALARM_PREAMBLE = "import signal as _sig; _sig.alarm({timeout})\n"


def _run_py(argv_or_src, timeout: int):
    """Run a python snippet (str) or argv (list) in a self-timing-out
    subprocess; returns (last stdout line, error)."""
    if isinstance(argv_or_src, str):
        argv = [sys.executable, "-c",
                _ALARM_PREAMBLE.format(timeout=timeout) + argv_or_src]
    else:
        argv = [sys.executable] + list(argv_or_src)
    try:
        p = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout + 30, cwd=_REPO)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    if p.returncode != 0:
        # always truthy, always carries the exit code (SIGALRM self-timeout
        # shows as -14 even when stderr holds only startup warnings)
        return None, f"exit {p.returncode}: {p.stderr[-2000:]}".strip()
    lines = [l for l in p.stdout.strip().splitlines() if l]
    if not lines:
        return None, "no output"
    return lines[-1], None


def _parse_json(line: str, label: str) -> dict:
    """json.loads that records non-JSON trailing stdout instead of crashing
    away the remaining stages (TPU runtimes routinely print noise)."""
    try:
        return json.loads(line)
    except ValueError:
        return {"error": f"non-JSON {label} output", "raw": line[-2000:]}


def main() -> int:
    results = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}

    # 1. probe
    probe_to = int(os.environ.get("PHOTON_TPU_PROBE_TIMEOUT", 120))
    line, err = _run_py(_PROBE_SRC, probe_to)
    results["probe"] = {"platform": line, "error": err}
    _save(results)
    if err or line == "cpu":
        print(f"no accelerator ({err or 'cpu backend'}); checklist aborted "
              f"— results in {_PARTIAL} (canonical {_OUT} untouched)")
        return 1
    print(f"backend: {line}")

    # 2. pallas non-interpret parity — TPU only (the kernels hard-require
    # TPU; on any other accelerator record a skip, not a traceback)
    if line == "tpu":
        line2, err = _run_py(_PALLAS_SRC, int(os.environ.get(
            "PHOTON_TPU_PALLAS_TIMEOUT", 600)))
        results["pallas_parity"] = ({"error": err} if err
                                    else _parse_json(line2, "pallas"))
    else:
        results["pallas_parity"] = {"skipped": f"backend {line!r} is not tpu"}
    _save(results)
    print("pallas parity:", json.dumps(results["pallas_parity"]))

    # 3. full bench (includes pallas-off / bf16 / fused-vs-host A/Bs on a
    # real accelerator).  bench.py runs its own watchdog subprocesses, so no
    # alarm preamble — just the argv path through the same runner.
    # PHOTON_BENCH_PROFILE_DIR: the glmix_chip child additionally captures
    # ONE untimed sweep under jax.profiler (device-side single-HBM-pass
    # evidence), AFTER flushing its result line.  A RUN-SPECIFIC subdir so
    # a stale trace from an earlier checklist can never masquerade as this
    # run's evidence.
    # ALWAYS a run-specific timestamp subdir — even under an inherited
    # PHOTON_BENCH_PROFILE_DIR — so stale traces can never be counted as
    # this run's evidence
    prof_base = os.environ.get("PHOTON_BENCH_PROFILE_DIR",
                               os.path.join(_REPO, "TPU_PROFILE"))
    prof_dir = os.path.join(prof_base,
                            time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()))
    os.environ["PHOTON_BENCH_PROFILE_DIR"] = prof_dir
    line3, err = _run_py([os.path.join(_REPO, "bench.py")],
                         int(os.environ.get("PHOTON_TPU_BENCH_TIMEOUT", 14400)))
    results["bench"] = {"error": err} if err else _parse_json(line3, "bench")
    results["profile_trace"] = {
        "dir": prof_dir,
        "files": (sum(len(fs) for _, _, fs in os.walk(prof_dir))
                  if os.path.isdir(prof_dir) else 0)}
    _save(results)
    print("bench:", json.dumps(results.get("bench", {}))[:400])
    # Promote only a run that KEEPS the canonical file's evidence value:
    # bench completed on an accelerator (a mid-run tunnel death makes
    # bench.py itself fall back to backend "cpu" — valid JSON, no error,
    # but promoting it would clobber banked TPU evidence and kill
    # bench.py's _tpu_evidence_pointer), and the pallas stage neither
    # errored nor failed (the canonical artifact's pallas_parity block is
    # cited as evidence by BASELINE.md/PARITY.md).
    pallas = results.get("pallas_parity") or {}
    pallas_ok = "error" not in pallas and pallas.get("pass") is not False
    if err or "error" in results["bench"] \
            or results["bench"].get("backend") in (None, "cpu") \
            or not pallas_ok:
        # the partial file + log hold this run's record; the canonical
        # artifact keeps the last complete banked run
        print(f"run not promotable (bench/pallas incomplete, errored, or "
              f"cpu-fallback); record in {_PARTIAL}")
        return 1
    _save(results, _OUT)
    print(f"checklist complete -> {_OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
