"""fleetwatch CLI — one fleet-global metrics plane over many processes.

Usage:
    python -m tools.fleetwatch owner=127.0.0.1:9100 \
        replica=127.0.0.1:9101 front=127.0.0.1:9102 \
        --interval 1.0 --slo slos.json --port 9200
    python -m tools.fleetwatch front=127.0.0.1:9102 --once

Each positional peer is ``LABEL=HOST:PORT`` of a process serving the
photonwatch federation pull (``GET /watchz`` on its ``--metrics-port`` —
``cli/serve.py``, ``cli/learn.py --metrics-port``, or anything holding a
``ThreadedMetricsEndpoint``).  fleetwatch polls every peer each
``--interval``, merges the snapshots in a ``FleetView`` (counters summed,
gauges labeled ``process=``, histograms bucket-merged), evaluates
``--slo`` burn rates against the MERGED registry, and serves the result:

  * ``GET /fleetz`` on ``--port`` — the fleet snapshot (per-source
    freshness/staleness + merged metrics), plus the standard
    ``/metrics`` / ``/metrics.json`` routes over the merged registry, so
    one Prometheus scrape sees the whole fleet;
  * SLO alert edges printed to stderr as they latch/resolve.

``--once`` does a single poll round, prints the fleet snapshot JSON to
stdout (or ``--out``), and exits — the scriptable/testable path.  Exit
status 1 if every peer was unreachable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # direct `python tools/fleetwatch.py` runs
    sys.path.insert(0, _REPO_ROOT)

from photon_ml_tpu.obs.watch import (FleetView, SLOEngine,  # noqa: E402
                                     load_slos)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fleetwatch",
        description="Aggregate photonwatch /watchz feeds from many "
                    "processes into one fleet registry with SLO burn-rate "
                    "alerting")
    p.add_argument("peers", nargs="+", metavar="LABEL=HOST:PORT",
                   help="processes to poll (their --metrics-port)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between poll rounds")
    p.add_argument("--stale-after", type=float, default=5.0,
                   help="seconds without a successful poll before a "
                        "source reports stale in /fleetz")
    p.add_argument("--slo", default="", metavar="FILE",
                   help="SLO objectives (JSON list, obs/watch/slo.py) "
                        "evaluated against the MERGED fleet registry")
    p.add_argument("--port", type=int, default=0,
                   help="serve GET /fleetz (+ /metrics over the merged "
                        "registry) on this localhost port (0 = off)")
    p.add_argument("--once", action="store_true",
                   help="one poll round, print the fleet snapshot JSON, "
                        "exit")
    p.add_argument("--rounds", type=int, default=0,
                   help="stop after this many poll rounds (0 = forever; "
                        "implies nothing about --once, which is 1 round)")
    p.add_argument("--out", default="-", metavar="FILE",
                   help="--once snapshot destination ('-' = stdout)")
    p.add_argument("--timeout", type=float, default=2.0,
                   help="per-peer HTTP timeout in seconds")
    return p


def _parse_peer(spec: str):
    label, sep, hostport = spec.partition("=")
    host, sep2, port = hostport.rpartition(":")
    if not sep or not sep2 or not label or not host:
        raise ValueError(f"peer wants LABEL=HOST:PORT, got {spec!r}")
    return label, host, int(port)


def poll_once(view: FleetView, peers, timeout: float = 2.0) -> int:
    """One round: pull /watchz from every peer, ingest into ``view``.
    Returns how many peers answered; failures warn on stderr and the
    source simply goes stale in the fleet snapshot."""
    ok = 0
    for label, host, port in peers:
        url = f"http://{host}:{port}/watchz"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                frame = json.loads(resp.read().decode("utf-8"))
        except (OSError, ValueError, urllib.error.URLError) as e:
            print(f"fleetwatch: {label} ({url}): {e}", file=sys.stderr)
            continue
        view.ingest(label, frame)
        ok += 1
    return ok


def run(argv) -> int:
    args = _parser().parse_args(argv)
    try:
        peers = [_parse_peer(s) for s in args.peers]
    except ValueError as e:
        print(f"fleetwatch: {e}", file=sys.stderr)
        return 2
    view = FleetView(stale_after_s=args.stale_after)
    engine = None
    if args.slo:
        try:
            slos = load_slos(args.slo)
        except (OSError, ValueError) as e:
            print(f"fleetwatch: --slo: {e}", file=sys.stderr)
            return 2

        def on_alert(edge: dict) -> None:
            print(f"fleetwatch: SLO {edge['slo']!r} {edge['state']} "
                  f"(burn fast={edge['burn_fast']:.2f} "
                  f"slow={edge['burn_slow']:.2f})", file=sys.stderr)

        engine = SLOEngine(slos, on_alert=on_alert)

    if args.once:
        ok = poll_once(view, peers, timeout=args.timeout)
        if engine is not None:
            engine.evaluate(view.registry)
        text = json.dumps(view.fleet_snapshot(), sort_keys=True)
        if args.out == "-":
            sys.stdout.write(text + "\n")
        else:
            with open(args.out, "w") as f:
                f.write(text)
        return 0 if ok else 1

    endpoint = None
    if args.port:
        from photon_ml_tpu.serving.frontend.metrics_http import \
            ThreadedMetricsEndpoint
        from photon_ml_tpu.serving.metrics import ServingMetrics

        endpoint = ThreadedMetricsEndpoint(
            ServingMetrics(registry=view.registry), port=args.port,
            fleet_view=view).start()
        print(f"fleetwatch: fleet plane on "
              f"http://127.0.0.1:{endpoint.port}/fleetz", file=sys.stderr)
    rounds = 0
    try:
        while True:
            poll_once(view, peers, timeout=args.timeout)
            if engine is not None:
                engine.evaluate(view.registry)
            rounds += 1
            if args.rounds and rounds >= args.rounds:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if endpoint is not None:
            endpoint.stop()


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
