# Makes tools/ importable so `python -m tools.photonlint` works from the
# repo root (the scripts themselves are still directly runnable).
