"""photonlint CLI — the repo's JAX/TPU static-analysis gate.

Usage:
    python -m tools.photonlint [paths ...]
    python -m tools.photonlint --paths photon_ml_tpu/serving/engine.py
    python -m tools.photonlint photon_ml_tpu/ --format json
    python -m tools.photonlint --list-rules
    python -m tools.photonlint photon_ml_tpu/ --write-baseline

Whole-program mode is the DEFAULT: a ProgramIndex over the lint paths lets
the trace-scoped rules (PL001/PL003/PL004) see functions jitted across
module boundaries and gives PL007/PL008 the program's mesh-axis universe.
``--no-program-index`` restores pure per-module analysis.

``--paths f1.py f2.py`` is the incremental (pre-commit) mode: lint ONLY the
named files, but still build the ProgramIndex over the whole package so
cross-module results match a full run — a violation in f1.py caused by a
jit site elsewhere is found without scanning everything.

``--diff [REF]`` (default REF: HEAD) asks git which package files changed —
tracked changes vs REF plus untracked files — and lints exactly those under
the same whole-package-index contract as ``--paths``.  Findings are
identical to a full run restricted to the changed files.  No changed
files: exit 0 without analysing anything.

Exit codes: 0 = clean (every finding baselined or suppressed, no stale
baseline entries); 1 = new violations OR stale baseline entries (paid-down
debt must be pruned — rerun with --prune-baseline to remove it); 2 = usage
/ configuration error.

The default baseline is ``photonlint_baseline.json`` at the repo root; see
README "Static analysis" for the suppression (`# photonlint: disable=rule
-- reason`) and baseline workflow.  tests/test_photonlint.py runs the same
analysis in-process, so tier-1 and this CLI cannot disagree.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # direct `python tools/photonlint.py` runs
    sys.path.insert(0, _REPO_ROOT)

from photon_ml_tpu.analysis import (BaselineError, build_rules,  # noqa: E402
                                    load_baseline, make_baseline, partition,
                                    registered_rules, render_json,
                                    render_sarif, render_text, run_analysis,
                                    save_baseline)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "photonlint_baseline.json")


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photonlint",
        description="JAX/TPU-aware static analysis for photon-ml-tpu")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: photon_ml_tpu/)")
    p.add_argument("--paths", dest="only_paths", nargs="+", metavar="FILE",
                   help="incremental mode: lint ONLY these files but index "
                        "the whole package, so cross-module findings match "
                        "a full run (fast pre-commit loop)")
    p.add_argument("--diff", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="incremental mode driven by git: lint the package "
                        "files changed vs REF (default HEAD), tracked and "
                        "untracked, under the whole-package index")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text",
                   help="sarif: SARIF 2.1.0 for code-scanning upload "
                        "(baselined/suppressed findings carried with "
                        "suppressions entries)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE, metavar="FILE",
                   help="baseline file of accepted debt "
                        "(default: %(default)s)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every violation as new")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into --baseline "
                        "(also prunes stale entries) and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="remove stale baseline entries (fingerprints no "
                        "current finding matches) instead of failing on "
                        "them")
    wp = p.add_mutually_exclusive_group()
    wp.add_argument("--whole-program", action="store_true", default=True,
                    help="build the cross-module ProgramIndex (default)")
    wp.add_argument("--no-program-index", dest="whole_program",
                    action="store_false",
                    help="escape hatch: per-module analysis only (no "
                         "cross-module jit resolution, module-local mesh "
                         "axes only)")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="comma-separated rule names (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--verbose", action="store_true",
                   help="text format: also print baselined findings")
    p.add_argument("--root", default=_REPO_ROOT,
                   help=argparse.SUPPRESS)  # tests anchor relpaths here
    return p


def _changed_package_files(root: str, ref: str):
    """Package .py files changed vs ``ref`` (tracked diff + untracked),
    as absolute paths; None on git failure (error already printed)."""
    import subprocess
    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, check=True)
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        print(f"photonlint: --diff {ref}: git failed: {detail.strip()}",
              file=sys.stderr)
        return None
    names = set(diff.stdout.splitlines()) | set(untracked.stdout.splitlines())
    out = []
    for name in sorted(names):
        if not name.endswith(".py") \
                or not name.startswith("photon_ml_tpu/"):
            continue  # outside the default lint scope
        fp = os.path.join(root, name)
        if os.path.exists(fp):  # deletions have nothing to lint
            out.append(fp)
    return out


def main(argv=None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        registry = registered_rules()
        for name in sorted(registry, key=lambda n: registry[n].code):
            cls = registry[name]
            print(f"{cls.code}  {name:<20} [{cls.severity}]  "
                  f"{cls.description}")
        return 0

    pkg_default = os.path.join(args.root, "photon_ml_tpu")
    if (args.diff is not None) and (args.paths or args.only_paths):
        print("photonlint: --diff computes its own file list and is "
              "mutually exclusive with positional paths / --paths",
              file=sys.stderr)
        return 2
    if args.diff is not None:
        changed = _changed_package_files(args.root, args.diff)
        if changed is None:
            return 2
        if not changed:
            print(f"photonlint: no package files changed vs {args.diff} — "
                  "nothing to lint")
            return 0
        paths = changed
        # same incremental contract as --paths: whole-package index
        index_paths = [pkg_default]
    elif args.only_paths:
        if args.paths:
            print("photonlint: positional paths and --paths are mutually "
                  "exclusive (--paths lints only the named files)",
                  file=sys.stderr)
            return 2
        paths = list(args.only_paths)
        # the incremental contract: index the whole package regardless of
        # which few files are being linted
        index_paths = [pkg_default]
    else:
        paths = args.paths or [pkg_default]
        index_paths = None
    for p in paths:
        if not os.path.exists(p):
            print(f"photonlint: no such path: {p}", file=sys.stderr)
            return 2
    try:
        rules = build_rules(args.rules.split(",") if args.rules else None)
    except KeyError as e:
        print(f"photonlint: {e.args[0]}", file=sys.stderr)
        return 2

    result = run_analysis(paths, rules=rules, root=args.root,
                          whole_program=args.whole_program,
                          index_paths=index_paths)

    if args.write_baseline:
        save_baseline(make_baseline(result.violations), args.baseline)
        print(f"photonlint: wrote {len(result.violations)} entr"
              f"{'y' if len(result.violations) == 1 else 'ies'} to "
              f"{args.baseline}")
        return 0

    try:
        baseline = (load_baseline(args.baseline) if not args.no_baseline
                    else {"version": 1, "entries": {}})
    except BaselineError as e:
        print(f"photonlint: {e}", file=sys.stderr)
        return 2
    new, baselined, stale = partition(result.violations, baseline)

    # staleness is only decidable for entries this run could have matched:
    # an incremental run can't vouch for files it didn't lint, a --rules
    # subset can't vouch for other rules' entries
    entries = baseline.get("entries", {})
    if args.only_paths or args.diff is not None:
        linted = {os.path.relpath(os.path.abspath(p), args.root)
                  .replace(os.sep, "/") for p in paths}
        stale = [fp for fp in stale
                 if entries.get(fp, {}).get("path") in linted]
    if args.rules:
        selected = set(args.rules.split(","))
        stale = [fp for fp in stale
                 if entries.get(fp, {}).get("rule") in selected]

    if stale and args.prune_baseline and not args.no_baseline:
        for fp in stale:
            baseline["entries"].pop(fp, None)
        save_baseline(baseline, args.baseline)
        print(f"photonlint: pruned {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} from {args.baseline}",
              file=sys.stderr)
        stale = []

    if args.format == "json":
        print(render_json(new, baselined, stale, result))
    elif args.format == "sarif":
        print(render_sarif(new, baselined, stale, result))
    else:
        print(render_text(new, baselined, stale, result,
                          verbose=args.verbose))

    if new:
        return 1
    if stale:
        # paid-down debt must not linger: a fingerprint nothing matches any
        # more means the baseline misstates the repo — prune it
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
