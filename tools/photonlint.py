"""photonlint CLI — the repo's JAX/TPU static-analysis gate.

Usage:
    python -m tools.photonlint [paths ...]
    python -m tools.photonlint photon_ml_tpu/ --format json
    python -m tools.photonlint --list-rules
    python -m tools.photonlint photon_ml_tpu/ --write-baseline

Exit codes: 0 = clean (every finding baselined or suppressed);
1 = new violations (or stale baseline entries under --strict-baseline);
2 = usage / configuration error.

The default baseline is ``photonlint_baseline.json`` at the repo root; see
README "Static analysis" for the suppression (`# photonlint: disable=rule
-- reason`) and baseline workflow.  tests/test_photonlint.py runs the same
analysis in-process, so tier-1 and this CLI cannot disagree.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # direct `python tools/photonlint.py` runs
    sys.path.insert(0, _REPO_ROOT)

from photon_ml_tpu.analysis import (BaselineError, build_rules,  # noqa: E402
                                    load_baseline, make_baseline, partition,
                                    registered_rules, render_json,
                                    render_text, run_analysis, save_baseline)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "photonlint_baseline.json")


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photonlint",
        description="JAX/TPU-aware static analysis for photon-ml-tpu")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: photon_ml_tpu/)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE, metavar="FILE",
                   help="baseline file of accepted debt "
                        "(default: %(default)s)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every violation as new")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into --baseline "
                        "(also prunes stale entries) and exit 0")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="comma-separated rule names (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--strict-baseline", action="store_true",
                   help="also fail when the baseline has stale entries")
    p.add_argument("--verbose", action="store_true",
                   help="text format: also print baselined findings")
    p.add_argument("--root", default=_REPO_ROOT,
                   help=argparse.SUPPRESS)  # tests anchor relpaths here
    return p


def main(argv=None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        registry = registered_rules()
        for name in sorted(registry, key=lambda n: registry[n].code):
            cls = registry[name]
            print(f"{cls.code}  {name:<18} [{cls.severity}]  "
                  f"{cls.description}")
        return 0

    paths = args.paths or [os.path.join(args.root, "photon_ml_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"photonlint: no such path: {p}", file=sys.stderr)
            return 2
    try:
        rules = build_rules(args.rules.split(",") if args.rules else None)
    except KeyError as e:
        print(f"photonlint: {e.args[0]}", file=sys.stderr)
        return 2

    result = run_analysis(paths, rules=rules, root=args.root)

    if args.write_baseline:
        save_baseline(make_baseline(result.violations), args.baseline)
        print(f"photonlint: wrote {len(result.violations)} entr"
              f"{'y' if len(result.violations) == 1 else 'ies'} to "
              f"{args.baseline}")
        return 0

    try:
        baseline = (load_baseline(args.baseline) if not args.no_baseline
                    else {"version": 1, "entries": {}})
    except BaselineError as e:
        print(f"photonlint: {e}", file=sys.stderr)
        return 2
    new, baselined, stale = partition(result.violations, baseline)

    if args.format == "json":
        print(render_json(new, baselined, stale, result))
    else:
        print(render_text(new, baselined, stale, result,
                          verbose=args.verbose))

    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
