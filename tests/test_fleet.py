"""photonfleet tests: multi-model serving, canary rollout, shadow scoring.

The contracts under test (ISSUE 16 / ROADMAP item 4):
  - Kernel sharing: same-shape models on one fleet share AOT executables
    outright (registering model N compiles NOTHING — probe-counted on the
    shared KernelCache); distinct-shape models coexist with zero
    cross-talk; ``StoreConfig.fleet_axis`` forces isolation when sharing
    compiled programs across tenants is not wanted.
  - Budget: one device hot-row budget with per-tenant quotas — over-quota
    registration refuses with TenantBudgetError, rebalance re-verifies.
  - Canary: DETERMINISTIC traffic split (stable key hash, not RNG),
    auto-promote on a clean observation window, auto-rollback on score
    drift / a not-ready health plane / an injected fault at the
    ``swap.activate`` seam — and rollback leaves the active generation
    serving bitwise-identically with zero admitted-request loss.
  - Shadow: both legs scored, primary served bitwise, per-bucket drift
    histograms, both legs under ONE photonpulse trace id.
  - Edge: tenant tokens scope connections, per-tenant admission budgets
    shed with reason ``tenant_overload``, /readyz gates admission with
    reason ``not_ready``, and clients that never heard of the model field
    keep working unchanged (wire back-compat).
"""

import io
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu import obs
from photon_ml_tpu.chaos.health import HealthState
from photon_ml_tpu.chaos.injector import (FaultInjector, InjectedCrash,
                                          set_injector)
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.obs.pulse import context as pctx
from photon_ml_tpu.obs.pulse.merge import merge_traces, spans_by_trace
from photon_ml_tpu.obs.trace import Tracer
from photon_ml_tpu.serving.batcher import BucketedBatcher, Request
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.fleet import (CANARY, PROMOTED, ROLLED_BACK,
                                         CanaryController, CanaryPolicy,
                                         FleetRouter, ModelFleet,
                                         ShadowScorer, TenantBudgetError,
                                         UnknownModelError, split_preview,
                                         stable_bucket, store_device_rows)
from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                            AdmissionController,
                                            FrontendConfig,
                                            ThreadedFrontend)
from photon_ml_tpu.serving.frontend.admission import (SHED_NOT_READY,
                                                      SHED_TENANT)
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.types import TaskType

N_ENT = 24


def _model(seed=0, d=4, n_ent=N_ENT):
    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    return GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=d)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(n_ent, d)) * 0.5,
            slot_of={i: i for i in range(n_ent)},
            random_effect_type="userId", feature_shard="all", task=task),
    }), task


def _store(seed=0, d=4, n_ent=N_ENT, fleet_axis="", metrics=None,
           version=None):
    model, task = _model(seed, d=d, n_ent=n_ent)
    imap = IndexMap({feature_key(f"f{j}"): j for j in range(d)})
    eidx = EntityIndex()
    for i in range(n_ent):
        eidx.get_or_add(f"user{i}")
    return CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=None, fleet_axis=fleet_axis),
        version=version or f"seed{seed}", metrics=metrics)


def _reqs(rng, k, d=4, model=None, uid0=0):
    from photon_ml_tpu.serving.batcher import request_from_json
    out = []
    for i in range(k):
        obj = {"uid": uid0 + i,
               "features": [[f"f{j}", float(v)]
                            for j, v in enumerate(rng.normal(size=d))],
               "ids": {"userId": f"user{int(rng.integers(0, N_ENT))}"}}
        if model is not None:
            obj["model"] = model
        out.append(request_from_json(obj))
    return out


def _fleet(max_batch=8, total_rows=None, quotas=None, metrics=None):
    """Fleet with one adopted synthetic model ``m0`` (tenant default)."""
    metrics = metrics or ServingMetrics()
    engine = ScoringEngine(_store(0, metrics=metrics),
                           BucketedBatcher(max_batch), metrics=metrics)
    engine.warm()
    fleet = ModelFleet(metrics=metrics, total_rows=total_rows,
                       quotas=quotas)
    fleet.adopt("m0", engine, HotSwapper(engine))
    return fleet


def _isolated_scores(seed, requests, d=4, max_batch=8):
    """Reference: the same store scored on a private single-model engine."""
    m = ServingMetrics()
    eng = ScoringEngine(_store(seed, d=d, metrics=m),
                        BucketedBatcher(max_batch), metrics=m)
    return eng.score_requests(requests)


# ---------------------------------------------------------------------------
# kernel sharing on one cache
# ---------------------------------------------------------------------------
class TestKernelSharing:
    def test_same_shape_models_share_executables(self):
        fleet = _fleet()
        warm_compiles = fleet.kernels.compile_count
        warm_execs = len(fleet.kernels)
        assert warm_compiles == 4  # ladder (1, 2, 4, 8)

        h1 = fleet.register_store("m1", _store(1, metrics=fleet.metrics),
                                  tenant="acme")
        # probe-counted: registering + warming an equal-shape model
        # compiled NOTHING and added no executables
        assert fleet.kernels.compile_count == warm_compiles
        assert len(fleet.kernels) == warm_execs
        assert h1.engine.kernels is fleet.kernels
        assert len(fleet.kernels.signatures()) == 1

        rng = np.random.default_rng(7)
        reqs = _reqs(rng, 11)
        s0 = fleet.handle("m0").engine.score_requests(reqs)
        s1 = h1.engine.score_requests(reqs)
        # zero cross-talk: each model scores exactly as it would alone
        np.testing.assert_array_equal(s0, _isolated_scores(0, reqs))
        np.testing.assert_array_equal(s1, _isolated_scores(1, reqs))
        assert fleet.kernels.compile_count == warm_compiles

    def test_distinct_shape_models_coexist(self):
        fleet = _fleet()
        base = fleet.kernels.compile_count
        h6 = fleet.register_store("wide", _store(3, d=6,
                                                 metrics=fleet.metrics))
        # a new shape compiles its own ladder, alongside — not instead of —
        # the old one
        assert fleet.kernels.compile_count == 2 * base
        assert len(fleet.kernels.signatures()) == 2

        rng = np.random.default_rng(11)
        reqs4, reqs6 = _reqs(rng, 9, d=4), _reqs(rng, 9, d=6)
        np.testing.assert_array_equal(
            fleet.handle("m0").engine.score_requests(reqs4),
            _isolated_scores(0, reqs4))
        np.testing.assert_array_equal(
            h6.engine.score_requests(reqs6),
            _isolated_scores(3, reqs6, d=6))

    def test_fleet_axis_isolates_same_shape(self):
        # the model axis of the cache key: an equal-shape store under its
        # own fleet_axis refuses to share compiled programs
        fleet = _fleet()
        base = fleet.kernels.compile_count
        fleet.register_store("iso", _store(1, fleet_axis="iso",
                                           metrics=fleet.metrics))
        assert fleet.kernels.compile_count == 2 * base
        assert len(fleet.kernels.signatures()) == 2

    def test_remove_prunes_only_orphaned_executables(self):
        fleet = _fleet()
        fleet.register_store("wide", _store(3, d=6, metrics=fleet.metrics))
        n_all = len(fleet.kernels)
        fleet.remove("wide")
        assert len(fleet.kernels) < n_all
        assert len(fleet.kernels.signatures()) == 1
        with pytest.raises(UnknownModelError):
            fleet.handle("wide")


# ---------------------------------------------------------------------------
# tenancy: budget + routing
# ---------------------------------------------------------------------------
class TestTenancy:
    def test_over_quota_registration_refused(self):
        rows = store_device_rows(_store(0))
        assert rows == N_ENT  # dense random-effect table, fixed excluded
        fleet = _fleet(quotas={"acme": rows - 1})
        with pytest.raises(TenantBudgetError):
            fleet.register_store("big", _store(1, metrics=fleet.metrics),
                                 tenant="acme")
        assert fleet.models() == ("m0",)

    def test_fleet_budget_caps_total(self):
        rows = store_device_rows(_store(0))
        fleet = _fleet(total_rows=rows + 1)
        with pytest.raises(TenantBudgetError):
            fleet.register_store("m1", _store(1, metrics=fleet.metrics))

    def test_resolve_default_and_unknown(self):
        fleet = _fleet()
        assert fleet.resolve(None).model_id == "m0"  # pre-fleet wire form
        with pytest.raises(UnknownModelError):
            fleet.resolve("nope")

    def test_rebalance_exports_tenant_gauges(self):
        fleet = _fleet(quotas={"default": 1000})
        fleet.rebalance()
        view = fleet.metrics.fleet_view()
        assert view["tenant_rows"]["default"] == \
            {"used": N_ENT, "quota": 1000}


# ---------------------------------------------------------------------------
# canary policy
# ---------------------------------------------------------------------------
class TestCanarySplit:
    def test_stable_bucket_frozen_values(self):
        # regression-frozen: the split must never move between releases —
        # a replayed log has to split identically forever
        assert stable_bucket("1") == 3910
        assert stable_bucket("42") == 1738
        assert stable_bucket("user3") == 494

    def test_split_preview_matches_controller(self):
        fleet = _fleet()
        ctl = CanaryController(fleet.handle("m0"),
                               CanaryPolicy(fraction=0.25,
                                            min_observations=10**6))
        ctl.start(_store(1, metrics=fleet.metrics))
        uids = list(range(400))
        canary, control = split_preview(uids, 0.25)
        assert sorted(canary + control) == uids
        assert 0.15 < len(canary) / len(uids) < 0.35
        for uid in uids:
            req = Request(uid=uid, features=[], ids={})
            assert ctl.is_canary(req) == (uid in set(canary))


class TestCanaryEpisode:
    def _controller(self, fleet, seed, **policy):
        ctl = CanaryController(fleet.handle("m0"), CanaryPolicy(**policy))
        ctl.start(_store(seed, metrics=fleet.metrics))
        return ctl

    def test_auto_promote_on_clean_window(self):
        fleet = _fleet()
        handle = fleet.handle("m0")
        gen0 = handle.store.generation
        compiles = fleet.kernels.compile_count
        # candidate == active coefficients (same seed) -> zero drift
        ctl = self._controller(fleet, 0, fraction=0.5, min_observations=8)
        rng = np.random.default_rng(5)
        scores = ctl.score(_reqs(rng, 40))
        assert len(scores) == 40 and np.all(np.isfinite(scores))
        assert ctl.state == PROMOTED
        assert ctl.settle_s is not None and ctl.settle_s >= 0
        assert handle.store.generation > gen0  # pointer flipped
        assert handle.swapper.delta_version == 0
        # the whole episode — warm, split, dual-score, promote — compiled
        # nothing (acceptance: zero engine recompiles across the episode)
        assert fleet.kernels.compile_count == compiles

    def test_auto_rollback_on_score_drift(self):
        fleet = _fleet()
        handle = fleet.handle("m0")
        rng = np.random.default_rng(6)
        probe = _reqs(rng, 13)
        baseline = handle.engine.score_requests(probe)
        ctl = self._controller(fleet, 9, fraction=0.5, min_observations=8,
                               max_drift=1e-9)
        served = ctl.score(probe + _reqs(rng, 27, uid0=100))
        assert len(served) == 40  # every admitted request got a score
        assert ctl.state == ROLLED_BACK
        assert ctl.rollback_reason == "score_drift"
        assert ctl.candidate is None
        # the active generation was never touched: bitwise-identical serve
        np.testing.assert_array_equal(handle.engine.score_requests(probe),
                                      baseline)

    def test_auto_rollback_on_health_not_ready(self):
        fleet = _fleet()
        health = HealthState()
        health.set_condition("plane", False, "injected degradation")
        ctl = CanaryController(fleet.handle("m0"),
                               CanaryPolicy(fraction=0.5,
                                            min_observations=10**6,
                                            health_poll_s=0.0),
                               health=health)
        ctl.start(_store(0, metrics=fleet.metrics))
        ctl.score(_reqs(np.random.default_rng(3), 8))
        assert ctl.state == ROLLED_BACK
        assert ctl.rollback_reason == "health_not_ready"

    def test_injected_fault_at_promotion_rolls_back_bitwise(self):
        # satellite (c): the rollback edge — a fault at the swap.activate
        # seam turns the promote into a rollback; the old generation keeps
        # serving bitwise-identically and zero admitted requests are lost
        fleet = _fleet()
        handle = fleet.handle("m0")
        gen0 = handle.store.generation
        compiles = fleet.kernels.compile_count
        rng = np.random.default_rng(8)
        probe = _reqs(rng, 16)
        baseline = handle.engine.score_requests(probe)

        inj = FaultInjector()
        inj.arm("swap.activate", kind="error")
        prev = set_injector(inj)
        try:
            ctl = self._controller(fleet, 0, fraction=0.5,
                                   min_observations=4)
            served = ctl.score(probe)
        finally:
            set_injector(prev)
        assert len(served) == len(probe)  # zero admitted-request loss
        assert ctl.state == ROLLED_BACK
        assert ctl.rollback_reason == "promotion_fault"
        assert handle.store.generation == gen0  # flip never happened
        np.testing.assert_array_equal(handle.engine.score_requests(probe),
                                      baseline)
        assert fleet.kernels.compile_count == compiles
        rollbacks = fleet.metrics.registry.counter_series(
            "fleet_canary_rollbacks_total")
        assert rollbacks[(("model", "m0"),
                          ("reason", "promotion_fault"))] == 1

    def test_injected_crash_at_promotion_propagates(self):
        fleet = _fleet()
        inj = FaultInjector()
        inj.arm("swap.activate", kind="crash")
        prev = set_injector(inj)
        try:
            ctl = self._controller(fleet, 0, fraction=1.0,
                                   min_observations=1)
            with pytest.raises(InjectedCrash):
                ctl.score(_reqs(np.random.default_rng(2), 4))
        finally:
            set_injector(prev)


# ---------------------------------------------------------------------------
# shadow scoring
# ---------------------------------------------------------------------------
class TestShadow:
    def test_serves_primary_bitwise_and_records_drift(self):
        fleet = _fleet()
        handle = fleet.handle("m0")
        compiles = fleet.kernels.compile_count
        rng = np.random.default_rng(4)
        reqs = _reqs(rng, 19)
        baseline = handle.engine.score_requests(reqs)

        scorer = ShadowScorer(handle, _store(9, metrics=fleet.metrics))
        served = scorer.score(reqs)
        np.testing.assert_array_equal(served, baseline)  # old leg served
        assert fleet.kernels.compile_count == compiles   # shadow warm free

        view = scorer.drift_view()
        assert view["pairs"] == 19
        # drift attributed to the micro-batch buckets 19 rows plan to
        assert set(view["drift"]) == {"8", "4"}
        assert all(h["count"] > 0 for h in view["drift"].values())
        assert fleet.metrics.fleet_view()["shadow"]["m0"]["pairs"] == 19

    def test_both_legs_under_one_trace_id(self):
        fleet = _fleet()
        handle = fleet.handle("m0")
        scorer = ShadowScorer(handle, _store(9, metrics=fleet.metrics))
        t = Tracer(capacity=4096, enabled=True)
        prev = obs.set_tracer(t)
        try:
            ctx = pctx.mint()
            reqs = _reqs(np.random.default_rng(1), 5)
            for r in reqs:
                r.ctx = ctx
            scorer.score(reqs)
        finally:
            obs.set_tracer(prev)
        # the tracemerge timeline joins primary and shadow executions of
        # one request under one trace id (acceptance criterion)
        by_trace = spans_by_trace(merge_traces([t.chrome_trace()]))
        names = {e["name"] for e in by_trace[ctx[0]]}
        assert {"fleet.serve", "fleet.shadow"} <= names

    def test_router_interposes_and_detaches(self):
        fleet = _fleet()
        router = FleetRouter(fleet)
        rng = np.random.default_rng(2)
        reqs = _reqs(rng, 7)
        plain = router.score("m0", reqs)
        router.attach_shadow("m0", _store(9, metrics=fleet.metrics))
        np.testing.assert_array_equal(router.score("m0", reqs), plain)
        assert router.shadows["m0"].drift_view()["pairs"] == 7
        assert router.detach_shadow("m0")
        assert not router.detach_shadow("m0")


# ---------------------------------------------------------------------------
# per-tenant admission + readiness shedding (edge policy units)
# ---------------------------------------------------------------------------
class TestTenantAdmission:
    def test_tenant_latch_and_hysteresis(self):
        ctl = AdmissionController(AdmissionConfig(budget_s=1.0,
                                                  tenant_budget_s=0.1))
        v = ctl.decide(0.0, tenant="acme", tenant_wait_s=0.5)
        assert not v.admitted and v.reason == SHED_TENANT
        assert ctl.tenant_shedding("acme")
        # latched: under budget but over the low watermark still sheds
        assert not ctl.decide(0.0, tenant="acme",
                              tenant_wait_s=0.08).admitted
        # other tenants keep admitting
        assert ctl.decide(0.0, tenant="beta", tenant_wait_s=0.0).admitted
        # unlatch at the low watermark
        assert ctl.decide(0.0, tenant="acme", tenant_wait_s=0.01).admitted
        assert not ctl.tenant_shedding("acme")

    def test_off_without_budget(self):
        ctl = AdmissionController(AdmissionConfig(budget_s=1.0))
        assert ctl.decide(0.0, tenant="acme", tenant_wait_s=99.0).admitted


# ---------------------------------------------------------------------------
# the network edge in fleet mode
# ---------------------------------------------------------------------------
def _two_tenant_fleet():
    fleet = _fleet()
    fleet.register_store("acme-model", _store(1, metrics=fleet.metrics),
                         tenant="acme")
    return fleet


def _wire_req(rng, uid, model=None):
    obj = {"uid": uid,
           "features": [[f"f{j}", float(v)]
                        for j, v in enumerate(rng.normal(size=4))],
           "ids": {"userId": f"user{int(rng.integers(0, N_ENT))}"}}
    if model is not None:
        obj["model"] = model
    return obj


class TestFrontendFleet:
    def _front(self, fleet, health=None, **over):
        kw = dict(admission=AdmissionConfig(budget_s=30.0),
                  batcher_deadline_s=0.002, health_poll_s=0.0)
        kw.update(over)
        engine = fleet.handle("m0").engine
        return ThreadedFrontend(engine, config=FrontendConfig(**kw),
                                fleet=fleet, health=health).start()

    def test_model_routing_and_backcompat(self):
        from test_frontend import Client

        fleet = _two_tenant_fleet()
        front = self._front(fleet)
        try:
            c = Client(front.port)
            rng = np.random.default_rng(3)
            wire = [_wire_req(rng, 0),                    # no model field
                    _wire_req(rng, 1, model="acme-model"),
                    _wire_req(rng, 2, model="ghost")]
            for obj in wire:
                c.send(obj)
            c.send_raw("\n")
            replies = {}
            for _ in range(3):
                r = c.recv()
                replies[r["uid"]] = r
            # model-field-less client rides the default model unchanged
            from photon_ml_tpu.serving.batcher import request_from_json
            np.testing.assert_allclose(
                replies[0]["score"],
                float(_isolated_scores(0, [request_from_json(wire[0])])[0]),
                rtol=1e-6)
            np.testing.assert_allclose(
                replies[1]["score"],
                float(_isolated_scores(1, [request_from_json(wire[1])])[0]),
                rtol=1e-6)
            assert replies[2]["error"] == "unknown_model"
            assert replies[2]["model"] == "ghost"
            c.close()
        finally:
            front.stop()
        view = fleet.metrics.fleet_view()["requests"]
        assert view["m0"]["default"] == 1
        assert view["acme-model"]["acme"] == 1

    def test_tenant_token_scopes_connection(self):
        from test_frontend import Client

        fleet = _two_tenant_fleet()
        front = self._front(fleet,
                            tenant_tokens={"tok-acme": "acme"})
        try:
            c = Client(front.port)
            c.send({"cmd": "auth", "token": "tok-acme"})
            assert c.recv() == {"auth": "ok", "tenant": "acme"}
            rng = np.random.default_rng(4)
            c.send(_wire_req(rng, 1, model="acme-model"))
            c.send(_wire_req(rng, 2, model="m0"))  # other tenant's model
            c.send_raw("\n")
            # reply order per connection is submission order
            first, second = c.recv(), c.recv()
            got = {first["uid"]: first, second["uid"]: second}
            assert "score" in got[1]
            assert got[2]["error"] == "forbidden"
            c.close()

            bad = Client(front.port)
            bad.send({"cmd": "auth", "token": "wrong"})
            assert bad.recv() == {"error": "unauthorized"}
            bad.close()
        finally:
            front.stop()

    def test_not_ready_sheds_admission(self):
        from test_frontend import Client

        fleet = _fleet()
        health = HealthState(registry=fleet.metrics.registry)
        health.set_condition("plane", True)
        front = self._front(fleet, health=health)
        try:
            c = Client(front.port)
            rng = np.random.default_rng(5)
            c.send(_wire_req(rng, 1))
            c.send_raw("\n")
            assert "score" in c.recv()

            health.set_condition("plane", False, "chaos says no")
            c.send(_wire_req(rng, 2))
            shed = c.recv()
            assert shed["error"] == "overloaded"
            assert shed["reason"] == SHED_NOT_READY

            health.set_condition("plane", True)
            c.send(_wire_req(rng, 3))
            c.send_raw("\n")
            assert "score" in c.recv()
            c.close()
        finally:
            front.stop()
        sheds = fleet.metrics.registry.counter_series("requests_shed_total")
        assert sheds[(("reason", SHED_NOT_READY),)] == 1


# ---------------------------------------------------------------------------
# per-model drain barriers
# ---------------------------------------------------------------------------
class TestPerModelDrain:
    """Control-plane quiesce is scoped to the TARGET model's batcher: a
    sibling model keeps serving, uninterrupted, while its neighbor
    drains for a swap/delta/canary transition."""

    def test_sibling_serves_through_model_scoped_drain(self):
        import threading

        from test_frontend import Client, _slow

        from photon_ml_tpu.serving.frontend.admission import SHED_DRAINING

        fleet = _two_tenant_fleet()
        # the TARGET model is slow, so its drain has real in-flight work
        _slow(fleet.handle("acme-model").engine, delay_s=0.005)
        engine = fleet.handle("m0").engine
        front = ThreadedFrontend(engine, config=FrontendConfig(
            admission=AdmissionConfig(budget_s=30.0),
            batcher_deadline_s=0.002, health_poll_s=0.0),
            fleet=fleet).start()
        load, ctrl, acme = (Client(front.port), Client(front.port),
                            Client(front.port))
        rng = np.random.default_rng(9)
        n = 80
        replies = {}
        reader_err = []

        def read_load():
            try:
                for _ in range(n):
                    rep = load.recv()
                    replies[rep["uid"]] = rep
            except Exception as e:
                reader_err.append(e)

        rt = threading.Thread(target=read_load)
        rt.start()
        try:
            for i in range(n):
                load.send(_wire_req(rng, i))  # m0: the untouched model
                if i % 4 == 0:  # keep the target's batcher busy
                    acme.send(_wire_req(rng, f"a{i}", model="acme-model"))
                if i == n // 2:
                    # model-scoped quiesce lands with load in flight on
                    # BOTH models
                    ctrl.send({"cmd": "delta", "model": "acme-model",
                               "coordinate": "user", "entity": "user0",
                               "row": [0.1, -0.2, 0.3, 0.05]})
            load.send_raw("\n")
            acme.send_raw("\n")
            rep = ctrl.recv()
            assert rep["delta"] == "ok", rep
            rt.join(120)
            assert not reader_err, reader_err
            assert len(replies) == n
            # the acceptance gate: the sibling NEVER sheds for a drain it
            # is not part of — every m0 request resolves to a score
            bad = [r for r in replies.values() if "score" not in r]
            assert not bad, bad[:3]
            # the target itself may shed while draining, but only with
            # the explicit draining reason — never silently dropped
            for _ in range(n // 4):
                r = acme.recv()
                assert ("score" in r
                        or r.get("reason") == SHED_DRAINING), r
        finally:
            load.close()
            ctrl.close()
            acme.close()
            front.stop()


# ---------------------------------------------------------------------------
# sampled always-on tracing
# ---------------------------------------------------------------------------
class TestSampledMinting:
    def test_every_nth_deterministic(self):
        pctx.reset_sampling()
        got = [pctx.maybe_mint(3) is not None for _ in range(7)]
        assert got == [False, False, True, False, False, True, False]

    def test_edge_rates(self):
        pctx.reset_sampling()
        assert pctx.maybe_mint(0) is None
        assert pctx.maybe_mint(-5) is None
        assert all(pctx.maybe_mint(1) is not None for _ in range(3))


# ---------------------------------------------------------------------------
# cli/serve.py end to end (trained model dirs)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def model_dirs(tmp_path_factory):
    from test_serving import _train

    tmp = tmp_path_factory.mktemp("fleet_cli")
    return _train(tmp, seed=1), _train(tmp, seed=2)


FEATURES = ["g0", "g1", "g2", "ux"]


def _cli_req(rng, uid, model=None):
    obj = {"uid": uid,
           "features": [[f, float(v)]
                        for f, v in zip(FEATURES, rng.normal(size=4))],
           "ids": {"userId": f"user{int(rng.integers(0, 6))}"}}
    if model is not None:
        obj["model"] = model
    return obj


def _run_cli(argv, req_lines, tmp_path, name="reqs"):
    import contextlib

    from photon_ml_tpu.cli import serve as serve_cli

    req = tmp_path / f"{name}.jsonl"
    req.write_text("\n".join(req_lines) + "\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = serve_cli.run(argv + ["--requests", str(req)])
    return rc, [json.loads(l) for l in buf.getvalue().strip().splitlines()]


class TestServeCliFleet:
    def test_canary_promote_rollback_e2e(self, model_dirs, tmp_path):
        """One stdio session: rollback episode (drift gate) then promote
        episode (clean gate) on the default model — zero admitted-request
        loss, zero recompiles, bitwise-identical serving after rollback."""
        dir1, dir2 = model_dirs
        rng = np.random.default_rng(21)
        probe = json.dumps(_cli_req(np.random.default_rng(99), 1))
        lines = [probe, ""]
        lines.append(json.dumps({"cmd": "fleet"}))
        # episode 1: candidate drifts (different training seed), tiny gate
        lines.append(json.dumps({"cmd": "canary", "model_dir": dir2,
                                 "min_observations": 8, "fraction": 0.5,
                                 "max_drift": 1e-9}))
        uids = list(range(100, 140))
        for uid in uids:
            lines.append(json.dumps(_cli_req(rng, uid)))
        lines.append("")
        lines.append(json.dumps({"cmd": "fleet"}))
        lines.append(probe)  # must score bitwise as before the episode
        lines.append("")
        # episode 2: same candidate, gate wide open -> clean window
        lines.append(json.dumps({"cmd": "canary", "model_dir": dir2,
                                 "min_observations": 8, "fraction": 0.5,
                                 "max_drift": 1e9}))
        for uid in range(200, 240):
            lines.append(json.dumps(_cli_req(rng, uid)))
        lines.append("")
        lines.append(json.dumps({"cmd": "fleet"}))

        rc, out = _run_cli(["--model-dir", dir1, "--max-batch", "8",
                            "--add-model", f"alt={dir2}"],
                           lines, tmp_path, "canary")
        assert rc == 0
        scores = {o["uid"]: o["score"] for o in out if "score" in o}
        # zero admitted-request loss across both episodes
        assert set(scores) == {1} | set(uids) | set(range(200, 240))
        fleets = [o["fleet"] for o in out if "fleet" in o]
        assert len(fleets) == 3
        ep1, ep2 = fleets[1]["canary"]["default"], \
            fleets[2]["canary"]["default"]
        assert ep1["state"] == ROLLED_BACK
        assert ep1["rollback_reason"] == "score_drift"
        assert ep2["state"] == PROMOTED
        assert ep2["settle_s"] > 0
        # same-shape candidate stores + shared cache: the whole session —
        # two episodes included — never compiled past the startup warm
        assert all(f["kernels"]["compiles"] ==
                   fleets[0]["kernels"]["compiles"] for f in fleets)
        # bitwise-identical serving after the rollback: the probe line
        # appears twice in the stream and must score identically
        probe_scores = [o["score"] for o in out if o.get("uid") == 1]
        assert probe_scores[0] == probe_scores[1]
        # ...and the promote flipped the default model's generation
        assert fleets[2]["models"]["default"]["generation"] > \
            fleets[0]["models"]["default"]["generation"]

    def test_wire_backcompat_model_field_less_clients(self, model_dirs,
                                                      tmp_path):
        dir1, dir2 = model_dirs
        rng = np.random.default_rng(31)
        lines = [json.dumps(_cli_req(rng, uid)) for uid in range(9)]
        rng = np.random.default_rng(31)
        lines2 = [json.dumps(_cli_req(rng, uid)) for uid in range(9)]
        assert lines == lines2
        rc1, out1 = _run_cli(["--model-dir", dir1, "--max-batch", "8"],
                             lines, tmp_path, "plain")
        rc2, out2 = _run_cli(["--model-dir", dir1, "--max-batch", "8",
                              "--add-model", f"alt={dir2}"],
                             lines, tmp_path, "fleetmode")
        assert rc1 == 0 and rc2 == 0
        s1 = {o["uid"]: o["score"] for o in out1 if "score" in o}
        s2 = {o["uid"]: o["score"] for o in out2 if "score" in o}
        assert s1 == s2  # pre-fleet clients observe nothing

    def test_unknown_model_error_reply(self, model_dirs, tmp_path):
        dir1, dir2 = model_dirs
        rng = np.random.default_rng(41)
        lines = [json.dumps(_cli_req(rng, 7, model="ghost"))]
        rc, out = _run_cli(["--model-dir", dir1, "--max-batch", "8",
                            "--add-model", f"alt={dir2}"],
                           lines, tmp_path, "unknown")
        assert rc == 0
        assert out[0] == {"uid": 7, "error": "unknown_model",
                          "model": "ghost"}

    def test_over_budget_tenant_refused_at_startup(self, model_dirs,
                                                   tmp_path):
        from photon_ml_tpu.cli import serve as serve_cli

        dir1, dir2 = model_dirs
        rc = serve_cli.run(["--model-dir", dir1, "--max-batch", "8",
                            "--add-model", f"alt={dir2},tenant=acme",
                            "--tenant-quota", "acme=1",
                            "--requests", os.devnull])
        assert rc == 1

    def test_shadow_cmd_e2e(self, model_dirs, tmp_path):
        dir1, dir2 = model_dirs
        rng = np.random.default_rng(51)
        lines = [json.dumps({"cmd": "shadow", "model_dir": dir2})]
        lines += [json.dumps(_cli_req(rng, uid)) for uid in range(12)]
        lines.append("")
        lines.append(json.dumps({"cmd": "fleet"}))
        lines.append(json.dumps({"cmd": "shadow", "off": True}))
        rc, out = _run_cli(["--model-dir", dir1, "--max-batch", "8",
                            "--add-model", f"alt={dir2}"],
                           lines, tmp_path, "shadow")
        assert rc == 0
        assert out[0] == {"shadow": "on", "model": "default",
                          "version": dir2}
        fleet_view = [o["fleet"] for o in out if "fleet" in o][0]
        assert fleet_view["shadow"]["default"]["pairs"] == 12
        assert [o for o in out if o.get("shadow") == "off"]
