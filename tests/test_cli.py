"""End-to-end CLI driver tests (reference analog: GameTrainingDriverIntegTest,
GameScoringDriverIntegTest — full pipeline runs on small fixtures)."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli.config_grammar import expand_game_configs, parse_coordinate_spec
from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.game.config import FixedEffectConfig, RandomEffectConfig
from photon_ml_tpu.types import TaskType


def _write_fixture(path, n=300, seed=0):
    """GLMix-ish avro fixture: global features f0/f1/f2 + per-user structure."""
    rng = np.random.default_rng(seed)
    n_users = 6
    uw = rng.normal(size=(n_users, 1)) * 1.5
    gw = np.asarray([0.8, -1.2, 0.5])
    records = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        xg = rng.normal(size=3)
        xu = rng.normal(size=1)
        logit = xg @ gw + xu @ uw[u]
        y = float(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        feats = [{"name": f"g{j}", "term": "", "value": float(xg[j])} for j in range(3)]
        feats.append({"name": "ux", "term": "", "value": float(xu[0])})
        records.append({"uid": i, "response": y, "label": None, "features": feats,
                        "weight": None, "offset": None,
                        "metadataMap": {"userId": f"user{u}"}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)


def test_config_grammar():
    spec = parse_coordinate_spec(
        "name=global,feature.shard=s,optimizer=TRON,max.iter=7,tolerance=1e-5,"
        "reg.weights=0.1|1|10,reg.type=L2")
    assert spec.name == "global" and len(spec.reg_weights) == 3
    assert isinstance(spec.template, FixedEffectConfig)
    assert spec.template.solver.max_iters == 7

    spec_re = parse_coordinate_spec(
        "name=user,random.effect.type=userId,feature.shard=u,"
        "active.data.lower.bound=2,active.data.upper.bound=100,reg.weights=1")
    assert isinstance(spec_re.template, RandomEffectConfig)
    assert spec_re.template.active_cap == 100
    assert spec_re.template.min_active_samples == 2

    configs = expand_game_configs([spec, spec_re], TaskType.LOGISTIC_REGRESSION, 2)
    assert len(configs) == 3  # 3 weights x 1 weight
    assert configs[0].coordinates["global"].reg.l2 == 0.1
    assert configs[0].num_outer_iterations == 2

    with pytest.raises(ValueError, match="unknown"):
        parse_coordinate_spec("name=x,feature.shard=s,bogus.key=1")
    with pytest.raises(ValueError, match="name"):
        parse_coordinate_spec("feature.shard=s")


def test_config_grammar_projection_keys():
    from photon_ml_tpu.types import ProjectorType

    spec = parse_coordinate_spec(
        "name=user,random.effect.type=userId,feature.shard=u,projector=INDEX_MAP,"
        "features.to.samples.ratio=0.5,intercept.index=3,reg.weights=1")
    assert spec.template.projector == ProjectorType.INDEX_MAP
    assert spec.template.features_to_samples_ratio == 0.5
    assert spec.template.intercept_index == 3

    spec = parse_coordinate_spec(
        "name=user,random.effect.type=userId,feature.shard=u,"
        "projector=RANDOM,projected.dim=16,reg.weights=1")
    assert spec.template.projector == ProjectorType.RANDOM
    assert spec.template.projected_dim == 16

    # down.sampling.rate is a FIXED-effect key: rejected on random effects
    # rather than silently dropped
    with pytest.raises(ValueError, match="unknown"):
        parse_coordinate_spec(
            "name=user,random.effect.type=userId,feature.shard=u,"
            "down.sampling.rate=0.1,reg.weights=1")


def test_train_score_pipeline(tmp_path):
    from photon_ml_tpu.cli import score as score_cli
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    val_path = str(tmp_path / "val.avro")
    _write_fixture(train_path, n=400, seed=1)
    _write_fixture(val_path, n=150, seed=2)
    out = str(tmp_path / "out")

    rc = train_cli.run([
        "--train-data", train_path,
        "--validation-data", val_path,
        "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1|10",
        "--coordinate", "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--id-tags", "userId",
        "--evaluators", "auc,logistic_loss",
        "--coordinate-descent-iterations", "2",
        "--output-dir", out,
    ])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["train_samples"] == 400
    assert summary["validation"]["auc"] > 0.6
    assert os.path.isdir(os.path.join(out, "best", "fixed-effect", "fixed"))
    assert os.path.isdir(os.path.join(out, "best", "random-effect", "user"))

    score_out = str(tmp_path / "scores")
    rc = score_cli.run([
        "--data", val_path,
        "--model-dir", out,
        "--output-dir", score_out,
        "--evaluators", "auc",
    ])
    assert rc == 0
    metrics = json.load(open(os.path.join(score_out, "metrics.json")))
    assert abs(metrics["auc"] - summary["validation"]["auc"]) < 0.15
    scores = list(avro_io.read_container(os.path.join(score_out, "scores.avro")))
    assert len(scores) == 150
    assert all(np.isfinite(s["predictionScore"]) for s in scores)


def test_index_driver(tmp_path):
    from photon_ml_tpu.cli import index as index_cli
    from photon_ml_tpu.data.index_map import IndexMap

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=50)
    out = str(tmp_path / "idx")
    rc = index_cli.run(["--data", train_path, "--feature-shards", "all",
                        "--output-dir", out])
    assert rc == 0
    m = IndexMap.load(os.path.join(out, "all.idx"))
    assert m.size == 5  # intercept + g0,g1,g2,ux
    assert m.intercept_index == 0


def test_index_driver_store_format(tmp_path):
    """--format store builds the off-heap PHIDX002 store and training loads it."""
    from photon_ml_tpu.cli import index as index_cli
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.native_index import StoreIndexMap

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=80)
    out = str(tmp_path / "idx")
    rc = index_cli.run(["--data", train_path, "--feature-shards", "all",
                        "--output-dir", out, "--format", "store"])
    assert rc == 0
    m = load_index(os.path.join(out, "all.phidx"))
    assert isinstance(m, StoreIndexMap)
    assert m.size == 5 and m.intercept_index == 0

    model_dir = str(tmp_path / "model")
    rc = train_cli.run([
        "--train-data", train_path, "--task", "LOGISTIC_REGRESSION",
        "--feature-shards", "all", "--index-map-dir", out,
        "--coordinate", "name=global,feature.shard=all,reg.weights=1.0",
        "--output-dir", model_dir])
    assert rc == 0
    assert os.path.exists(os.path.join(model_dir, "all.phidx"))


def test_warm_start_and_partial_retrain(tmp_path):
    """--model-input-dir warm start + --lock-coordinates partial retraining
    (reference GameTrainingDriver.scala:370-379, GameEstimator :106-112)."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.storage.model_io import load_game_model
    from photon_ml_tpu.data.index_map import load_index

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=300, seed=3)
    out1 = str(tmp_path / "round1")
    base = ["--train-data", train_path, "--feature-shards", "all",
            "--id-tags", "userId",
            "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
            "--coordinate", "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1"]
    assert train_cli.run(base + ["--output-dir", out1]) == 0

    # partial retrain: lock the fixed effect, retrain only the random effect
    out2 = str(tmp_path / "round2")
    events = []
    import tests.test_cli as self_mod
    self_mod._seen_events = events
    assert train_cli.run(base + [
        "--output-dir", out2, "--model-input-dir", out1,
        "--lock-coordinates", "fixed",
        "--event-listener", "tests.test_cli:_RecordingListener"]) == 0

    imaps = {"all": load_index(os.path.join(out1, "all.idx"))}
    from photon_ml_tpu.data.reader import EntityIndex
    eidx = {"userId": EntityIndex.load(os.path.join(out1, "userId.entities.json"))}
    m1, _ = load_game_model(os.path.join(out1, "best"), imaps, eidx)
    m2, _ = load_game_model(os.path.join(out2, "best"), imaps, eidx)
    np.testing.assert_allclose(m1.models["fixed"].coefficients.means,
                               m2.models["fixed"].coefficients.means)  # locked
    # lifecycle events fired
    names = [e.name for e in events]
    assert names[0] == "training_start" and names[-1] == "training_end"
    assert "fit_start" in names
    # log file written next to outputs (PhotonLogger parity)
    assert os.path.getsize(os.path.join(out2, "log-message.txt")) > 0

    # locking without an input model is a usage error
    assert train_cli.run(base + ["--output-dir", str(tmp_path / "bad"),
                                 "--lock-coordinates", "fixed"]) == 1
    # unknown locked coordinate name is a clean usage error, not a traceback
    assert train_cli.run(base + ["--output-dir", str(tmp_path / "bad2"),
                                 "--model-input-dir", out1,
                                 "--lock-coordinates", "fxied"]) == 1
    # missing model dir likewise
    assert train_cli.run(base + ["--output-dir", str(tmp_path / "bad3"),
                                 "--model-input-dir", str(tmp_path / "nope")]) == 1

    # tuning + partial retraining: locked coefficients survive the tuner
    out3 = str(tmp_path / "round3")
    val_path = str(tmp_path / "val.avro")
    _write_fixture(val_path, n=120, seed=4)
    assert train_cli.run(base + [
        "--output-dir", out3, "--model-input-dir", out1,
        "--lock-coordinates", "fixed", "--validation-data", val_path,
        "--evaluators", "auc", "--tuning-iterations", "2",
        "--tuning-mode", "random"]) == 0
    m3, _ = load_game_model(os.path.join(out3, "best"), imaps, eidx)
    np.testing.assert_allclose(m1.models["fixed"].coefficients.means,
                               m3.models["fixed"].coefficients.means)


from photon_ml_tpu.utils.events import EventListener  # noqa: E402


class _RecordingListener(EventListener):
    """Registered by name via --event-listener (reflection-style wiring)."""

    def on_event(self, event):
        _seen_events.append(event)


_seen_events: list = []


def test_normalization_cli(tmp_path):
    """--normalization STANDARDIZATION trains e2e and writes feature stats."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=300, seed=11)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--normalization", "STANDARDIZATION",
        "--output-dir", out])
    assert rc == 0
    stats = json.load(open(os.path.join(out, "feature-stats.json")))
    assert "all" in stats and stats["all"]["intercept_index"] == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6


def test_checkpoint_dir_cli(tmp_path):
    """--checkpoint-dir writes per-update checkpoints; a rerun resumes
    (skipping completed work) and produces a valid model."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.storage.checkpoint import load_checkpoint
    from photon_ml_tpu.data.index_map import load_index

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=200, seed=9)
    out = str(tmp_path / "out")
    ckpt = str(tmp_path / "ckpt")
    argv = ["--train-data", train_path, "--feature-shards", "all",
            "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
            "--coordinate-descent-iterations", "2",
            "--output-dir", out, "--checkpoint-dir", ckpt]
    assert train_cli.run(argv) == 0
    imaps = {"all": load_index(os.path.join(out, "all.idx"))}
    model, _, cursor, _best = load_checkpoint(ckpt, imaps)
    assert cursor.pop("fingerprint")
    assert cursor == {"config": 0, "iteration": 2, "coordinate": 0}
    assert "fixed" in model.models
    # rerun: everything before the cursor is skipped, still succeeds
    assert train_cli.run(argv + ["--output-dir", str(tmp_path / "out2")]) == 0
    # a rerun with a CHANGED grid must refuse to resume (wrong-cursor guard)
    changed = list(argv)
    changed[changed.index("name=fixed,feature.shard=all,reg.weights=1")] = \
        "name=fixed,feature.shard=all,reg.weights=1|10"
    assert train_cli.run(changed + ["--output-dir", str(tmp_path / "out3")]) == 1


def test_diagnose_driver(tmp_path):
    from photon_ml_tpu.cli import diagnose as diag_cli
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    val_path = str(tmp_path / "val.avro")
    _write_fixture(train_path, n=400, seed=5)
    _write_fixture(val_path, n=150, seed=6)
    out = str(tmp_path / "model")
    assert train_cli.run([
        "--train-data", train_path, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--id-tags", "userId",
        "--output-dir", out]) == 0

    diag_out = str(tmp_path / "diag")
    rc = diag_cli.run(["--data", train_path, "--holdout", val_path,
                       "--model-dir", out, "--output-dir", diag_out,
                       "--bootstrap-replicates", "4",
                       "--compare-l2", "0.1,1,10"])
    assert rc == 0
    html = open(os.path.join(diag_out, "report.html")).read()
    # per-coordinate chapters + model summary + full-model chapters,
    # reachable from the index page
    assert "Model summary" in html
    assert "Coordinate &#x27;fixed&#x27; (fixed effect)" in html
    assert "Coordinate &#x27;user&#x27; (random effect)" in html
    assert "Calibration (full model)" in html
    assert "Residuals (full model)" in html
    assert '<a href="#s1">' in html  # index page
    assert "Bootstrap" in html and "Feature importance" in html
    # regularization-path comparison chapter: one NESTED subsection per
    # weight, a numbered weight list, and a resolved cross-reference back
    # to the coordinate's own chapter
    assert "Regularization path comparison" in html
    for w in ("0.1", "1", "10"):
        assert f"l2 = {w}</h4>" in html
    assert "<ol><li>l2 = 0.1</li>" in html
    assert "full diagnostics for this coordinate" in html
    assert "[unresolved reference" not in html
    text = open(os.path.join(diag_out, "report.txt")).read()
    assert "l2 = 10" in text and "see §" in text
    assert "<svg" in html and "<polyline" in html  # line plots
    assert "<rect" in html and "<circle" in html  # bar charts + scatter
    summary = json.load(open(os.path.join(diag_out, "diagnostics.json")))
    assert set(summary["coordinates"]) == {"fixed", "user"}
    assert summary["coordinates"]["fixed"]["fitting"] is not None
    assert summary["coordinates"]["user"]["entities"] == 6
    assert summary["hosmer_lemeshow"] is not None
    assert abs(summary["kendall_tau"]["tau"]) <= 1.0


def test_train_rejects_invalid_data(tmp_path):
    from photon_ml_tpu.cli import train as train_cli

    path = str(tmp_path / "bad.avro")
    records = [{"uid": 0, "response": 0.5, "label": None,
                "features": [{"name": "f", "term": "", "value": 1.0}],
                "weight": None, "offset": None, "metadataMap": None}]
    avro_io.write_container(path, TRAINING_EXAMPLE, records)
    rc = train_cli.run([
        "--train-data", path, "--feature-shards", "s",
        "--coordinate", "name=fixed,feature.shard=s,reg.weights=1",
        "--output-dir", str(tmp_path / "o"),
    ])
    assert rc == 1  # 0.5 label fails logistic validation


def test_train_with_date_range_partitions(tmp_path):
    """Daily-partitioned input dirs resolved via --input-date-range
    (reference IOUtils.getInputPathsWithinDateRange:113-153)."""
    from photon_ml_tpu.cli import train as train_cli

    base = tmp_path / "daily"
    for i, day in enumerate(("2017/03/01", "2017/03/02", "2017/03/03")):
        d = base / day
        d.mkdir(parents=True)
        _write_fixture(str(d / "part-0.avro"), n=120, seed=10 + i)

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", str(base),
        "--input-date-range", "20170301-20170310",  # missing days skipped
        "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--output-dir", out,
    ])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["train_samples"] == 360  # all three days read


def test_model_output_modes(tmp_path):
    """Reference ModelOutputMode.scala + selectModels:683-701: NONE saves no
    models, BEST saves best/ only, EXPLICIT also saves the grid under
    models/<i>/ with a model-spec."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    val_path = str(tmp_path / "val.avro")
    _write_fixture(train_path, n=200, seed=3)
    _write_fixture(val_path, n=100, seed=4)
    base = [
        "--train-data", train_path, "--validation-data", val_path,
        "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=0.1|1|10",
        "--evaluators", "auc",
    ]

    out_none = str(tmp_path / "none")
    assert train_cli.run(base + ["--output-dir", out_none,
                                 "--model-output-mode", "NONE"]) == 0
    assert not os.path.exists(os.path.join(out_none, "best"))
    assert os.path.exists(os.path.join(out_none, "training-summary.json"))

    out_best = str(tmp_path / "best")
    assert train_cli.run(base + ["--output-dir", out_best]) == 0
    assert os.path.isdir(os.path.join(out_best, "best"))
    assert json.load(open(os.path.join(out_best, "best", "model-spec.json")))
    assert not os.path.exists(os.path.join(out_best, "models"))

    out_all = str(tmp_path / "explicit")
    assert train_cli.run(base + ["--output-dir", out_all,
                                 "--model-output-mode", "EXPLICIT"]) == 0
    # 3 reg weights -> models/0..2, each with spec + saved validation metric
    for i in range(3):
        spec = json.load(open(os.path.join(out_all, "models", str(i),
                                           "model-spec.json")))
        assert "fixed" in spec["config"] and spec["validation"]["auc"] > 0.5
    l2s = [json.load(open(os.path.join(out_all, "models", str(i),
                                       "model-spec.json")))["config"]["fixed"]["l2"]
           for i in range(3)]
    assert sorted(l2s) == [0.1, 1.0, 10.0]

    out_lim = str(tmp_path / "limited")
    assert train_cli.run(base + ["--output-dir", out_lim,
                                 "--model-output-mode", "EXPLICIT",
                                 "--output-models-limit", "1"]) == 0
    assert os.path.isdir(os.path.join(out_lim, "models", "0"))
    assert not os.path.exists(os.path.join(out_lim, "models", "1"))


def test_variance_type_in_coordinate_spec():
    from photon_ml_tpu.types import VarianceComputationType

    spec = parse_coordinate_spec(
        "name=fixed,feature.shard=g,reg.weights=1,variance.type=SIMPLE")
    assert spec.template.variance == VarianceComputationType.SIMPLE
    spec2 = parse_coordinate_spec(
        "name=u,random.effect.type=uid,feature.shard=g,variance.type=FULL")
    assert spec2.template.variance == VarianceComputationType.FULL


def test_feature_summary_avro_output(tmp_path):
    """Normalization runs emit the reference's FeatureSummarizationResultAvro
    records next to the JSON stats."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data import avro as avro_io

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=150, seed=9)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--normalization", "STANDARDIZATION",
        "--output-dir", out,
    ])
    assert rc == 0
    recs = list(avro_io.read_container(os.path.join(out, "all.feature-summary.avro")))
    assert len(recs) >= 4  # g0..g2, ux (+ intercept row if mapped)
    by_name = {r["name"]: r["metrics"] for r in recs}
    assert "g0" in by_name and set(by_name["g0"]) == {"mean", "variance", "absMax"}


def test_input_columns_remap(tmp_path):
    """--input-columns remaps the reserved record fields
    (reference InputColumnsNames)."""
    from photon_ml_tpu.cli import train as train_cli

    # fixture with custom field names
    rng = np.random.default_rng(4)
    schema = {
        "type": "record", "name": "Custom", "namespace": "x",
        "fields": [
            {"name": "clicked", "type": "double"},
            {"name": "feats", "type": {"type": "array", "items": {
                "type": "record", "name": "F", "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": "string"},
                    {"name": "value", "type": "double"}]}}},
            {"name": "meta", "type": {"type": "map", "values": "string"}},
        ],
    }
    gw = np.asarray([1.0, -1.0])
    records = []
    for i in range(200):
        x = rng.normal(size=2)
        yv = float(rng.random() < 1 / (1 + np.exp(-x @ gw)))
        records.append({"clicked": yv,
                        "feats": [{"name": f"g{j}", "term": "", "value": float(x[j])}
                                  for j in range(2)],
                        "meta": {"userId": f"u{i % 3}"}})
    path = str(tmp_path / "custom.avro")
    avro_io.write_container(path, schema, records)

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", path, "--feature-shards", "all",
        "--input-columns", "response=clicked,features=feats,metadataMap=meta",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--id-tags", "userId",
        "--output-dir", out,
    ])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["train_samples"] == 200

    # scoring the same remapped data works (cross-driver wiring)
    from photon_ml_tpu.cli import score as score_cli

    score_out = str(tmp_path / "scores")
    rc = score_cli.run(["--data", path, "--model-dir", out,
                        "--input-columns",
                        "response=clicked,features=feats,metadataMap=meta",
                        "--evaluators", "auc", "--output-dir", score_out])
    assert rc == 0
    metrics = json.load(open(os.path.join(score_out, "metrics.json")))
    assert metrics["auc"] > 0.6

    # bad key and physical-name collisions are rejected with exit code 1
    base_bad = ["--train-data", path, "--feature-shards", "all",
                "--coordinate", "name=fixed,feature.shard=all,reg.weights=1"]
    assert train_cli.run(base_bad + ["--input-columns", "nope=x",
                                     "--output-dir", str(tmp_path / "bad")]) == 1
    assert train_cli.run(base_bad + ["--input-columns", "response=weight",
                                     "--output-dir", str(tmp_path / "bad2")]) == 1


def test_tuning_shrink_radius_cli(tmp_path):
    """--tuning-shrink-radius narrows the search domain around the best
    prior before tuning (reference ShrinkSearchRange)."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    val_path = str(tmp_path / "val.avro")
    _write_fixture(train_path, n=200, seed=5)
    _write_fixture(val_path, n=100, seed=6)
    priors = str(tmp_path / "priors.json")
    with open(priors, "w") as f:
        json.dump({"records": [
            {"l2:fixed": "1.0", "evaluationValue": "0.75"},
            {"l2:fixed": "100.0", "evaluationValue": "0.55"},
            {"l2:fixed": "0.01", "evaluationValue": "0.6"},
        ]}, f)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", val_path,
        "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--evaluators", "auc",
        "--tuning-iterations", "2", "--tuning-mode", "random",
        "--tuning-priors", priors, "--tuning-shrink-radius", "0.15",
        "--output-dir", out,
    ])
    assert rc == 0
    assert os.path.isdir(os.path.join(out, "best"))

    # shrink without priors is rejected
    assert train_cli.run([
        "--train-data", train_path, "--validation-data", val_path,
        "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--evaluators", "auc", "--tuning-iterations", "2",
        "--tuning-shrink-radius", "0.15",
        "--output-dir", str(tmp_path / "bad"),
    ]) == 1


def test_dummy_tuner_cli(tmp_path):
    """--tuner DUMMY: tuning requested but no-op (reference DummyTuner)."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    val_path = str(tmp_path / "val.avro")
    _write_fixture(train_path, n=150, seed=7)
    _write_fixture(val_path, n=80, seed=8)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", val_path,
        "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1|10",
        "--evaluators", "auc", "--tuning-iterations", "5",
        "--tuner", "DUMMY", "--model-output-mode", "ALL",
        "--output-dir", out,
    ])
    assert rc == 0
    # only the 2 grid models saved: the DUMMY tuner produced none
    assert sorted(os.listdir(os.path.join(out, "models"))) == ["0", "1"]


def test_corrupt_checkpoint_fails_cleanly(tmp_path):
    """A damaged checkpoint dir (atomic saves make this external damage)
    must produce a clear error, not a traceback or a silent fresh restart."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=150, seed=12)
    ck = tmp_path / "ckpt"
    out1 = str(tmp_path / "out1")
    base = ["--train-data", train_path, "--feature-shards", "all",
            "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
            "--checkpoint-dir", str(ck)]
    assert train_cli.run(base + ["--output-dir", out1]) == 0

    # corrupt the pointed-to version's cursor
    import json as _json

    ptr = (ck / "LATEST").read_text().strip()
    (ck / ptr / "cursor.json").write_text("{not json")

    rc = train_cli.run(base + ["--output-dir", str(tmp_path / "out2")])
    assert rc == 1  # clean failure, not a crash

    # deleted version dir with a live pointer: also a loud refusal, NOT a
    # silent fresh restart (the pointer is the no-checkpoint discriminator)
    import shutil

    shutil.rmtree(ck / ptr)
    rc = train_cli.run(base + ["--output-dir", str(tmp_path / "out3")])
    assert rc == 1
    assert not os.path.exists(os.path.join(str(tmp_path / "out3"),
                                           "training-summary.json"))


def test_config_grammar_storage_dtype():
    spec = parse_coordinate_spec(
        "name=global,feature.shard=s,reg.weights=1,storage.dtype=bfloat16")
    assert spec.template.storage_dtype == "bfloat16"
    spec_re = parse_coordinate_spec(
        "name=u,random.effect.type=userId,feature.shard=s,reg.weights=1,"
        "storage.dtype=bfloat16")
    assert spec_re.template.storage_dtype == "bfloat16"


def test_config_grammar_storage_dtype_validation():
    with pytest.raises(ValueError, match="storage.dtype"):
        parse_coordinate_spec(
            "name=g,feature.shard=s,reg.weights=1,storage.dtype=bf16")
    with pytest.raises(ValueError, match="narrower"):
        parse_coordinate_spec(
            "name=g,feature.shard=s,reg.weights=1,storage.dtype=float64")
    # sub-4-byte but non-floating dtypes must be rejected too (they would
    # silently truncate the design matrix at the host-side cast)
    for bad in ("int8", "uint8", "bool"):
        with pytest.raises(ValueError, match="floating"):
            parse_coordinate_spec(
                f"name=g,feature.shard=s,reg.weights=1,storage.dtype={bad}")


def test_score_predict_mean_and_grouped_evaluators(tmp_path):
    """Score driver: --predict-mean writes inverse-link means (bounded (0,1)
    for logistic) while evaluators run on RAW margins; grouped 'auc:userId'
    spec evaluates per id-tag (reference MultiEvaluator per-tag semantics)."""
    from photon_ml_tpu.cli import score as score_cli
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=400, seed=11)
    out = str(tmp_path / "model")
    assert train_cli.run([
        "--train-data", train_path, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--id-tags", "userId",
        "--output-dir", out]) == 0

    score_out = str(tmp_path / "scores")
    rc = score_cli.run([
        "--data", train_path, "--model-dir", out,
        "--predict-mean",
        "--evaluators", "auc,auc:userId",
        "--output-dir", score_out,
    ])
    assert rc == 0
    scores = list(avro_io.read_container(os.path.join(score_out, "scores.avro")))
    vals = np.asarray([s["predictionScore"] for s in scores])
    assert np.all((vals > 0) & (vals < 1))  # sigmoid means, not margins
    metrics = json.load(open(os.path.join(score_out, "metrics.json")))
    assert metrics["auc"] > 0.6          # raw-margin AUC unaffected by link
    assert "auc:userId" in metrics       # grouped per-tag evaluator ran


def test_cli_constraints_end_to_end(tmp_path):
    """constraints=@file (reference constraint-string grammar) through the
    train CLI: coefficients verifiably inside bounds in the saved model."""
    import os

    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.storage.model_io import load_game_model

    dp = str(tmp_path / "train.avro")
    _write_fixture(dp, n=500)
    cpath = str(tmp_path / "constraints.json")
    with open(cpath, "w") as f:
        json.dump([
            {"name": "g0", "term": "", "lowerBound": -0.1, "upperBound": 0.1},
            {"name": "g1", "term": "", "upperBound": 0.0},
        ], f)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", dp, "--feature-shards", "all",
        "--coordinate",
        f"name=g,feature.shard=all,reg.weights=0.1,constraints=@{cpath}",
        "--output-dir", out])
    assert rc == 0
    imap = load_index(os.path.join(out, "all.idx"))
    model, _ = load_game_model(os.path.join(out, "best"), {"all": imap})
    w = model["g"].coefficients.means
    j0 = imap.get_index("g0", "")
    j1 = imap.get_index("g1", "")
    assert -0.1 - 1e-6 <= w[j0] <= 0.1 + 1e-6
    assert w[j1] <= 1e-6
    # binding check: the unconstrained fit puts |g0| well above 0.1
    assert abs(w[j0]) > 0.05


def test_resolve_constraints_wildcards():
    from photon_ml_tpu.cli.config_grammar import resolve_constraints
    from photon_ml_tpu.data.index_map import IndexMap, feature_key

    imap = IndexMap({feature_key("a", ""): 0, feature_key("a", "t"): 1,
                     feature_key("b", ""): 2,
                     feature_key("(INTERCEPT)", ""): 3})
    # term wildcard: every term of name 'a'
    got = resolve_constraints(
        [{"name": "a", "term": "*", "lowerBound": -1, "upperBound": 1}], imap)
    assert got == ((0, -1.0, 1.0), (1, -1.0, 1.0))
    # all-feature wildcard skips the intercept
    got = resolve_constraints(
        [{"name": "*", "term": "*", "lowerBound": 0}], imap)
    assert [j for j, _, _ in got] == [0, 1, 2]
    assert all(hi == float("inf") for _, _, hi in got)
    # overlap and name-only wildcard are errors
    with pytest.raises(ValueError, match="overlap"):
        resolve_constraints(
            [{"name": "a", "term": "*", "lowerBound": -1},
             {"name": "a", "term": "t", "upperBound": 1}], imap)
    with pytest.raises(ValueError, match="wildcard"):
        resolve_constraints([{"name": "*", "term": "t", "lowerBound": -1}], imap)
    # unknown features are silently skipped (reference: only mapped features
    # constrain), missing both bounds is an error
    assert resolve_constraints(
        [{"name": "zz", "term": "", "lowerBound": 0}], imap) == ()


def test_columnar_model_format_round_trip(tmp_path):
    """--model-save-format columnar: raw-array model files load back
    identically (the 1e7-feature fast path; avro NTV stays the portable
    default), including warm-start through the train driver."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.storage.model_io import load_game_model, save_game_model

    dp = str(tmp_path / "train.avro")
    _write_fixture(dp, n=300)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", dp, "--feature-shards", "all",
        "--coordinate", "name=g,feature.shard=all,reg.weights=0.5",
        "--coordinate",
        "name=u,random.effect.type=userId,feature.shard=all,reg.weights=1,"
        "variance.type=SIMPLE",
        "--id-tags", "userId",
        "--model-save-format", "columnar",
        "--output-dir", out])
    assert rc == 0
    assert os.path.isfile(os.path.join(out, "best", "fixed-effect", "g",
                                       "coefficients.npz"))
    imap = load_index(os.path.join(out, "all.idx"))
    eidx = EntityIndex.load(os.path.join(out, "userId.entities.json"))
    model, task = load_game_model(os.path.join(out, "best"), {"all": imap},
                                  {"userId": eidx})
    w = model["g"].coefficients.means
    assert w.shape == (imap.size,) and np.all(np.isfinite(w))
    assert model["u"].variances is not None

    # round trip: columnar save of the loaded model == itself
    d2 = str(tmp_path / "resave")
    save_game_model(model, d2, {"all": imap}, {"userId": eidx}, task,
                    fmt="columnar")
    back, _ = load_game_model(d2, {"all": imap}, {"userId": eidx})
    np.testing.assert_array_equal(back["g"].coefficients.means, w)
    np.testing.assert_array_equal(back["u"].w_stack, model["u"].w_stack)
    assert back["u"].slot_of == model["u"].slot_of

    # warm start from the columnar model through the driver
    out2 = str(tmp_path / "warm")
    rc = train_cli.run([
        "--train-data", dp, "--feature-shards", "all",
        "--coordinate", "name=g,feature.shard=all,reg.weights=0.5",
        "--coordinate",
        "name=u,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--id-tags", "userId",
        "--model-input-dir", out,
        "--output-dir", out2])
    assert rc == 0


def test_columnar_checkpoint_resume(tmp_path):
    """Columnar-format checkpoints: interrupted training resumes from the
    fast-path npz checkpoint and finishes identically to avro checkpoints."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.storage.model_io import load_game_model

    dp = str(tmp_path / "train.avro")
    _write_fixture(dp, n=250)

    outs = {}
    for fmt in ("avro", "columnar"):
        out = str(tmp_path / f"out_{fmt}")
        ck = str(tmp_path / f"ck_{fmt}")
        argv = [
            "--train-data", dp, "--feature-shards", "all",
            "--coordinate", "name=g,feature.shard=all,reg.weights=0.5",
            "--coordinate",
            "name=u,random.effect.type=userId,feature.shard=all,reg.weights=1",
            "--id-tags", "userId",
            "--coordinate-descent-iterations", "2",
            "--checkpoint-dir", ck,
            "--model-save-format", fmt,
            "--output-dir", out]
        assert train_cli.run(argv) == 0
        # checkpoint version exists and carries the requested format
        vdirs = [d for d in os.listdir(ck) if d.startswith("v")]
        assert vdirs
        meta = json.load(open(os.path.join(ck, vdirs[0], "metadata.json")))
        assert meta.get("format", "avro") == fmt
        # resume-from-checkpoint run completes (idempotent: already done)
        assert train_cli.run(argv) == 0
        imap = load_index(os.path.join(out, "all.idx"))
        from photon_ml_tpu.data.reader import EntityIndex
        eidx = EntityIndex.load(os.path.join(out, "userId.entities.json"))
        model, _ = load_game_model(os.path.join(out, "best"), {"all": imap},
                                   {"userId": eidx})
        outs[fmt] = model
    np.testing.assert_allclose(outs["avro"]["g"].coefficients.means,
                               outs["columnar"]["g"].coefficients.means,
                               rtol=1e-6, atol=1e-8)


def test_columnar_cross_run_entity_remap_and_fingerprint(tmp_path):
    """The two columnar-binding safety contracts: (1) entity ids remap BY
    NAME through id-index.json when the loading run numbers entities
    differently; (2) a same-size-but-different index map is refused via the
    content fingerprint."""
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import GameModel, RandomEffectModel
    from photon_ml_tpu.storage.model_io import load_game_model, save_game_model

    d = str(tmp_path / "m")
    imap = IndexMap({feature_key(f"f{j}", ""): j for j in range(3)})
    eidx_a = EntityIndex()
    a_alice, a_bob = eidx_a.get_or_add("alice"), eidx_a.get_or_add("bob")
    w = np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    m = RandomEffectModel(w_stack=w, slot_of={a_alice: 0, a_bob: 1},
                          random_effect_type="userId", feature_shard="s",
                          task=TaskType.LOGISTIC_REGRESSION)
    save_game_model(GameModel(models={"u": m}), d, {"s": imap},
                    {"userId": eidx_a}, TaskType.LOGISTIC_REGRESSION,
                    fmt="columnar")

    # loading run sees bob FIRST -> different ids; names must still win
    eidx_b = EntityIndex()
    b_bob, b_alice = eidx_b.get_or_add("bob"), eidx_b.get_or_add("alice")
    model, _ = load_game_model(d, {"s": imap}, {"userId": eidx_b})
    np.testing.assert_array_equal(
        model["u"].w_stack[model["u"].slot_of[b_alice]], [1.0, 2.0, 3.0])
    np.testing.assert_array_equal(
        model["u"].w_stack[model["u"].slot_of[b_bob]], [4.0, 5.0, 6.0])

    # same-size, different-content index map -> loud fingerprint refusal
    imap_shuffled = IndexMap({feature_key(f"g{j}", ""): j for j in range(3)})
    with pytest.raises(ValueError, match="different contents"):
        load_game_model(d, {"s": imap_shuffled}, {"userId": eidx_b})
    # different size -> loud size refusal
    imap_small = IndexMap({feature_key("f0", ""): 0})
    with pytest.raises(ValueError, match="coefficients"):
        load_game_model(d, {"s": imap_small}, {"userId": eidx_b})


def test_normalization_applies_to_random_effects(tmp_path):
    """--normalization now normalizes random-effect coordinates too
    (reference NormalizationContextRDD): a GLMix run with STANDARDIZATION
    trains e2e — including under INDEX_MAP compaction since round 4 (the
    context is projected per entity; the per-lane intercept position
    absorbs the margin shift)."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write_fixture(train_path, n=400, seed=13)
    out = str(tmp_path / "out")
    base = [
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--id-tags", "userId",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
    ]
    rc = train_cli.run(base + [
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--normalization", "STANDARDIZATION",
        "--output-dir", out])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6

    # INDEX_MAP + shifts: SUPPORTED since round 4 (the old loud refusal)
    rc = train_cli.run(base + [
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,"
        "projector=INDEX_MAP,reg.weights=1",
        "--normalization", "STANDARDIZATION",
        "--output-dir", str(tmp_path / "out2")])
    assert rc == 0
    summary2 = json.load(open(os.path.join(tmp_path / "out2",
                                           "training-summary.json")))
    assert summary2["validation"]["auc"] > 0.6
    # factor-only normalization with INDEX_MAP stays fine
    rc = train_cli.run(base + [
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,"
        "projector=INDEX_MAP,reg.weights=1",
        "--normalization", "SCALE_WITH_STANDARD_DEVIATION",
        "--output-dir", str(tmp_path / "out3")])
    assert rc == 0


def test_sparse_shard_factor_normalization(tmp_path):
    """Sparse shards compute feature stats straight from the COO arrays
    (compute_feature_stats_sparse) — a sparse-threshold run with
    SCALE_WITH_STANDARD_DEVIATION normalizes both coordinates."""
    import numpy as np

    from photon_ml_tpu.cli import train as train_cli

    rng = np.random.default_rng(3)
    n_users, vocab, k = 8, 120, 6
    uw = {u: rng.normal(size=vocab) * 1.2 for u in range(n_users)}
    scale_col = np.exp(rng.normal(size=vocab))  # wildly varied column scales

    def write(path, n, seed):
        r = np.random.default_rng(seed)
        recs = []
        for i in range(n):
            u = int(r.integers(0, n_users))
            js = r.choice(vocab, size=k, replace=False)
            vs = r.normal(size=k) * scale_col[js]
            logit = vs @ (uw[u][js] / scale_col[js])
            yv = float(r.random() < 1 / (1 + np.exp(-logit)))
            feats = [{"name": f"u{j}", "term": "", "value": float(v)}
                     for j, v in zip(js, vs)]
            recs.append({"uid": i, "response": yv, "label": None,
                         "features": feats, "weight": None, "offset": None,
                         "metadataMap": {"userId": f"user{u}"}})
        avro_io.write_container(path, TRAINING_EXAMPLE, recs)

    train_path = str(tmp_path / "train.avro")
    write(train_path, 1600, 1)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--id-tags", "userId",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--normalization", "SCALE_WITH_STANDARD_DEVIATION",
        "--sparse-threshold", "100",
        "--output-dir", out])
    assert rc == 0
    stats = json.load(open(os.path.join(out, "feature-stats.json")))
    assert "all" in stats  # sparse stats were computed and recorded
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6


def _run_multihost_train(data_path, output_dir, *, max_iter=80, extra=()):
    """Shared 2-process `train_multihost` launch scaffolding: CPU env with a
    2-device virtual mesh, fresh coordinator port, one worker process per
    pid, and a zero-exit assertion carrying each worker's stderr tail."""
    import socket
    import subprocess
    import sys

    import photon_ml_tpu
    from conftest import require_multiprocess_backend

    require_multiprocess_backend()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    env.pop("PYTEST_CURRENT_TEST", None)
    repo_root = os.path.dirname(os.path.dirname(photon_ml_tpu.__file__))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def cmd(pid):
        return [sys.executable, "-m", "photon_ml_tpu.cli.train_multihost",
                "--train-data", data_path,
                "--feature-shards", "global,user", "--id-tags", "userId",
                "--fixed", "name=fixed,feature.shard=global,"
                           f"reg.weights=0.1,max.iter={max_iter},"
                           "tolerance=1e-9",
                "--random", "name=user,random.effect.type=userId,"
                            "feature.shard=user,reg.weights=1,"
                            f"max.iter={max_iter},tolerance=1e-9",
                "--coordinator-address", f"127.0.0.1:{port}",
                "--num-processes", "2", "--process-id", str(pid),
                "--expected-processes", "2", "--iterations", "2",
                "--output-dir", output_dir, "--seed", "3"] + list(extra)

    procs = [subprocess.Popen(cmd(pid), env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for pid in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=420))
    finally:
        # drain BOTH before asserting: a worker-0 failure must not leave
        # worker-1 blocked on the dead coordinator as a leaked subprocess
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{se[-3000:]}"


def test_train_multihost_cli(tmp_path):
    """2-process `train_multihost` end-to-end: both processes train the
    same GLMix under jax.distributed, write the executor-partitioned model
    layout (part-{pid}.avro per host + fixed/metadata from process 0), and
    the STANDARD loader merges the directory into a model matching the
    single-process train driver to solver tolerance."""
    data_path = str(tmp_path / "train.avro")
    _write_fixture(data_path, n=500, seed=11)
    out_mh = str(tmp_path / "out_mh")
    _run_multihost_train(data_path, out_mh)
    # the executor-partitioned layout: one part per process
    parts = sorted(os.listdir(os.path.join(out_mh, "random-effect", "user")))
    assert parts == ["part-00000.avro", "part-00001.avro"]

    # single-process reference through the normal train driver
    from photon_ml_tpu.cli import train as train_cli

    out_sp = str(tmp_path / "out_sp")
    rc = train_cli.run([
        "--train-data", data_path, "--feature-shards", "global,user",
        "--coordinate", "name=fixed,feature.shard=global,optimizer=LBFGS,"
                        "max.iter=80,tolerance=1e-9,reg.weights=0.1",
        "--coordinate", "name=user,random.effect.type=userId,"
                        "feature.shard=user,max.iter=80,tolerance=1e-9,"
                        "reg.weights=1",
        "--id-tags", "userId", "--coordinate-descent-iterations", "2",
        "--output-dir", out_sp, "--seed", "3"])
    assert rc == 0

    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.storage.model_io import load_game_model

    imaps = {"global": load_index(os.path.join(out_mh, "global.idx")),
             "user": load_index(os.path.join(out_mh, "user.idx"))}
    eidx = {"userId": EntityIndex.load(
        os.path.join(out_mh, "userId.entities.json"))}
    mh_model, _ = load_game_model(out_mh, imaps, eidx)
    sp_model, _ = load_game_model(os.path.join(out_sp, "best"), imaps, eidx)
    np.testing.assert_allclose(
        np.asarray(mh_model["fixed"].coefficients.means),
        np.asarray(sp_model["fixed"].coefficients.means), atol=5e-4)
    re_mh, re_sp = mh_model["user"], sp_model["user"]
    assert set(re_mh.slot_of) == set(re_sp.slot_of)
    for e, s in re_mh.slot_of.items():
        np.testing.assert_allclose(
            np.asarray(re_mh.w_stack[s]),
            np.asarray(re_sp.w_stack[re_sp.slot_of[e]]), atol=2e-3)


def test_train_multihost_checkpoint_resume(tmp_path):
    """Preemption drill: --stop-after-iteration 0 exits cleanly after the
    per-host checkpoint + cursor; rerunning the SAME command resumes at the
    cursor (scores recomputed from the loaded lane blocks) and the final
    model is BITWISE the uninterrupted run's."""
    data_path = str(tmp_path / "train.avro")
    _write_fixture(data_path, n=400, seed=13)

    def run(outdir, extra):
        _run_multihost_train(data_path, str(tmp_path / outdir),
                             max_iter=60, extra=extra)

    ck = str(tmp_path / "ck")
    run("out_ck", ["--checkpoint-dir", ck, "--stop-after-iteration", "0"])
    cur = json.load(open(os.path.join(ck, "cursor.json")))
    assert cur == {"next_iteration": 1, "num_processes": 2}
    assert sorted(f for f in os.listdir(ck) if f.endswith(".npz")) == \
        ["host-00000.npz", "host-00001.npz"]
    run("out_ck", ["--checkpoint-dir", ck])     # resume
    run("out_ref", [])                          # uninterrupted reference

    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.storage.model_io import load_game_model

    base = str(tmp_path / "out_ck")
    imaps = {"global": load_index(os.path.join(base, "global.idx")),
             "user": load_index(os.path.join(base, "user.idx"))}
    eidx = {"userId": EntityIndex.load(
        os.path.join(base, "userId.entities.json"))}
    a, _ = load_game_model(base, imaps, eidx)
    b, _ = load_game_model(str(tmp_path / "out_ref"), imaps, eidx)
    np.testing.assert_array_equal(
        np.asarray(a["fixed"].coefficients.means),
        np.asarray(b["fixed"].coefficients.means))
    ra, rb = a["user"], b["user"]
    for e, s in ra.slot_of.items():
        np.testing.assert_array_equal(
            np.asarray(ra.w_stack[s]),
            np.asarray(rb.w_stack[rb.slot_of[e]]))


def test_train_multihost_normalization(tmp_path):
    """--normalization STANDARDIZATION on the multihost driver: shared
    contexts from training stats, transformed solves, original-space
    publish — matches the single-process normalized train driver."""
    data_path = str(tmp_path / "train.avro")
    _write_fixture(data_path, n=500, seed=17)
    out_mh = str(tmp_path / "out_mh")
    _run_multihost_train(data_path, out_mh,
                         extra=["--normalization", "STANDARDIZATION"])

    from photon_ml_tpu.cli import train as train_cli

    out_sp = str(tmp_path / "out_sp")
    rc = train_cli.run([
        "--train-data", data_path, "--feature-shards", "global,user",
        "--coordinate", "name=fixed,feature.shard=global,optimizer=LBFGS,"
                        "max.iter=80,tolerance=1e-9,reg.weights=0.1",
        "--coordinate", "name=user,random.effect.type=userId,"
                        "feature.shard=user,max.iter=80,tolerance=1e-9,"
                        "reg.weights=1",
        "--id-tags", "userId", "--coordinate-descent-iterations", "2",
        "--normalization", "STANDARDIZATION",
        "--output-dir", out_sp, "--seed", "3"])
    assert rc == 0

    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.storage.model_io import load_game_model

    imaps = {"global": load_index(os.path.join(out_mh, "global.idx")),
             "user": load_index(os.path.join(out_mh, "user.idx"))}
    eidx = {"userId": EntityIndex.load(
        os.path.join(out_mh, "userId.entities.json"))}
    a, _ = load_game_model(out_mh, imaps, eidx)
    b, _ = load_game_model(os.path.join(out_sp, "best"), imaps, eidx)
    np.testing.assert_allclose(
        np.asarray(a["fixed"].coefficients.means),
        np.asarray(b["fixed"].coefficients.means), atol=2e-3, rtol=1e-2)
    ra, rb = a["user"], b["user"]
    assert set(ra.slot_of) == set(rb.slot_of)
    for e, sl in ra.slot_of.items():
        np.testing.assert_allclose(
            np.asarray(ra.w_stack[sl]),
            np.asarray(rb.w_stack[rb.slot_of[e]]), atol=2e-3, rtol=1e-2)
