"""Property tests for deterministic entity bucketing (SURVEY §5 determinism).

The reservoir cap must be a pure function of (rows, seed) — the reference
keys its reservoir on a seeded hash so retries/stragglers resample the SAME
rows (RandomEffectDataset.scala:358-420).  Hypothesis drives random entity
layouts through the grouping core and checks determinism, permutation
stability, cap/rescale accounting, and dense/sparse bucketer agreement.
"""

import os
import sys

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.parallel import bucketing

_ids = st.lists(st.integers(0, 6), min_size=1, max_size=60).map(
    lambda v: np.asarray(v, np.int64))


@settings(max_examples=60, deadline=None)
@given(ids=_ids, cap=st.integers(1, 8), seed=st.integers(0, 2**31 - 1))
def test_group_rows_deterministic_and_capped(ids, cap, seed):
    a = bucketing._group_rows(ids, cap, 1, seed)
    b = bucketing._group_rows(ids, cap, 1, seed)
    assert all(np.array_equal(x, y) for x, y in zip(a[0], b[0]))
    assert a[1] == b[1] and a[2] == b[2]
    kept_rows, kept_entities, rescale = a
    assert kept_entities == sorted(set(int(i) for i in ids))
    for rows, ent, sc in zip(kept_rows, kept_entities, rescale):
        total = int(np.sum(ids == ent))
        assert len(rows) == min(total, cap)
        # weight rescale preserves total weight: kept * (count/cap) = count
        assert sc * len(rows) == total
        assert np.all(ids[rows] == ent)  # rows really belong to the entity


@settings(max_examples=60, deadline=None)
@given(ids=_ids, cap=st.integers(1, 8),
       s1=st.integers(0, 2**31 - 1), s2=st.integers(0, 2**31 - 1))
def test_group_rows_seed_controls_sample(ids, cap, s1, s2):
    """Same seed -> same sample; the seed is the ONLY stochastic input."""
    a = bucketing._group_rows(ids, cap, 1, s1)
    b = bucketing._group_rows(ids, cap, 1, s2)
    if s1 == s2:
        assert all(np.array_equal(x, y) for x, y in zip(a[0], b[0]))
    # regardless of seed, the kept-entity directory is identical
    assert a[1] == b[1]


@settings(max_examples=40, deadline=None)
@given(ids=_ids, min_active=st.integers(1, 6))
def test_min_active_lower_bound_semantics(ids, min_active):
    """Under-bound entities are dropped ONLY when a prior model covers them
    (reference RandomEffectDataset.scala:322-333); new entities always
    train."""
    covered = frozenset(int(i) for i in np.unique(ids)[::2])
    kept_rows, kept_entities, _ = bucketing._group_rows(
        ids, None, min_active, 0, existing_model_keys=covered)
    for ent in np.unique(ids):
        count = int(np.sum(ids == ent))
        if count >= min_active or int(ent) not in covered:
            assert int(ent) in kept_entities
        else:
            assert int(ent) not in kept_entities


@settings(max_examples=30, deadline=None)
@given(ids=_ids, cap=st.integers(2, 8), seed=st.integers(0, 1000))
def test_dense_and_sparse_bucketers_agree(ids, cap, seed):
    """Same grouping core, same lane metadata: the dense bucketer and the
    row-sparse bucketer must agree on labels/weights/row maps lane by lane
    (their padding/rescale semantics share _pack_lane_meta by design)."""
    rng = np.random.default_rng(seed)
    n, d, k = len(ids), 8, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=n).astype(np.float32)
    w = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    off = rng.normal(size=n).astype(np.float32)
    dense = bucketing.bucket_by_entity(ids, x, y, offset=off, weight=w,
                                       active_cap=cap, seed=seed,
                                       dtype=np.float32)
    idx = np.tile(np.arange(k, dtype=np.int32), (n, 1))
    sparse, _projections = bucketing.bucket_by_entity_sparse(
        ids, idx, x[:, :k], d, y, offset=off, weight=w,
        active_cap=cap, seed=seed, dtype=np.float32)
    assert dense.lane_of.keys() == sparse.lane_of.keys()
    d_b = {e: dense.buckets[bi] for e, (bi, _) in dense.lane_of.items()}
    s_b = {e: sparse.buckets[bi] for e, (bi, _) in sparse.lane_of.items()}
    for e in dense.lane_of:
        (dbi, dl), (sbi, sl) = dense.lane_of[e], sparse.lane_of[e]
        db, sb = d_b[e], s_b[e]
        np.testing.assert_array_equal(np.asarray(db.rows)[dl],
                                      np.asarray(sb.rows)[sl])
        np.testing.assert_allclose(np.asarray(db.weight)[dl],
                                   np.asarray(sb.weight)[sl], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(db.y)[dl],
                                   np.asarray(sb.y)[sl], rtol=1e-6)


@settings(max_examples=40, deadline=None)
@given(n=st.integers(1, 300), ne=st.integers(1, 40),
       d=st.integers(1, 24), seed=st.integers(0, 2**31 - 1),
       bf16=st.booleans())
def test_score_samples_t_property(n, ne, d, seed, bf16):
    """[d, n] samples-on-lanes scoring == [n, d] gather scoring for ANY
    shape/slot pattern/storage dtype (the narrow-shard layout swap must be
    a pure layout change — including d=1, all-(-1) slots, and bf16
    storage against f32 coefficients)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(ne, d)).astype(np.float32)
    slots = rng.integers(-1, ne, size=n).astype(np.int32)
    xa = jnp.asarray(x)
    tol = dict(rtol=1e-5, atol=1e-6)
    if bf16:
        xa = xa.astype(jnp.bfloat16)
        tol = dict(rtol=2e-2, atol=2e-2)
    a = np.asarray(bucketing.score_samples(
        jnp.asarray(w), jnp.asarray(slots), xa), np.float64)
    b = np.asarray(bucketing.score_samples_t(
        jnp.asarray(w), jnp.asarray(slots), xa.T), np.float64)
    np.testing.assert_allclose(a, b, **tol)
    assert (b[slots < 0] == 0).all()
