"""Property tests for the round-4 compact-space carve-outs.

The random-effect coordinate solves each entity in the compact space of its
observed columns; round 4 composed that with shift normalization, box
constraints and FULL variances (game/coordinate.py).  These properties pin
the math that makes each composition exact:

- FULL/SIMPLE variances: the full-space Hessian is block-diagonal (an
  unobserved column is identically zero), so compact computation + 1/λ2
  fill equals the full-space answer.
- box constraints: an unobserved constrained feature's full-space optimum
  is clip(0, lo, hi) — the back-projection fill's value.
- per-lane projected contexts: a published original-space model maps back
  to the transformed-space iterate (the maps are inverses per lane).

Shapes are FIXED (hypothesis varies data only) so every example reuses one
compiled solve.
"""

import os
import sys

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402  (conftest forces cpu + x64)

from photon_ml_tpu.core.batch import dense_batch  # noqa: E402
from photon_ml_tpu.core.losses import logistic_loss  # noqa: E402
from photon_ml_tpu.core.objective import GLMObjective  # noqa: E402
from photon_ml_tpu.core.regularization import Regularization  # noqa: E402
from photon_ml_tpu.types import VarianceComputationType  # noqa: E402
from photon_ml_tpu.utils.linalg import cholesky_inverse  # noqa: E402

_N, _D, _OBS = 24, 8, 4  # samples, full dim, observed columns


@st.composite
def _entity_problem(draw):
    """One entity's data over _OBS observed columns of a _D-wide space."""
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(seed)
    obs = np.sort(rng.choice(_D, size=_OBS, replace=False))
    x_c = rng.normal(size=(_N, _OBS))
    y = (rng.random(_N) < 0.5).astype(float)
    w_c = rng.normal(size=_OBS) * 0.5
    l2 = float(draw(st.floats(min_value=0.1, max_value=5.0)))
    return obs, x_c, y, w_c, l2


@settings(max_examples=15, deadline=None)
@given(_entity_problem())
def test_full_variances_block_diagonal_exact(prob):
    """diag(H_full⁻¹) == [diag(H_compact⁻¹) on observed, 1/λ2 elsewhere] —
    the fact _expand_compact_variances relies on for FULL variances under
    compaction (game/coordinate.py)."""
    obs, x_c, y, w_c, l2 = prob
    x_full = np.zeros((_N, _D))
    x_full[:, obs] = x_c
    w_full = np.zeros(_D)
    w_full[obs] = w_c

    obj = GLMObjective(loss=logistic_loss, reg=Regularization(l2=l2))
    h_full = np.asarray(obj.hessian(jnp.asarray(w_full),
                                    dense_batch(x_full, y)))
    v_full = np.diagonal(np.asarray(cholesky_inverse(jnp.asarray(h_full))))

    h_c = np.asarray(obj.hessian(jnp.asarray(w_c), dense_batch(x_c, y)))
    v_c = np.diagonal(np.asarray(cholesky_inverse(jnp.asarray(h_c))))
    v_expand = np.full(_D, 1.0 / l2)
    v_expand[obs] = v_c
    np.testing.assert_allclose(v_full, v_expand, rtol=1e-8, atol=1e-12)


@settings(max_examples=15, deadline=None)
@given(_entity_problem(),
       st.floats(min_value=0.01, max_value=0.3),
       st.floats(min_value=0.4, max_value=1.0))
def test_unobserved_box_optimum_is_clipped_zero(prob, lo, hi):
    """A constrained feature the entity never observes reaches exactly
    clip(0, lo, hi) in the FULL-space box solve — the value the compact
    path's back-projection fill publishes (BucketProjection.back_project)."""
    from photon_ml_tpu.opt.solve import make_solver
    from photon_ml_tpu.opt.types import SolverConfig

    obs, x_c, y, w_c, l2 = prob
    x_full = np.zeros((_N, _D))
    x_full[:, obs] = x_c
    unobs = [j for j in range(_D) if j not in set(obs.tolist())]

    lo_v = np.full(_D, -np.inf)
    hi_v = np.full(_D, np.inf)
    for j in unobs:
        lo_v[j], hi_v[j] = lo, hi  # a positive box away from 0
    obj = GLMObjective(loss=logistic_loss, reg=Regularization(l2=l2))
    solve = make_solver(obj, config=SolverConfig(max_iters=60),
                        box=(jnp.asarray(lo_v), jnp.asarray(hi_v)))
    import jax

    res = jax.jit(solve)(jnp.zeros(_D), dense_batch(x_full, y))
    w = np.asarray(res.w)
    expected = float(np.clip(0.0, lo, hi))  # == lo here (lo > 0)
    np.testing.assert_allclose(w[unobs], expected, atol=1e-6)


@settings(max_examples=10, deadline=None)
@given(_entity_problem(),
       st.lists(st.floats(min_value=0.2, max_value=5.0),
                min_size=_OBS, max_size=_OBS),
       st.lists(st.floats(min_value=-2.0, max_value=2.0),
                min_size=_OBS, max_size=_OBS))
def test_per_lane_context_maps_are_inverses(prob, facs, shifts):
    """The per-lane projected context's coefficient-space maps round-trip:
    model_to_original_space ∘ model_to_transformed_space == id at the lane's
    own intercept position — what warm starts + publishing rely on under
    shift normalization with compaction (game/coordinate._warm_start /
    _lanes_to_original)."""
    from photon_ml_tpu.core.normalization import NormalizationContext

    obs, x_c, y, w_c, l2 = prob
    ii = 0  # compact intercept position within the lane
    x_c = x_c.copy()
    x_c[:, ii] = 1.0  # margin invariance NEEDS a real intercept column:
    # the shift folds into its coefficient (why the compact path requires
    # the intercept observed in every sample)
    fac = np.asarray(facs)
    sh = np.asarray(shifts)
    fac[ii], sh[ii] = 1.0, 0.0  # the intercept column is never transformed
    ctx = NormalizationContext(factors=jnp.asarray(fac),
                               shifts=jnp.asarray(sh))
    w_t = ctx.model_to_transformed_space(jnp.asarray(w_c), ii)
    w_rt = ctx.model_to_original_space(w_t, ii)
    np.testing.assert_allclose(np.asarray(w_rt), w_c, rtol=1e-9, atol=1e-10)
    # and margins are invariant: eff(w_t)·x + margin_shift == w_orig·x
    z_t = (np.asarray(ctx.effective_coefficients(w_t)) @ x_c.T
           + float(ctx.margin_shift(w_t)))
    z_o = w_c @ x_c.T
    np.testing.assert_allclose(z_t, z_o, rtol=1e-8, atol=1e-9)
