"""Online serving tests (photon_ml_tpu/serving/*, cli/serve.py).

Reference analog: the batch repo has no serving integ tests to mirror — the
contract here is INTERNAL parity: padded, bucketed, AOT-compiled serving
scores must be bitwise the ``GameTransformer`` batch scores on the same
inputs (property test over random batch sizes / entity mixes / cold-entity
splits), plus the operational guarantees the subsystem exists for: zero
recompiles after warm, atomic hot swap, clean rejection of corrupt model
dirs, metrics accounting.
"""

import json
import os
import shutil
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.estimator import GameTransformer
from photon_ml_tpu.serving.batcher import (BucketedBatcher, Request,
                                           densify_features,
                                           pow2_bucket_ladder,
                                           request_from_json)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.storage.model_io import (ModelLoadError,
                                            load_model_bundle)

N_USERS = 6
FEATURES = ["g0", "g1", "g2", "ux"]


def _write_fixture(path, n=250, seed=0):
    rng = np.random.default_rng(seed)
    uw = rng.normal(size=(N_USERS, 1)) * 1.5
    gw = np.asarray([0.8, -1.2, 0.5])
    records = []
    for i in range(n):
        u = int(rng.integers(0, N_USERS))
        xg = rng.normal(size=3)
        xu = rng.normal(size=1)
        logit = xg @ gw + xu @ uw[u]
        y = float(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        feats = [{"name": f"g{j}", "term": "", "value": float(xg[j])}
                 for j in range(3)]
        feats.append({"name": "ux", "term": "", "value": float(xu[0])})
        records.append({"uid": i, "response": y, "label": None,
                        "features": feats, "weight": None, "offset": None,
                        "metadataMap": {"userId": f"user{u}"}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)


def _train(tmp, seed):
    from photon_ml_tpu.cli import train as train_cli

    data = str(tmp / f"train{seed}.avro")
    _write_fixture(data, n=250, seed=seed)
    out = str(tmp / f"model{seed}")
    rc = train_cli.run([
        "--train-data", data, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--id-tags", "userId", "--coordinate-descent-iterations", "2",
        "--output-dir", out])
    assert rc == 0
    return out


@pytest.fixture(scope="module")
def model_dirs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serving")
    return _train(tmp, seed=1), _train(tmp, seed=2)


def _mk_requests(rng, k, offset=False):
    """Random requests over a mix of trained (user0..5) and unknown users."""
    reqs = []
    for i in range(k):
        feats = [{"name": f, "term": "", "value": float(rng.normal())}
                 for f in FEATURES]
        name = f"user{int(rng.integers(0, N_USERS + 2))}"  # +2 unknown
        reqs.append(Request(uid=i, features=feats, ids={"userId": name},
                            offset=float(rng.normal()) if offset else 0.0))
    return reqs


def _batch_reference(bundle, reqs):
    """Score the same requests through the BATCH path (GameTransformer on a
    GameData built with the same index/entity maps)."""
    x = densify_features(reqs, bundle.index_maps, len(reqs))
    ids = np.asarray([bundle.entity_indexes["userId"].get(r.ids["userId"])
                      for r in reqs], np.int64)
    data = GameData(y=np.zeros(len(reqs)), features=x,
                    id_tags={"userId": ids})
    return np.asarray(GameTransformer(bundle.model, bundle.task).score(data))


# ---------------------------------------------------------------------------
# batcher
# ---------------------------------------------------------------------------
class TestBatcher:
    def test_pow2_ladder(self):
        assert pow2_bucket_ladder(64) == (1, 2, 4, 8, 16, 32, 64)
        assert pow2_bucket_ladder(5) == (1, 2, 4, 8)
        assert pow2_bucket_ladder(1) == (1,)
        with pytest.raises(ValueError):
            pow2_bucket_ladder(0)

    def test_pow2_ladder_min_bucket(self):
        assert pow2_bucket_ladder(64, min_bucket=8) == (8, 16, 32, 64)
        assert pow2_bucket_ladder(16, min_bucket=16) == (16,)
        # min_bucket above the top bucket would yield a ladder that cannot
        # hold the promised max_batch — must raise, not silently shrink
        with pytest.raises(ValueError, match="min_bucket"):
            pow2_bucket_ladder(5, min_bucket=32)

    def test_plan_pads_and_splits(self):
        b = BucketedBatcher(max_batch=8)
        assert [(mb.bucket, mb.real_rows) for mb in b.plan(3)] == [(4, 3)]
        # 21 = 2 full top buckets + padded tail
        plan = b.plan(21)
        assert [(mb.bucket, mb.real_rows) for mb in plan] == \
            [(8, 8), (8, 8), (8, 5)]
        assert b.padding_rows(plan) == 3
        assert b.plan(0) == []

    def test_custom_buckets_and_overflow(self):
        b = BucketedBatcher(bucket_sizes=[4, 16])
        assert b.bucket_for(3) == 4
        assert b.bucket_for(5) == 16
        with pytest.raises(ValueError):
            b.bucket_for(17)

    def test_request_from_json_forms(self):
        r = request_from_json({"uid": 3, "features": [
            {"name": "a", "term": "t", "value": 1.5}, ["b", 2.0],
            ["c", "u", 3.0]], "ids": {"userId": "u1"}, "offset": 0.25})
        assert r.uid == 3 and r.offset == 0.25
        assert r.features[1] == {"name": "b", "term": "", "value": 2.0}
        assert r.features[2] == {"name": "c", "term": "u", "value": 3.0}
        with pytest.raises(ValueError):
            request_from_json({"features": [["only-name"]]})


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
class TestMetrics:
    def test_latency_histogram_percentiles(self):
        h = LatencyHistogram()
        for ms in (1, 1, 1, 1, 1, 1, 1, 1, 1, 100):
            h.record(ms / 1000.0)
        assert h.count == 10
        assert h.percentile(0.5) <= 0.005
        assert h.percentile(0.99) <= h.max == pytest.approx(0.1)
        assert h.snapshot()["p50_s"] < h.snapshot()["p99_s"]

    def test_padding_waste_and_counters(self):
        m = ServingMetrics()
        m.observe_batch(bucket=8, real_rows=5, seconds=0.001)
        m.observe_batch(bucket=4, real_rows=4, seconds=0.002)
        assert m.padding_waste_ratio == pytest.approx(3 / 12)
        m.inc("requests", 9)
        snap = m.snapshot()
        assert snap["counters"]["requests"] == 9
        assert snap["counters"]["batches"] == 2
        assert "bucket_8" in snap["latency"]
        json.loads(m.to_json())  # serializable

    def test_bucket_occupancy_gauges(self):
        m = ServingMetrics()
        m.observe_batch(bucket=8, real_rows=5, seconds=0.001)
        m.observe_batch(bucket=8, real_rows=8, seconds=0.001)
        m.observe_batch(bucket=2, real_rows=1, seconds=0.001)
        occ = m.snapshot()["bucket_occupancy"]
        assert occ["bucket_8"] == pytest.approx(13 / 16)
        assert occ["bucket_2"] == pytest.approx(0.5)

    def test_hot_set_and_miss_rate_gauges(self):
        m = ServingMetrics()
        snap = m.snapshot()
        assert snap["hot_set_hit_rate"] == 0.0  # no lookups yet
        m.inc("hot_hits", 6)
        m.inc("lru_hits", 1)
        m.inc("cold_fetches", 1)
        m.inc("entity_misses", 2)
        snap = m.snapshot()
        assert snap["hot_set_hit_rate"] == pytest.approx(0.6)
        assert snap["entity_miss_rate"] == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# typed model-load errors (satellite: clean failure for the swap path)
# ---------------------------------------------------------------------------
class TestModelLoadErrors:
    def test_missing_dir(self, tmp_path):
        with pytest.raises(ModelLoadError, match="does not exist"):
            load_model_bundle(str(tmp_path / "nope"))

    def test_no_metadata(self, tmp_path):
        d = tmp_path / "empty"
        d.mkdir()
        with pytest.raises(ModelLoadError, match="metadata.json"):
            load_model_bundle(str(d))

    def test_missing_index_map(self, model_dirs, tmp_path):
        broken = str(tmp_path / "no_idx")
        shutil.copytree(model_dirs[0], broken)
        for f in os.listdir(broken):
            if f.endswith((".idx", ".phidx")):
                os.remove(os.path.join(broken, f))
        with pytest.raises(ModelLoadError, match=r"\.idx"):
            load_model_bundle(broken)

    def test_missing_entity_index(self, model_dirs, tmp_path):
        broken = str(tmp_path / "no_entities")
        shutil.copytree(model_dirs[0], broken)
        for f in os.listdir(broken):
            if f.endswith(".entities.json"):
                os.remove(os.path.join(broken, f))
        with pytest.raises(ModelLoadError, match="entities.json"):
            load_model_bundle(broken)

    def test_corrupt_metadata(self, model_dirs, tmp_path):
        broken = str(tmp_path / "corrupt")
        shutil.copytree(model_dirs[0], broken)
        with open(os.path.join(broken, "best", "metadata.json"), "w") as f:
            f.write("{not json")
        with pytest.raises(ModelLoadError, match="corrupt|unreadable"):
            load_model_bundle(broken)

    def test_load_game_model_typed(self, tmp_path):
        from photon_ml_tpu.storage.model_io import load_game_model

        with pytest.raises(ModelLoadError):
            load_game_model(str(tmp_path), {}, {})


# ---------------------------------------------------------------------------
# serving == batch scoring (tentpole property test)
# ---------------------------------------------------------------------------
class TestServingParity:
    @pytest.mark.parametrize("device_capacity,lru_capacity", [
        (None, 4096),  # everything hot
        (3, 2),        # half the users cold, tiny LRU (forces evictions)
        (0, 1),        # everything cold: pure host-fallback scoring
    ])
    def test_bucketed_padded_matches_transformer(self, model_dirs,
                                                 device_capacity,
                                                 lru_capacity):
        """Property: for random batch sizes and entity mixes (trained, cold,
        and unknown entities), padded bucketed AOT scoring is BITWISE equal
        to unpadded GameTransformer batch scoring."""
        bundle = load_model_bundle(model_dirs[0])
        store = CoefficientStore.from_bundle(
            bundle, config=StoreConfig(device_capacity=device_capacity,
                                       lru_capacity=lru_capacity))
        engine = ScoringEngine(store, BucketedBatcher(max_batch=16))
        rng = np.random.default_rng(20260804)
        for trial in range(12):
            k = int(rng.integers(1, 14))
            reqs = _mk_requests(rng, k)
            got = engine.score_requests(reqs)
            want = _batch_reference(bundle, reqs)
            np.testing.assert_array_equal(
                got, want,
                err_msg=f"trial={trial} k={k} cap={device_capacity}")

    def test_offset_and_predict_mean(self, model_dirs):
        bundle = load_model_bundle(model_dirs[0])
        store = CoefficientStore.from_bundle(bundle)
        engine = ScoringEngine(store, BucketedBatcher(max_batch=8))
        rng = np.random.default_rng(5)
        reqs = _mk_requests(rng, 6, offset=True)
        offsets = np.asarray([r.offset for r in reqs])
        raw = engine.score_requests(reqs)
        np.testing.assert_array_equal(raw,
                                      _batch_reference(bundle, reqs) + offsets)
        mean = engine.score_requests(reqs, predict_mean=True)
        assert np.all((mean > 0) & (mean < 1))  # logistic inverse link
        np.testing.assert_allclose(mean, 1.0 / (1.0 + np.exp(-raw)))

    def test_cold_entity_lru_accounting(self, model_dirs):
        bundle = load_model_bundle(model_dirs[0])
        metrics = ServingMetrics()
        store = CoefficientStore.from_bundle(
            bundle, config=StoreConfig(device_capacity=2, lru_capacity=2),
            metrics=metrics)
        engine = ScoringEngine(store, BucketedBatcher(max_batch=8),
                               metrics=metrics)
        rng = np.random.default_rng(9)
        reqs = _mk_requests(rng, 8)
        engine.score_requests(reqs)
        engine.score_requests(reqs)  # repeats hit the LRU
        assert metrics.counter("cold_fetches") > 0
        assert metrics.counter("lru_hits") > 0
        assert metrics.counter("entity_misses") > 0  # the unknown users


# ---------------------------------------------------------------------------
# AOT compilation: warm once, zero recompiles after
# ---------------------------------------------------------------------------
class TestCompilationCache:
    def test_zero_recompiles_after_warm(self, model_dirs):
        bundle = load_model_bundle(model_dirs[0])
        engine = ScoringEngine(CoefficientStore.from_bundle(bundle),
                               BucketedBatcher(max_batch=8))
        n = engine.warm()
        assert n == len(engine.batcher.bucket_sizes) == 4
        rng = np.random.default_rng(3)
        for k in (1, 3, 3, 8, 5, 2, 7, 1):
            engine.score_requests(_mk_requests(rng, k))
        assert engine.compile_count == n  # acceptance: zero recompiles

    def test_lazy_compile_once_per_bucket(self, model_dirs):
        bundle = load_model_bundle(model_dirs[0])
        engine = ScoringEngine(CoefficientStore.from_bundle(bundle),
                               BucketedBatcher(max_batch=8))
        rng = np.random.default_rng(4)
        engine.score_requests(_mk_requests(rng, 3))  # bucket 4
        assert engine.compile_count == 1
        engine.score_requests(_mk_requests(rng, 4))  # same bucket
        assert engine.compile_count == 1
        engine.score_requests(_mk_requests(rng, 5))  # bucket 8
        assert engine.compile_count == 2


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------
class TestHotSwap:
    def test_swap_serves_new_model(self, model_dirs):
        dir1, dir2 = model_dirs
        bundle2 = load_model_bundle(dir2)
        engine = ScoringEngine(
            CoefficientStore.from_bundle(load_model_bundle(dir1)),
            BucketedBatcher(max_batch=8))
        engine.warm()
        swapper = HotSwapper(engine)
        rng = np.random.default_rng(11)
        reqs = _mk_requests(rng, 6)
        s1 = engine.score_requests(reqs)
        gen1 = engine.store.generation
        compiles_before = engine.compile_count

        assert swapper.swap(dir2) is True
        assert engine.store.generation != gen1
        s2 = engine.score_requests(reqs)
        assert not np.array_equal(s1, s2)  # different model now serving
        np.testing.assert_array_equal(s2, _batch_reference(bundle2, reqs))
        # both versions have identical shapes -> the swap reused every
        # compiled executable (signature-keyed cache)
        assert engine.compile_count == compiles_before
        assert engine.metrics.counter("swaps") == 1

    def test_corrupt_swap_keeps_old_version(self, model_dirs, tmp_path):
        dir1, _ = model_dirs
        engine = ScoringEngine(
            CoefficientStore.from_bundle(load_model_bundle(dir1)),
            BucketedBatcher(max_batch=8))
        swapper = HotSwapper(engine)
        rng = np.random.default_rng(13)
        reqs = _mk_requests(rng, 5)
        s1 = engine.score_requests(reqs)
        gen1 = engine.store.generation

        # missing dir
        assert swapper.swap(str(tmp_path / "missing")) is False
        # structurally broken dir (metadata.json is garbage)
        broken = str(tmp_path / "broken")
        shutil.copytree(dir1, broken)
        with open(os.path.join(broken, "best", "metadata.json"), "w") as f:
            f.write("not json at all")
        assert swapper.swap(broken) is False

        assert engine.store.generation == gen1  # old version still serving
        np.testing.assert_array_equal(engine.score_requests(reqs), s1)
        assert engine.metrics.counter("swap_failures") == 2
        assert engine.metrics.counter("swaps") == 0

    def test_swap_async(self, model_dirs):
        dir1, dir2 = model_dirs
        engine = ScoringEngine(
            CoefficientStore.from_bundle(load_model_bundle(dir1)),
            BucketedBatcher(max_batch=8))
        swapper = HotSwapper(engine)
        gen1 = engine.store.generation
        t = swapper.swap_async(dir2)
        t.join(timeout=60)
        assert not t.is_alive()
        assert engine.store.generation != gen1


# ---------------------------------------------------------------------------
# the JSON-lines driver
# ---------------------------------------------------------------------------
class TestServeCli:
    def test_stream_with_swap_and_metrics(self, model_dirs, tmp_path, capsys):
        from photon_ml_tpu.cli import serve as serve_cli

        dir1, dir2 = model_dirs
        rng = np.random.default_rng(17)
        lines = []
        for i in range(5):
            feats = [[f, float(rng.normal())] for f in FEATURES]
            lines.append(json.dumps({
                "uid": i, "features": feats,
                "ids": {"userId": f"user{i % N_USERS}"}}))
        lines.append("")  # flush
        lines.append(json.dumps({"cmd": "metrics"}))
        lines.append(json.dumps({"cmd": "swap", "model_dir": dir2}))
        lines.append(json.dumps({
            "uid": 99, "features": [[f, 0.5] for f in FEATURES],
            "ids": {"userId": "user0"}}))
        req_file = tmp_path / "requests.jsonl"
        req_file.write_text("\n".join(lines) + "\n")
        metrics_file = str(tmp_path / "metrics.json")

        rc = serve_cli.run(["--model-dir", dir1, "--max-batch", "8",
                            "--requests", str(req_file),
                            "--metrics-json", metrics_file])
        assert rc == 0
        out = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
        scores = [o for o in out if "score" in o]
        assert [o["uid"] for o in scores] == [0, 1, 2, 3, 4, 99]
        assert all(np.isfinite(o["score"]) for o in scores)
        swaps = [o for o in out if "swap" in o]
        assert len(swaps) == 1
        assert swaps[0]["swap"] == "ok"
        assert swaps[0]["version"] == dir2
        assert swaps[0]["delta_version"] == 0  # fresh generation
        metrics_lines = [o for o in out if "counters" in o]
        assert len(metrics_lines) == 1
        exported = json.load(open(metrics_file))
        assert exported["counters"]["requests"] == 6
        assert exported["counters"]["swaps"] == 1

    def test_rejects_broken_model_dir(self, tmp_path, capsys):
        from photon_ml_tpu.cli import serve as serve_cli

        d = tmp_path / "not_a_model"
        d.mkdir()
        assert serve_cli.run(["--model-dir", str(d)]) == 1


# ---------------------------------------------------------------------------
# bench hook
# ---------------------------------------------------------------------------
def test_bench_serving_smoke(tmp_path):
    import bench

    out = bench.run_serving_bench(n_entities=50, d=4, n_requests=40,
                                  max_batch=8, device_capacity=10,
                                  out_path=str(tmp_path / "b.json"))
    assert out["metric"] == "serving_p99_latency"
    assert out["single_request"]["p50_s"] > 0
    assert out["stream"]["qps"] > 0
    assert 0 <= out["stream"]["padding_waste_ratio"] < 1
    assert out["warm"]["executables"] == 4
    assert out["compiles_after_warm"] == 0  # acceptance: flat after warm
    on_disk = json.load(open(tmp_path / "b.json"))
    assert on_disk["value"] == out["value"]


def test_bench_serving_zipf_smoke(tmp_path):
    import bench

    out = bench.run_serving_bench(n_entities=60, d=4, n_requests=48,
                                  max_batch=8, device_capacity=12,
                                  zipf=1.2, deadline_us=100.0,
                                  rebalance_every=16,
                                  out_path=str(tmp_path / "z.json"))
    assert out["zipf"] == 1.2
    # the three cross-PR trajectory numbers are recorded top-level
    assert 0 <= out["padding_waste_ratio"] < 1
    assert 0 <= out["entity_miss_rate"] < 1
    assert out["p99_s"] > 0
    assert out["hot_set"]["rebalances"] >= 3
    assert out["hot_set"]["promotions"] >= 1  # skew moved residency
    assert out["compiles_after_warm"] == 0
    flushes = out["flushes"]
    assert flushes["full"] + flushes["deadline"] + flushes["forced"] >= 1
