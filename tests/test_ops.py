"""Pallas fused-kernel parity tests (interpret mode on the CPU test mesh).

The kernels must be bit-for-bit the same *math* as GLMObjective's reference
path; tolerances cover f64 summation-order differences only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import (logistic_loss, poisson_loss,
                                       smoothed_hinge_loss, squared_loss)
from photon_ml_tpu.core.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.ops.fused_glm import (_pad_rows, _pick_block_rows, fused_hvp,
                                         fused_value_and_grad)

LOSSES = [logistic_loss, squared_loss, poisson_loss, smoothed_hinge_loss]


def _batch(rng, loss, n=100, d=12):
    x = rng.normal(size=(n, d)) * 0.3
    if loss is logistic_loss or loss is smoothed_hinge_loss:
        y = (rng.random(n) < 0.5).astype(np.float64)
    elif loss is poisson_loss:
        y = rng.poisson(2.0, size=n).astype(np.float64)
    else:
        y = rng.normal(size=n)
    weight = rng.uniform(0.5, 2.0, size=n)
    weight[: n // 10] = 0.0  # padded/masked rows
    return DenseBatch(x=jnp.asarray(x), y=jnp.asarray(y),
                      offset=jnp.asarray(rng.normal(size=n) * 0.1),
                      weight=jnp.asarray(weight))


def _norm(rng, d):
    return NormalizationContext(factors=jnp.asarray(rng.uniform(0.5, 2.0, size=d)),
                                shifts=jnp.asarray(rng.normal(size=d) * 0.2))


@pytest.mark.parametrize("loss", LOSSES, ids=lambda l: l.name)
@pytest.mark.parametrize("normed", [False, True], ids=["nonorm", "norm"])
def test_value_grad_parity(rng, loss, normed):
    batch = _batch(rng, loss)
    norm = _norm(rng, batch.dim) if normed else no_normalization()
    obj = GLMObjective(loss=loss, reg=Regularization(l2=0.1), norm=norm)
    w = jnp.asarray(rng.normal(size=batch.dim) * 0.2)

    ref_val, ref_grad = obj.value_and_grad(w, batch)
    w_eff = norm.effective_coefficients(w)
    val, g_raw, r_sum = fused_value_and_grad(loss, w_eff, batch,
                                             margin_shift=norm.margin_shift(w),
                                             block_rows=32, interpret=True)
    got_val = val + obj.l2_term(w)
    got_grad = obj._chain(g_raw, r_sum) + 0.1 * w
    np.testing.assert_allclose(got_val, ref_val, rtol=1e-12)
    np.testing.assert_allclose(got_grad, ref_grad, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("loss", [logistic_loss, poisson_loss], ids=lambda l: l.name)
def test_hvp_parity(rng, loss):
    batch = _batch(rng, loss)
    norm = _norm(rng, batch.dim)
    obj = GLMObjective(loss=loss, reg=Regularization(l2=0.05), norm=norm)
    w = jnp.asarray(rng.normal(size=batch.dim) * 0.2)
    v = jnp.asarray(rng.normal(size=batch.dim))

    ref = obj.hvp(w, batch, v)
    hv_raw, q_sum = fused_hvp(loss, norm.effective_coefficients(w),
                              norm.effective_coefficients(v), batch,
                              margin_shift=norm.margin_shift(w),
                              v_shift=norm.margin_shift(v),
                              block_rows=32, interpret=True)
    got = obj._chain(hv_raw, q_sum) + 0.05 * v
    np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)


def test_row_padding_is_invisible(rng):
    batch = _batch(rng, squared_loss, n=70)  # 70 % 32 != 0 -> padded to 96
    padded = _pad_rows(batch, 32)
    assert padded.num_examples == 96
    assert float(jnp.sum(padded.weight[70:])) == 0.0
    w = jnp.asarray(rng.normal(size=batch.dim))
    obj = GLMObjective(loss=squared_loss)
    v_ref, g_ref = obj.value_and_grad(w, batch)
    v, g, r = fused_value_and_grad(squared_loss, w, batch, block_rows=32, interpret=True)
    np.testing.assert_allclose(v, v_ref, rtol=1e-12)
    np.testing.assert_allclose(g, g_ref, rtol=1e-10)


def test_ineligible_raises(rng):
    batch = _batch(rng, squared_loss, n=16)
    w = jnp.asarray(rng.normal(size=batch.dim))
    with pytest.raises(ValueError, match="eligible"):
        fused_value_and_grad(squared_loss, w, batch)  # CPU, unaligned dim


def test_pick_block_rows():
    assert _pick_block_rows(10_000, 128) % 128 == 0
    assert _pick_block_rows(4, 128) >= 128
    # huge d -> smallest legal block, the lane granule
    assert _pick_block_rows(10_000, 1 << 20) == 128


@pytest.mark.parametrize("n", [4, 100, 2000, 2048, 5000, 131072])
@pytest.mark.parametrize("d", [128, 512, 4096])
def test_pick_block_rows_idempotent_under_padding(n, d):
    """pick(pad(n)) must divide the padded n — otherwise the coordinate's
    one-time pre-pad still re-pads (full X copy) inside every jitted call."""
    bn = _pick_block_rows(n, d)
    n_pad = n + (-n) % bn
    assert _pick_block_rows(n_pad, d) == bn
    assert n_pad % bn == 0


def test_objective_fused_flag_cpu_fallback(rng):
    """fused=True on CPU uses the XLA fallback — same results, still jittable."""
    batch = _batch(rng, logistic_loss)
    w = jnp.asarray(rng.normal(size=batch.dim) * 0.1)
    plain = GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.01))
    fused = GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.01), fused=True)
    v1, g1 = jax.jit(plain.value_and_grad)(w, batch)
    v2, g2 = jax.jit(fused.value_and_grad)(w, batch)
    np.testing.assert_allclose(v1, v2, rtol=1e-12)
    np.testing.assert_allclose(g1, g2, rtol=1e-12)
    np.testing.assert_allclose(plain.hvp(w, batch, g1), fused.hvp(w, batch, g2),
                               rtol=1e-12)


def test_tpu_checklist_pallas_snippet_interpret():
    """The one-command TPU capture (tools/tpu_checklist.py) embeds a
    non-interpret pallas parity snippet that only ever runs on real
    hardware — keep its MATH pinned green here by executing it in
    interpret mode (same kernels, interpreter backend)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import tools.tpu_checklist as tc

    def patch(src, old, new):
        # a reworded snippet must fail HERE (stale patch), not as a
        # confusing eligibility/kernel error after a silent no-op replace
        out = src.replace(old, new)
        assert out != src, f"snippet no longer contains: {old!r}"
        return out

    src = tc._PALLAS_SRC
    src = patch(src, "fused_value_and_grad(loss, jnp.asarray(w), b)",
                "fused_value_and_grad(loss, jnp.asarray(w), b, interpret=True)")
    src = patch(src, "fused_hvp(loss, jnp.asarray(w), jnp.asarray(v), b)",
                "fused_hvp(loss, jnp.asarray(w), jnp.asarray(v), b, interpret=True)")
    src = patch(src, "assert eligible(b)",
                "assert eligible(b, interpret=True)")
    src = patch(src, "fused_value_and_grad(logistic_loss, w16, b)",
                "fused_value_and_grad(logistic_loss, w16, b, interpret=True)")
    captured = {}
    src = patch(src, "print(json.dumps(out))", "captured['out'] = out")
    g = {"captured": captured}
    exec(src, g)
    out = captured["out"]
    assert out["pass"], out
    assert {c["loss"] for c in out["cases"]} == {"logistic", "squared",
                                                "poisson", "logistic_bf16"}


@pytest.mark.parametrize("loss", [logistic_loss, poisson_loss], ids=lambda l: l.name)
def test_bf16_storage_parity_normalized(rng, loss):
    """bf16 storage through the FULL objective fused path WITH a non-trivial
    NormalizationContext — the narrowing cast applies to the norm-scaled
    effective coefficients and the f32 margin_shift rides beside bf16
    operands, exactly where storage width and normalization interact.
    Parity vs the XLA mixed path (fused=False) on identical inputs."""
    n, d = 96, 16
    x32 = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    y = ((rng.random(n) < 0.5).astype(np.float32) if loss is logistic_loss
         else rng.poisson(2.0, size=n).astype(np.float32))
    weight = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    weight[: n // 10] = 0.0
    batch = DenseBatch(x=jnp.asarray(x32).astype(jnp.bfloat16),
                       y=jnp.asarray(y),
                       offset=jnp.asarray((rng.normal(size=n) * 0.1)
                                          .astype(np.float32)),
                       weight=jnp.asarray(weight))
    norm = NormalizationContext(
        factors=jnp.asarray(rng.uniform(0.5, 2.0, size=d).astype(np.float32)),
        shifts=jnp.asarray((rng.normal(size=d) * 0.2).astype(np.float32)))
    w = jnp.asarray((rng.normal(size=d) * 0.2).astype(np.float32))
    v = jnp.asarray(rng.normal(size=d).astype(np.float32))

    plain = GLMObjective(loss=loss, reg=Regularization(l2=0.05), norm=norm)
    eff = norm.effective_coefficients(w).astype(jnp.bfloat16)
    val, g_raw, r_sum = fused_value_and_grad(
        loss, eff, batch, margin_shift=norm.margin_shift(w),
        block_rows=32, interpret=True)
    got_val = val + plain.l2_term(w)
    got_grad = plain._chain(g_raw, r_sum) + 0.05 * w
    ref_val, ref_grad = plain.value_and_grad(w, batch)
    np.testing.assert_allclose(np.asarray(got_val), np.asarray(ref_val),
                               rtol=2e-2)
    np.testing.assert_allclose(np.asarray(got_grad), np.asarray(ref_grad),
                               rtol=6e-2, atol=6e-2)

    eff_v = norm.effective_coefficients(v).astype(jnp.bfloat16)
    hv_raw, q_sum = fused_hvp(loss, eff, eff_v, batch,
                              margin_shift=norm.margin_shift(w),
                              v_shift=norm.margin_shift(v),
                              block_rows=32, interpret=True)
    got_hvp = plain._chain(hv_raw, q_sum) + 0.05 * v
    np.testing.assert_allclose(np.asarray(got_hvp),
                               np.asarray(plain.hvp(w, batch, v)),
                               rtol=6e-2, atol=6e-2)


@pytest.mark.parametrize("loss", [logistic_loss, poisson_loss], ids=lambda l: l.name)
def test_bf16_storage_parity_with_xla_mixed_path(rng, loss):
    """Narrow (bf16) storage now keeps the pallas path: kernels take
    storage-width MXU operands with f32 accumulation — the same contract as
    DenseBatch.margins / _xt_dot on the XLA mixed path.  Parity here is
    against that XLA mixed path (fused=False), tolerances at bf16 scale."""
    n, d = 96, 16
    x32 = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    y = ((rng.random(n) < 0.5).astype(np.float32) if loss is logistic_loss
         else rng.poisson(2.0, size=n).astype(np.float32))
    weight = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    weight[: n // 10] = 0.0
    offset = (rng.normal(size=n) * 0.1).astype(np.float32)
    batch = DenseBatch(x=jnp.asarray(x32).astype(jnp.bfloat16),
                       y=jnp.asarray(y), offset=jnp.asarray(offset),
                       weight=jnp.asarray(weight))
    w = jnp.asarray((rng.normal(size=d) * 0.2).astype(np.float32))
    v = jnp.asarray(rng.normal(size=d).astype(np.float32))

    plain = GLMObjective(loss=loss, reg=Regularization(l2=0.05))

    ref_val, ref_grad = plain.value_and_grad(w, batch)
    val, g_raw, r_sum = fused_value_and_grad(
        loss, w.astype(jnp.bfloat16), batch, block_rows=32, interpret=True)
    got_val = val + plain.l2_term(w)
    got_grad = g_raw + 0.05 * w
    np.testing.assert_allclose(np.asarray(got_val), np.asarray(ref_val),
                               rtol=2e-2)
    np.testing.assert_allclose(np.asarray(got_grad), np.asarray(ref_grad),
                               rtol=5e-2, atol=5e-2)

    ref_hvp = plain.hvp(w, batch, v)
    hv_raw, q_sum = fused_hvp(loss, w.astype(jnp.bfloat16),
                              v.astype(jnp.bfloat16), batch,
                              block_rows=32, interpret=True)
    got_hvp = hv_raw + 0.05 * v
    np.testing.assert_allclose(np.asarray(got_hvp), np.asarray(ref_hvp),
                               rtol=5e-2, atol=5e-2)


def test_bf16_block_rows_doubled():
    """bf16 X tiles carry 2x the rows in the same VMEM budget."""
    assert _pick_block_rows(1 << 20, 256, 2) == 2 * _pick_block_rows(
        1 << 20, 256, 4)


def test_fused_eligible_dtype_gate(rng, monkeypatch):
    """Isolate the dtype guard: with kernel eligibility stubbed true,
    narrow float storage (bf16 x / f32 w) passes, widening mixes (f64 x /
    f32 w) stay on the XLA path (promotion would change solver numerics)."""
    from photon_ml_tpu.ops import fused_glm

    monkeypatch.setattr(fused_glm, "eligible", lambda b, interpret=False: True)
    batch64 = _batch(rng, squared_loss)  # f64 x on the f64 test mesh
    w32 = jnp.asarray(rng.normal(size=batch64.dim).astype(np.float32))
    assert not GLMObjective._fused_eligible(batch64, w32)
    batch16 = DenseBatch(x=batch64.x.astype(jnp.bfloat16),
                         y=batch64.y.astype(jnp.float32),
                         offset=batch64.offset.astype(jnp.float32),
                         weight=batch64.weight.astype(jnp.float32))
    assert GLMObjective._fused_eligible(batch16, w32)
    # uniform dtypes always pass the guard
    assert GLMObjective._fused_eligible(batch64, batch64.x[0])
