"""ShardMapObjective parity: explicit-SPMD objective == single-device math.

The mesh path must be bit-compatible (up to f64 reduction order) with the
plain objective — the chip-count-invariance property (SURVEY.md §4: the
reference's distributed-vs-local parity tests).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import logistic_loss, poisson_loss
from photon_ml_tpu.core.normalization import NormalizationContext
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.parallel.fixed import ShardMapObjective
from photon_ml_tpu.parallel.mesh import make_mesh, replicate, shard_batch


def _make(rng, n=256, d=12, normed=True):
    x = rng.normal(size=(n, d)) * 0.4
    y = (rng.random(n) < 0.5).astype(np.float64)
    batch = DenseBatch(x=jnp.asarray(x), y=jnp.asarray(y),
                       offset=jnp.asarray(rng.normal(size=n) * 0.1),
                       weight=jnp.asarray(rng.uniform(0.5, 2.0, size=n)))
    norm = (NormalizationContext(factors=jnp.asarray(rng.uniform(0.5, 2.0, size=d)),
                                 shifts=jnp.asarray(rng.normal(size=d) * 0.1))
            if normed else None)
    obj = GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.05),
                       **({"norm": norm} if norm else {}))
    return obj, batch


class TestShardMapObjective:
    @pytest.mark.parametrize("normed", [False, True], ids=["nonorm", "norm"])
    def test_value_grad_parity(self, rng, devices, normed):
        obj, batch = _make(rng, normed=normed)
        mesh = make_mesh(n_data=8)
        sharded = shard_batch(batch, mesh)
        sm = ShardMapObjective(obj, mesh)
        w = jnp.asarray(rng.normal(size=batch.dim) * 0.2)

        v_ref, g_ref = obj.value_and_grad(w, batch)
        v_sm, g_sm = jax.jit(sm.value_and_grad)(w, sharded)
        np.testing.assert_allclose(v_sm, v_ref, rtol=1e-12)
        np.testing.assert_allclose(g_sm, g_ref, rtol=1e-10)

    def test_hvp_parity(self, rng, devices):
        obj, batch = _make(rng)
        mesh = make_mesh(n_data=8)
        sharded = shard_batch(batch, mesh)
        sm = ShardMapObjective(obj, mesh)
        w = jnp.asarray(rng.normal(size=batch.dim) * 0.2)
        v = jnp.asarray(rng.normal(size=batch.dim))
        np.testing.assert_allclose(jax.jit(sm.hvp)(w, sharded, v),
                                   obj.hvp(w, batch, v), rtol=1e-10)

    @pytest.mark.parametrize("opt", ["LBFGS", "TRON"])
    def test_full_solve_matches_single_device(self, rng, devices, opt):
        """The whole jitted solver over the shard_map objective converges to
        the same optimum as the plain single-device solve."""
        from photon_ml_tpu.types import OptimizerType

        obj, batch = _make(rng, n=512, normed=False)
        if opt == "TRON":
            obj = obj.replace(loss=poisson_loss)
            batch = batch.replace(y=jnp.asarray(
                np.random.default_rng(0).poisson(1.5, size=512).astype(np.float64)))
        mesh = make_mesh(n_data=8)
        sharded = shard_batch(batch, mesh)
        w0 = jnp.zeros(batch.dim)

        plain = jax.jit(make_solver(obj, OptimizerType[opt]))(w0, batch)
        sm = ShardMapObjective(obj, mesh)
        dist = jax.jit(make_solver(sm, OptimizerType[opt]),
                       out_shardings=replicate(mesh))(w0, sharded)
        np.testing.assert_allclose(dist.w, plain.w, atol=1e-8)
        np.testing.assert_allclose(float(dist.value), float(plain.value), rtol=1e-10)

    def test_uneven_rows_padded(self, rng, devices):
        """n not divisible by the mesh: shard_batch pads with weight-0 rows
        and results are unchanged."""
        obj, batch = _make(rng, n=250)  # 250 % 8 != 0
        mesh = make_mesh(n_data=8)
        sharded = shard_batch(batch, mesh)
        sm = ShardMapObjective(obj, mesh)
        w = jnp.asarray(rng.normal(size=batch.dim) * 0.1)
        v_ref, g_ref = obj.value_and_grad(w, batch)
        v_sm, g_sm = jax.jit(sm.value_and_grad)(w, sharded)
        np.testing.assert_allclose(v_sm, v_ref, rtol=1e-12)
        np.testing.assert_allclose(g_sm, g_ref, rtol=1e-10)
