"""photonscope tests (photon_ml_tpu/obs/*, the ServingMetrics facade, the
Timed/EventEmitter bridges, and the end-to-end traced CLI paths).

The contracts under test (ISSUE 5):
  - Tracer: span nesting/ordering within and across concurrent threads,
    ring-buffer wraparound (newest spans win, no tearing), Chrome
    ``trace_event`` export round-trip (valid JSON, monotonic ts, pid/tid
    present, children contained in parents), instant events, the opt-in
    device fence.
  - MetricsRegistry: counter/gauge/histogram families, label aliasing
    (keyword order never splits a series), Prometheus text exposition
    (golden), JSON snapshot, concurrent increments.
  - JaxRuntimeProbe: compile-counter parity with
    ``ScoringEngine.compile_count``, transfer-byte accounting at the
    ``utils/transfer`` chunk path.
  - ServingMetrics facade: ``snapshot()`` wire format byte-compatible with
    PR 4 (key set + semantics BENCH_SERVING history depends on).
  - One trace through ``CoordinateDescent.run`` (2 coordinates x 2
    iterations, nested solve/score spans) and one through ``cli/serve.py``
    (submit -> flush -> resolve -> execute -> respond), both valid Chrome
    trace JSON.
"""

import io
import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu import obs
from photon_ml_tpu.obs.registry import MetricsRegistry
from photon_ml_tpu.obs.trace import Tracer
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.utils import Event, EventEmitter, Timed


@pytest.fixture
def tracer():
    """A fresh enabled tracer installed as the process default; restored
    (and tracing re-disabled) afterwards so tests never leak spans."""
    t = Tracer(capacity=512, enabled=True)
    prev = obs.set_tracer(t)
    try:
        yield t
    finally:
        obs.set_tracer(prev)


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------
class TestTracer:
    def test_span_nesting_and_ordering(self, tracer):
        with obs.span("outer", who="a"):
            with obs.span("mid"):
                with obs.span("inner"):
                    pass
            with obs.span("mid2"):
                pass
        recs = {r["name"]: r for r in tracer.records()}
        assert set(recs) == {"outer", "mid", "inner", "mid2"}
        assert recs["mid"]["parent"] == recs["outer"]["id"]
        assert recs["inner"]["parent"] == recs["mid"]["id"]
        assert recs["mid2"]["parent"] == recs["outer"]["id"]
        assert recs["outer"]["parent"] == 0
        assert recs["outer"]["attrs"] == {"who": "a"}
        # children record (at exit) before parents; ts says inner started last
        assert recs["inner"]["ts_ns"] >= recs["mid"]["ts_ns"] >= \
            recs["outer"]["ts_ns"]

    def test_disabled_is_silent_noop(self):
        t = Tracer(capacity=16, enabled=False)
        prev = obs.set_tracer(t)
        try:
            with obs.span("nothing", k=1):
                obs.instant("tick")
        finally:
            obs.set_tracer(prev)
        assert t.records() == []

    def test_ring_wraparound_keeps_newest(self, tracer):
        small = Tracer(capacity=8, enabled=True)
        for i in range(20):
            with small.span(f"s{i}"):
                pass
        recs = small.records()
        assert len(recs) == 8  # exactly the ring capacity survives
        assert [r["name"] for r in recs] == [f"s{i}" for i in range(12, 20)]
        # export is still valid JSON with monotonic ts
        trace = json.loads(json.dumps(small.chrome_trace()))
        ts = [e["ts"] for e in trace["traceEvents"]]
        assert ts == sorted(ts)

    def test_concurrent_threads_nest_independently(self, tracer):
        n_threads, n_spans = 8, 30
        barrier = threading.Barrier(n_threads)

        def work(k):
            barrier.wait()
            for i in range(n_spans):
                with obs.span("parent", thread=k, i=i):
                    with obs.span("child", thread=k, i=i):
                        pass

        threads = [threading.Thread(target=work, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        recs = tracer.records()
        assert len(recs) == n_threads * n_spans * 2
        parents = {r["id"]: r for r in recs if r["name"] == "parent"}
        children = [r for r in recs if r["name"] == "child"]
        assert len(children) == n_threads * n_spans
        for c in children:
            p = parents[c["parent"]]  # every child belongs to a parent...
            assert p["tid"] == c["tid"]  # ...on its OWN thread
            assert p["attrs"]["thread"] == c["attrs"]["thread"]
            assert p["attrs"]["i"] == c["attrs"]["i"]
            assert p["ts_ns"] <= c["ts_ns"]
            assert c["ts_ns"] + c["dur_ns"] <= p["ts_ns"] + p["dur_ns"]

    def test_chrome_export_round_trip(self, tracer):
        with obs.span("a", x=1):
            with obs.span("b"):
                pass
        obs.instant("evt", y=2)
        raw = json.dumps(tracer.chrome_trace())
        trace = json.loads(raw)  # valid JSON
        meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        events = [e for e in trace["traceEvents"] if e["ph"] != "M"]
        assert len(events) == 3
        pid = os.getpid()
        # photonpulse merge keys on the metadata rows: a process_name for
        # the Perfetto process lane, thread_name per recording thread
        assert any(m["name"] == "process_name" for m in meta)
        assert any(m["name"] == "thread_name" for m in meta)
        for m in meta:
            assert m["pid"] == pid and m["ts"] == 0
        for e in events:
            assert e["pid"] == pid and e["tid"] and "ts" in e
            assert e["ph"] in ("X", "i")
            if e["ph"] == "X":
                assert e["dur"] >= 0
        ts = [e["ts"] for e in trace["traceEvents"]]
        assert ts == sorted(ts)  # metadata (ts 0) first, then monotonic
        by_name = {e["name"]: e for e in events}
        assert by_name["b"]["args"]["parent_id"] == \
            by_name["a"]["args"]["span_id"]
        assert by_name["evt"]["ph"] == "i" and by_name["evt"]["args"]["y"] == 2
        assert trace["otherData"]["pid"] == pid

    def test_device_sync_runs_fence(self, tracer):
        fences = []
        tracer.set_device_fence(lambda: fences.append(1))
        with obs.span("plain"):
            pass
        assert fences == []  # no fence unless asked
        with obs.span("synced", device_sync=True):
            pass
        assert len(fences) == 2  # entry + exit

    def test_clear(self, tracer):
        with obs.span("x"):
            pass
        assert tracer.records()
        tracer.clear()
        assert tracer.records() == []


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counters_and_label_aliasing(self):
        r = MetricsRegistry()
        r.inc("requests_total", bucket="64", model="a")
        r.inc("requests_total", 2, model="a", bucket="64")  # kwarg order!
        r.inc("requests_total", bucket="32")
        assert r.counter("requests_total", bucket="64", model="a") == 3
        assert r.counter("requests_total", bucket="32") == 1
        assert r.counter("requests_total") == 0  # unlabeled is its own series
        series = r.counter_series("requests_total")
        assert len(series) == 2  # aliased labels collapsed

    def test_gauges_set_and_add(self):
        r = MetricsRegistry()
        r.set_gauge("temp", 3.5, zone="hbm")
        r.set_gauge("temp", 4.0, zone="hbm")
        r.add_gauge("phase_s", 1.0, phase="warm")
        r.add_gauge("phase_s", 0.5, phase="warm")
        assert r.gauge("temp", zone="hbm") == 4.0
        assert r.gauge("phase_s", phase="warm") == pytest.approx(1.5)
        assert r.gauge("missing") is None

    def test_histograms(self):
        r = MetricsRegistry()
        for ms in (1, 2, 3):
            r.observe("lat", ms / 1000.0, key="bucket_8")
        snap = r.histogram_snapshot("lat", key="bucket_8")
        assert snap["count"] == 3
        assert 0 < snap["p50_s"] <= snap["p99_s"] <= snap["max_s"]
        series = r.histogram_series("lat")
        assert list(series) == [(("key", "bucket_8"),)]

    def test_json_snapshot(self):
        r = MetricsRegistry()
        r.inc("c", 2, a="1")
        r.set_gauge("g", 0.5)
        r.observe("h", 0.001)
        snap = json.loads(r.to_json())
        assert snap["counters"] == {'c{a="1"}': 2}
        assert snap["gauges"] == {"g": 0.5}
        assert snap["histograms"]["h"]["count"] == 1

    def test_prometheus_golden(self):
        r = MetricsRegistry()
        r.inc("requests_total", 3, bucket="64")
        r.inc("requests_total", 1, bucket="8")
        r.set_gauge("hot.rate", 0.75)  # "." sanitized to "_"
        text = r.to_prometheus()
        lines = text.splitlines()
        assert lines[0] == "# TYPE requests_total counter"
        assert 'requests_total{bucket="8"} 1' in lines
        assert 'requests_total{bucket="64"} 3' in lines
        assert "# TYPE hot_rate gauge" in lines
        assert "hot_rate 0.75" in lines
        assert text.endswith("\n")

    def test_prometheus_histogram_exposition(self):
        r = MetricsRegistry()
        r.observe("lat_s", 0.001, key="b8")
        text = r.to_prometheus()
        assert "# TYPE lat_s histogram" in text
        assert 'lat_s_bucket{key="b8",le="+Inf"} 1' in text
        assert 'lat_s_count{key="b8"} 1' in text
        assert 'lat_s_sum{key="b8"} 0.001' in text
        # cumulative: the 1.024ms bin already holds the observation
        assert 'lat_s_bucket{key="b8",le="0.001024"} 1' in text
        assert 'lat_s_bucket{key="b8",le="0.000512"} 0' in text

    def test_concurrent_increments_sum(self):
        r = MetricsRegistry()
        n_threads, n_incs = 8, 2000

        def work():
            for _ in range(n_incs):
                r.inc("hits", shard="s")

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert r.counter("hits", shard="s") == n_threads * n_incs


# ---------------------------------------------------------------------------
# jax runtime probe
# ---------------------------------------------------------------------------
@pytest.fixture
def fresh_registry():
    """A fresh process-default registry (the lazily-bound probe target);
    restored afterwards."""
    r = MetricsRegistry()
    prev = obs.set_registry(r)
    try:
        yield r
    finally:
        obs.set_registry(prev)


class TestProbe:
    def test_compile_span_counts_and_times(self, fresh_registry):
        probe = obs.get_probe()
        with probe.compile_span("test.site", bucket=4):
            pass
        assert probe.compile_count("test.site") == 1
        assert probe.compile_count() == 1
        hist = fresh_registry.histogram_snapshot("jax_compile_seconds",
                                                 site="test.site")
        assert hist["count"] == 1

    def test_compile_counter_parity_with_engine(self, fresh_registry):
        from test_serving_async import _engine, _req

        probe = obs.get_probe()
        eng, _, _ = _engine(max_batch=4)
        assert probe.compile_count("serving.engine") == eng.compile_count > 0
        rng = np.random.default_rng(3)
        eng.score_requests([_req(rng, uid=i) for i in range(5)])
        # zero-recompile guarantee holds in BOTH ledgers
        assert probe.compile_count("serving.engine") == eng.compile_count

    def test_transfer_accounting_chunked(self, fresh_registry, monkeypatch):
        from photon_ml_tpu.utils.transfer import chunked_device_put

        monkeypatch.setenv("PHOTON_CHUNKED_PUT_MIN_MB", "0.001")
        arr = np.ones((64, 128), np.float32)  # 32KB > 1KB threshold
        out = chunked_device_put(arr, chunk_bytes=8 * 1024)
        np.testing.assert_array_equal(np.asarray(out), arr)
        probe = obs.get_probe()
        assert probe.transfer_bytes("h2d") == arr.nbytes
        n_chunks = fresh_registry.counter("jax_transfers_total",
                                          direction="h2d", site="chunked_put")
        assert n_chunks == 4  # 32KB in 8KB chunks

    def test_compile_cache_gauge(self, fresh_registry, monkeypatch):
        from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

        monkeypatch.setenv("PHOTON_COMPILE_CACHE", "0")
        assert enable_compilation_cache() is None
        assert fresh_registry.gauge("xla_compile_cache_enabled") == 0


# ---------------------------------------------------------------------------
# ServingMetrics facade: PR-4 wire-format regression
# ---------------------------------------------------------------------------
PR4_SNAPSHOT_KEYS = {
    "counters", "qps", "uptime_s", "padding_waste_ratio",
    "padded_rows_launched", "real_rows_launched", "bucket_occupancy",
    "hot_set_hit_rate", "entity_miss_rate", "latency", "phases_s",
}
PR4_HISTOGRAM_KEYS = {"count", "mean_s", "p50_s", "p99_s", "min_s", "max_s"}


class TestServingMetricsFacade:
    def test_snapshot_keys_byte_compatible_with_pr4(self):
        m = ServingMetrics()
        m.inc("requests", 9)
        m.observe_batch(bucket=8, real_rows=5, seconds=0.001)
        m.observe_latency("request", 0.002)
        m.phase("warm", 0.5)
        snap = m.snapshot()
        assert set(snap) == PR4_SNAPSHOT_KEYS
        assert set(snap["latency"]["bucket_8"]) == PR4_HISTOGRAM_KEYS
        assert snap["counters"] == {"requests": 9, "batches": 1,
                                    "scored_samples": 5}
        assert snap["padded_rows_launched"] == 8
        assert snap["real_rows_launched"] == 5
        assert snap["padding_waste_ratio"] == pytest.approx(3 / 8)
        assert snap["bucket_occupancy"] == {"bucket_8": pytest.approx(5 / 8)}
        assert snap["phases_s"] == {"warm": pytest.approx(0.5)}
        json.dumps(snap)  # wire-serializable

    def test_bench_serving_fields_still_derivable(self):
        """The exact counter names BENCH_SERVING diffs across epochs."""
        m = ServingMetrics()
        for name in ("hot_hits", "lru_hits", "cold_fetches", "entity_misses",
                     "batches", "flushes_full", "flushes_deadline",
                     "flushes_forced", "hot_promotions", "hot_demotions",
                     "rebalances"):
            m.inc(name)
        snap = m.snapshot()
        for name in ("hot_hits", "entity_misses", "flushes_full",
                     "hot_promotions", "rebalances"):
            assert snap["counters"][name] == 1
        assert snap["hot_set_hit_rate"] == pytest.approx(0.25)
        assert snap["entity_miss_rate"] == pytest.approx(0.25)

    def test_facade_backed_by_registry(self):
        m = ServingMetrics()
        m.inc("requests", 3)
        m.observe_batch(bucket=4, real_rows=2, seconds=0.001)
        # the SAME data is queryable/scrapable through the registry
        assert m.registry.counter("requests") == 3
        assert m.registry.counter("serving_batches_total", bucket=4) == 1
        prom = m.to_prometheus()
        assert "requests 3" in prom
        assert 'serving_batches_total{bucket="4"} 1' in prom


# ---------------------------------------------------------------------------
# Timed + EventEmitter bridges
# ---------------------------------------------------------------------------
class TestBridges:
    def test_timed_emits_span(self, tracer):
        sunk = {}
        with Timed("my.phase", sink=lambda k, s: sunk.update({k: s})):
            pass
        recs = [r for r in tracer.records() if r["name"] == "my.phase"]
        assert len(recs) == 1 and recs[0]["ph"] == "X"
        assert "my.phase" in sunk  # the sink path still works

    def test_event_emitter_bridges_instants(self, tracer):
        seen = []
        em = EventEmitter()
        em.register(lambda e: seen.append(e))
        em.emit("training_start", task="logistic")
        assert len(seen) == 1 and isinstance(seen[0], Event)
        recs = [r for r in tracer.records() if r["name"] == "training_start"]
        assert len(recs) == 1 and recs[0]["ph"] == "i"
        assert recs[0]["attrs"] == {"task": "logistic"}

    def test_event_emitter_opt_out(self, tracer):
        em = EventEmitter(trace=False)
        em.emit("noisy_tick")
        assert [r for r in tracer.records() if r["name"] == "noisy_tick"] == []


# ---------------------------------------------------------------------------
# descent trace: nested spans through CoordinateDescent.run
# ---------------------------------------------------------------------------
class TestDescentTrace:
    def test_descent_run_traces_nested_updates(self, tracer, tmp_path):
        from test_serving import _write_fixture
        from photon_ml_tpu.cli import train as train_cli

        data = str(tmp_path / "t.avro")
        val = str(tmp_path / "v.avro")
        _write_fixture(data, n=120, seed=5)
        _write_fixture(val, n=60, seed=6)
        trace_path = str(tmp_path / "trace.json")
        metrics_path = str(tmp_path / "metrics.json")
        reg = MetricsRegistry()
        prev = obs.set_registry(reg)
        try:
            # --fused off pins the host-paced CoordinateDescent.run — the
            # per-update span nesting under test lives there (validated
            # fits otherwise run as one fused program with a single span)
            rc = train_cli.run([
                "--fused", "off",
                "--train-data", data, "--validation-data", val,
                "--evaluators", "auc", "--feature-shards", "all",
                "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
                "--coordinate", "name=user,random.effect.type=userId,"
                                "feature.shard=all,reg.weights=1",
                "--id-tags", "userId", "--coordinate-descent-iterations", "2",
                "--output-dir", str(tmp_path / "out"),
                "--trace-out", trace_path, "--metrics-out", metrics_path])
        finally:
            obs.set_registry(prev)
        assert rc == 0
        trace = json.load(open(trace_path))  # valid JSON on disk
        events = [e for e in trace["traceEvents"] if e["ph"] != "M"]
        pid = os.getpid()
        assert all(e["pid"] == pid and e["tid"] for e in events)
        updates = [e for e in events if e["name"] == "descent.update"]
        # 2 coordinates x 2 iterations
        assert len(updates) == 4
        assert {(e["args"]["iteration"], e["args"]["coordinate"])
                for e in updates} == {(0, "fixed"), (0, "user"),
                                      (1, "fixed"), (1, "user")}
        by_id = {e["args"]["span_id"]: e for e in events}
        for name in ("descent.solve", "descent.score"):
            children = [e for e in events if e["name"] == name]
            assert len(children) == 4
            for c in children:
                p = by_id[c["args"]["parent_id"]]
                assert p["name"] == "descent.update"
                assert p["ts"] <= c["ts"]
                assert c["ts"] + c["dur"] <= p["ts"] + p["dur"] + 0.01
        ts = [e["ts"] for e in events]
        assert ts == sorted(ts)
        # lifecycle instants bridged onto the same timeline
        assert {"training_start", "fit_start", "training_end"} <= \
            {e["name"] for e in events if e["ph"] == "i"}
        # DescentHistory bookkeeping landed in the registry
        snap = json.load(open(metrics_path))
        assert snap["counters"]['descent_updates_total{coordinate="fixed"}'] \
            == 2
        assert snap["counters"]['descent_updates_total{coordinate="user"}'] \
            == 2
        assert snap["histograms"][
            'descent_update_seconds{coordinate="user"}']["count"] == 2


# ---------------------------------------------------------------------------
# serve trace: submit -> flush -> resolve -> execute -> respond
# ---------------------------------------------------------------------------
class TestServeCliTrace:
    def test_serve_stream_trace_and_prometheus(self, tracer, tmp_path):
        from test_serving import _train
        from photon_ml_tpu.cli import serve as serve_cli

        model_dir = _train(tmp_path, seed=7)
        lines = []
        for i in range(6):
            lines.append(json.dumps({
                "uid": i, "features": [["g0", 0.3], ["ux", 0.1]],
                "ids": {"userId": f"user{i % 6}"}}))
        lines.append(json.dumps({"cmd": "trace"}))
        lines.append(json.dumps({"cmd": "metrics", "format": "prometheus"}))
        lines.append(json.dumps({"cmd": "metrics"}))
        req_file = str(tmp_path / "reqs.jsonl")
        with open(req_file, "w") as f:
            f.write("\n".join(lines) + "\n")
        trace_path = str(tmp_path / "serve_trace.json")

        import contextlib

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = serve_cli.run(["--model-dir", model_dir,
                                "--requests", req_file,
                                "--trace", "--trace-out", trace_path,
                                "--max-batch", "4"])
        assert rc == 0
        out = [json.loads(l) for l in buf.getvalue().splitlines()]
        scores = [o for o in out if "score" in o]
        assert len(scores) == 6 and [o["uid"] for o in scores] == list(range(6))

        trace_line = [o for o in out if "traceEvents" in o]
        assert len(trace_line) == 1
        events = [e for e in trace_line[0]["traceEvents"]
                  if e["ph"] != "M"]
        names = {e["name"] for e in events}
        # the whole request path is on the timeline
        assert {"serve.submit", "serve.flush", "store.resolve",
                "serve.execute", "serve.respond"} <= names
        assert {"jax.compile"} <= names  # warm compiles traced too
        # resolve nests inside the executing micro-batch, which nests
        # inside the batcher flush (on the worker thread)
        by_id = {e["args"]["span_id"]: e for e in events}
        resolves = [e for e in events if e["name"] == "store.resolve"]
        assert resolves
        for r in resolves:
            parent = by_id[r["args"]["parent_id"]]
            assert parent["name"] == "serve.execute"
            gp = by_id[parent["args"]["parent_id"]]
            assert gp["name"] == "serve.flush"
            assert gp["tid"] == parent["tid"] == r["tid"]
        # submits happen on the stream thread, flushes on the worker
        submit_tids = {e["tid"] for e in events if e["name"] == "serve.submit"}
        flush_tids = {e["tid"] for e in events if e["name"] == "serve.flush"}
        assert submit_tids and flush_tids and submit_tids != flush_tids
        ts = [e["ts"] for e in events]
        assert ts == sorted(ts)
        # exported file matches the wire dump's shape
        exported = json.load(open(trace_path))
        assert {e["name"] for e in exported["traceEvents"]} >= names

        prom = [o for o in out if "prometheus" in o]
        assert len(prom) == 1
        assert "# TYPE requests counter" in prom[0]["prometheus"]
        snap = [o for o in out if "counters" in o and "qps" in o]
        assert len(snap) == 1
        assert set(snap[0]) == PR4_SNAPSHOT_KEYS


# ---------------------------------------------------------------------------
# bench.py --obs plumbing (budget relaxed: CI boxes are noisy; the real
# 1µs assertion runs in the bench itself)
# ---------------------------------------------------------------------------
class TestObsBench:
    def test_obs_bench_shape(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setenv("PHOTON_BENCH_OBS_BUDGET_NS", "1e9")
        out_path = str(tmp_path / "BENCH_OBS.json")
        out = bench.run_obs_bench(n_calls=2000, out_path=out_path)
        on_disk = json.load(open(out_path))
        assert on_disk == out
        assert set(out) >= {"disabled_span_ns", "enabled_span_ns",
                            "instant_ns", "registry_inc_labeled_ns",
                            "budget_ns", "within_budget"}
        assert out["disabled_span_ns"] > 0
        # the guard must be cheaper than actually recording
        assert out["disabled_span_ns"] < out["enabled_span_ns"]
