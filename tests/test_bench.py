"""Unit tests for the bench harness itself (bench.py is the driver's entry
artifact — its measurement and gating logic deserve the same regression
protection as the library)."""

import os
import sys

import pytest

# bench.py lives at the repo root, one level above tests/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


class TestMeasure:
    def test_five_repeats_and_median(self):
        calls = []

        def thunk():
            calls.append(1)
            return [0.3, 0.1, 0.2, 0.5, 0.4][len(calls) - 1]

        med, timing = bench._measure(thunk)
        assert len(calls) == 5
        assert med == pytest.approx(0.3)
        assert timing == {"n_repeats": 5, "dt_median": 0.3,
                          "dt_min": 0.1, "dt_max": 0.5}

    def test_fast_thunk_accumulates_min_window(self):
        """A sub-ms thunk (TPU a1a's whole solve is ~0.1ms) must repeat until
        >=min_window seconds of samples exist — 5 samples of dispatch jitter
        are not a measurement (VERDICT r2 weak #5, second edition)."""
        med, timing = bench._measure(lambda: 0.001)
        assert timing["n_repeats"] == 500  # 0.5s window / 1ms
        assert med == pytest.approx(0.001)

    def test_fast_thunk_repeat_cap(self):
        """The repeat cap must sit far above min_window/dt for any real
        config so the window is reached, and still bound a pathological
        zero-cost thunk."""
        med, timing = bench._measure(lambda: 0.0)
        assert timing["n_repeats"] == 5000

    def test_slow_config_stops_at_budget(self):
        """Full-scale configs with multi-minute repeats stop at max_total —
        every repeat is seconds long, satisfying the dt>=2s criterion."""
        med, timing = bench._measure(lambda: 50.0, max_total=120.0)
        assert timing["n_repeats"] == 3  # 50+50 < 120 <= 50+50+50
        assert med == 50.0


class TestQualityGates:
    def test_gp_tune_gate_can_fail(self):
        """VERDICT r2 weak #2: the old gate passed on equality (a tuner that
        finds nothing).  The gate must now DEMAND improvement."""
        stats_flat = {"best_auc": 0.90477, "prior_auc": 0.90477, "fits": 7}
        assert bench.quality_gate("gp_tune", stats_flat, None)["pass"] is False
        stats_worse = {"best_auc": 0.80, "prior_auc": 0.85, "fits": 7}
        assert bench.quality_gate("gp_tune", stats_worse, None)["pass"] is False
        stats_better = {"best_auc": 0.88, "prior_auc": 0.84, "fits": 7}
        gate = bench.quality_gate("gp_tune", stats_better, None)
        assert gate["pass"] is True
        assert gate["improvement"] == pytest.approx(0.04)

    def test_auc_gates_use_reference(self):
        ref = {"auc": 0.9}
        assert bench.quality_gate("a1a", {"auc": 0.9001}, ref)["pass"] is True
        assert bench.quality_gate("a1a", {"auc": 0.88}, ref)["pass"] is False
        assert bench.quality_gate("glmix2", {"auc": 0.904},
                                  {"auc": 0.9})["pass"] is True
        # no reference -> explicitly unknown, never silently green
        assert bench.quality_gate("a1a", {"auc": 0.9}, None)["pass"] is None

    def test_sparse1m_gate_relative(self):
        ref = {"mean_nll": 0.5}
        assert bench.quality_gate("sparse1m", {"mean_nll": 0.5001},
                                  ref)["pass"] is True
        assert bench.quality_gate("sparse1m", {"mean_nll": 0.52},
                                  ref)["pass"] is False


class TestEntry:
    def test_entry_from_carries_timing_and_ratio(self):
        got = {"dt": 2.0, "units": 100, "unit": "examples/sec",
               "backend": "cpu", "stats": {"best_auc": 0.9, "prior_auc": 0.8,
                                           "fits": 7},
               "timing": {"n_repeats": 5, "dt_median": 2.0,
                          "dt_min": 1.9, "dt_max": 2.2},
               "impl": "fused"}
        entry = bench._entry_from("gp_tune", got, scale=8, want_cpu_ref=False)
        assert entry["value"] == 50.0
        assert entry["vs_baseline"] is None  # no stand-in requested
        assert entry["timing"]["n_repeats"] == 5
        assert entry["impl"] == "fused"
        assert entry["quality"]["pass"] is True


class TestSynth:
    def test_synth_shapes_scale(self):
        xg, xu, uids, y = bench.synth_tune(8)
        assert len(y) == len(uids) == xg.shape[0] == xu.shape[0] == 8192
        xg1, *_ = bench.synth_tune(1)
        assert xg1.shape[0] == 65536
        data = bench.synth_glmix(8, False)
        assert data["xg"].shape == (2048 * 32, 256)


class TestAbChain:
    def test_chain_lines_parse_and_cover_variants(self):
        """The accelerator A/B chain child emits one JSON line per variant
        over ONE design upload; the parent must recover every emitted line
        (even from a partially-dead child, which this parse path tolerates
        by skipping unparseable tails)."""
        import os

        # scale 128 -> n=4096: keeps the three compiles the dominant cost
        # (~15s total) so the 520s alarm has huge headroom under CI load
        env = dict(os.environ, PHOTON_BENCH_CPU_SCALE="128", PYTHONPATH="")
        lines = bench._subprocess_json_lines(
            ["--config", "glmix2", "--ab-chain", "--platform", "cpu"],
            timeout=520, env=env)
        by = {ln["variant"]: ln for ln in lines if "variant" in ln}
        assert set(by) == {"glmix2", "glmix2_host", "glmix2_xla"}
        for v in by.values():
            assert "error" not in v, v["error"]
            assert v["units"] > 0 and v["dt"] > 0

    def test_json_lines_keeps_lines_from_dead_child(self, tmp_path,
                                                    monkeypatch):
        """A child that emits valid lines then dies nonzero must still
        yield its emitted lines (wedge costs the un-run variants only)."""
        import subprocess

        real_run = subprocess.run

        def fake_run(argv, **kw):
            class R:
                returncode = 1
                stdout = ('noise\n{"variant": "a", "x": 1}\n'
                          'WARN xyz\n{"variant": "b"}\n')
                stderr = "boom"
            return R()

        monkeypatch.setattr(bench.subprocess, "run", fake_run)
        monkeypatch.setattr(bench, "_REPO", str(tmp_path))  # error log target
        lines = bench._subprocess_json_lines(["--config", "x"], timeout=5)
        assert [d["variant"] for d in lines] == ["a", "b"]
        assert "boom" in (tmp_path / ".bench_errors.log").read_text()


class TestInProcessFallback:
    def test_fused_crash_falls_back_to_host_with_error_tag(self, monkeypatch):
        """run_glmix: a fused-impl exception must yield a HOST measurement
        carrying fused_error (the parent logs it and skips the fused A/B),
        without a second child / re-upload."""
        calls = []

        def fake_measure(backend, data, three, impl):
            calls.append(impl)
            if impl == "fused":
                raise RuntimeError("synthetic fused crash")
            return {"backend": backend, "dt": 1.0, "impl": impl,
                    "units": 10, "unit": "x/sec", "stats": {}}

        monkeypatch.setattr(bench, "_glmix_measure", fake_measure)
        monkeypatch.setattr(bench, "_select_platform", lambda p: "cpu")
        monkeypatch.delenv("PHOTON_BENCH_IMPL", raising=False)
        got = bench.run_glmix("cpu", 128, three=False)
        assert calls == ["fused", "host"]
        assert got["impl"] == "host"
        assert "synthetic fused crash" in got["fused_error"]
        entry = bench._entry_from("glmix2", got, 128, want_cpu_ref=False)
        assert entry["fused_error"] == got["fused_error"]

    def test_explicit_impl_env_disables_fallback(self, monkeypatch):
        def fake_measure(backend, data, three, impl):
            raise RuntimeError("boom")

        monkeypatch.setattr(bench, "_glmix_measure", fake_measure)
        monkeypatch.setattr(bench, "_select_platform", lambda p: "cpu")
        monkeypatch.setenv("PHOTON_BENCH_IMPL", "fused")
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="boom"):
            bench.run_glmix("cpu", 128, three=False)


class TestGateFalsifiability:
    """VERDICT r3 weak #3: the glmix gates must be able to FAIL.  Both
    sabotages run the real measurement end-to-end at 1/64 scale against the
    real scipy stand-in; the synthetics' cross-shard correlation is what
    makes the residual fold's absence visible (synth_glmix docstring)."""

    @pytest.fixture(scope="class")
    def glmix64(self):
        data = bench.synth_glmix(64, False)
        return data, bench._scipy_glmix(data, False)

    def test_healthy_run_passes(self, glmix64):
        data, ref = glmix64
        got = bench._glmix_measure("cpu", dict(data), False, "fused")
        gate = bench.quality_gate("glmix2", got["stats"], ref)
        assert gate["pass"] is True
        assert gate["coef_rel_err"] <= 0.01  # healthy margin is ~3e-5

    def test_mis_set_reg_weight_fails(self, glmix64, monkeypatch):
        import dataclasses

        from photon_ml_tpu.core.regularization import Regularization

        data, ref = glmix64
        orig = bench._glmix_coords

        def sabotaged(d, three):
            return {cid: c.rebind(dataclasses.replace(
                c.config, reg=Regularization(l2=c.config.reg.l2 * 100.0)))
                for cid, c in orig(d, three).items()}

        monkeypatch.setattr(bench, "_glmix_coords", sabotaged)
        got = bench._glmix_measure("cpu", dict(data), False, "fused")
        gate = bench.quality_gate("glmix2", got["stats"], ref)
        assert gate["pass"] is False
        assert gate["coef_rel_err"] > 0.05

    def test_broken_residual_fold_fails(self, glmix64, monkeypatch):
        """Coordinates trained against ZERO residuals (the exact breakage a
        wrong fold would cause).  The AUC barely moves — the coefficient
        parity is what catches it, which is why the gate has it."""
        import jax.numpy as jnp

        import photon_ml_tpu.game.coordinate as gc

        data, ref = glmix64
        o_f = gc.FixedEffectCoordinate.trace_update
        o_r = gc.RandomEffectCoordinate.trace_update
        monkeypatch.setattr(
            gc.FixedEffectCoordinate, "trace_update",
            lambda self, s, off, **k: o_f(self, s, jnp.zeros_like(off), **k))
        monkeypatch.setattr(
            gc.RandomEffectCoordinate, "trace_update",
            lambda self, s, off, **k: o_r(self, s, jnp.zeros_like(off), **k))
        got = bench._glmix_measure("cpu", dict(data), False, "fused")
        gate = bench.quality_gate("glmix2", got["stats"], ref)
        assert gate["pass"] is False
        assert gate["auc_diff"] <= 0.005          # AUC alone would pass...
        assert gate["coef_rel_err"] > 0.05        # ...the coef gate fails it


class TestChipGateFalsifiability:
    """VERDICT r4 missing #3: glmix_chip's gate was self-referential (AUC +
    signal/noise columns from the same generative formula).  At CPU-feasible
    scales the device-generated design is host-reconstructible (threefry is
    platform-deterministic), so an INDEPENDENT scipy fit of the same data
    pins coefficient parity — the chip-scale run keeps vs_baseline null but
    inherits this floor-scale anchor as its falsifiable gate."""

    @pytest.fixture(scope="class")
    def chip1024(self):
        # direct stand-in call (like TestGateFalsifiability's _scipy_glmix):
        # going through cpu_ref would read/write the shared repo-level
        # .bench_cpu_cache.json and let a stale entry stand in for the code
        # under test
        got = bench.run_glmix_chip("cpu", 1024)
        ref = bench._scipy_glmix_chip(1024)
        return got, ref

    def test_healthy_run_passes(self, chip1024):
        got, ref = chip1024
        gate = bench.quality_gate("glmix_chip", got["stats"], ref)
        assert gate["pass"] is True
        assert gate["coef_rel_err"] <= 0.01  # healthy margin is ~5e-4
        assert gate["auc_diff"] <= 0.005

    def test_mis_set_reg_weight_fails(self, chip1024, monkeypatch):
        import dataclasses

        import photon_ml_tpu.game.coordinate as gc
        from photon_ml_tpu.core.regularization import Regularization

        _, ref = chip1024
        orig = gc.build_coordinate

        def sabotaged(cid, data, cfg, task, **kw):
            cfg = dataclasses.replace(
                cfg, reg=Regularization(l2=cfg.reg.l2 * 100.0))
            return orig(cid, data, cfg, task, **kw)

        monkeypatch.setattr(gc, "build_coordinate", sabotaged)
        got = bench.run_glmix_chip("cpu", 1024)
        gate = bench.quality_gate("glmix_chip", got["stats"], ref)
        assert gate["pass"] is False
        assert gate["coef_rel_err"] > 0.05

    def test_chip_scale_run_keeps_null_baseline(self, chip1024):
        """A chip-backend run carries no wg (and no scipy ref is reachable):
        the gate must stay the self-band, with no parity fields."""
        got, ref = chip1024
        stats = {k: v for k, v in got["stats"].items() if k != "wg"}
        gate = bench.quality_gate("glmix_chip", stats, ref)
        assert "coef_rel_err" not in gate
        assert gate["pass"] is True


class TestTpuEvidencePointer:
    """The cpu-fallback line's pointer at banked accelerator evidence is
    driver-facing output: it must appear exactly when TPU_CHECKLIST.json
    holds a tpu-backend bench, and NEVER raise on malformed content."""

    def _write(self, tmp_path, content):
        import json

        (tmp_path / "TPU_CHECKLIST.json").write_text(json.dumps(content))
        return str(tmp_path)

    def test_present_for_banked_tpu_bench(self, tmp_path):
        repo = self._write(tmp_path, {
            "started": "2026-08-01T08:04:35Z",
            "window_note": "x",
            "bench": {"backend": "tpu", "configs": {}}})
        ev = bench._tpu_evidence_pointer(repo)
        assert ev["file"] == "TPU_CHECKLIST.json"
        assert ev["captured"] == "2026-08-01T08:04:35Z"
        assert "window_note" in ev["note"]

    def test_absent_for_cpu_bench_or_missing(self, tmp_path):
        assert bench._tpu_evidence_pointer(str(tmp_path)) is None
        repo = self._write(tmp_path, {"bench": {"backend": "cpu"}})
        assert bench._tpu_evidence_pointer(repo) is None

    def test_malformed_content_never_raises(self, tmp_path):
        for content in ([1, 2], {"bench": [1, 2]}, {"bench": "tpu"}, 7):
            repo = self._write(tmp_path, content)
            assert bench._tpu_evidence_pointer(repo) is None
        (tmp_path / "TPU_CHECKLIST.json").write_text("not json{")
        assert bench._tpu_evidence_pointer(str(tmp_path)) is None


class TestChecklistPromotion:
    """tools/tpu_checklist.py must never clobber the canonical banked
    artifact with a lesser run: in-progress state goes to the .partial
    file, and promotion requires an accelerator-backend bench AND a
    healthy pallas stage (learned 2026-08-02, when a degraded-window
    rerun overwrote the banked pass at start)."""

    def _run_main(self, tmp_path, monkeypatch, stage_lines):
        import tools.tpu_checklist as tc

        out = tmp_path / "TPU_CHECKLIST.json"
        partial = tmp_path / "TPU_CHECKLIST.partial.json"
        monkeypatch.setattr(tc, "_OUT", str(out))
        monkeypatch.setattr(tc, "_PARTIAL", str(partial))
        monkeypatch.setenv("PHOTON_BENCH_PROFILE_DIR", str(tmp_path / "prof"))
        calls = iter(stage_lines)

        def fake_run(argv_or_src, timeout):
            return next(calls)

        monkeypatch.setattr(tc, "_run_py", fake_run)
        # _save(..., _OUT) uses the default-arg binding captured at import;
        # patch _save to honor the monkeypatched module globals
        real_save = tc._save.__wrapped__ if hasattr(tc._save, "__wrapped__") \
            else tc._save

        def save(results, path=None):
            real_save(results, path or tc._PARTIAL)

        monkeypatch.setattr(tc, "_save", save)
        return tc.main(), out, partial

    def test_healthy_tpu_run_promotes(self, tmp_path, monkeypatch):
        import json

        rc, out, partial = self._run_main(tmp_path, monkeypatch, [
            ("tpu", None),
            (json.dumps({"pass": True, "cases": []}), None),
            (json.dumps({"backend": "tpu", "metric": "m", "configs": {}}),
             None),
        ])
        assert rc == 0 and out.exists()
        assert json.loads(out.read_text())["bench"]["backend"] == "tpu"

    def test_cpu_fallback_bench_not_promoted(self, tmp_path, monkeypatch):
        import json

        banked = {"bench": {"backend": "tpu"}, "pallas_parity": {"pass": True}}
        (tmp_path / "TPU_CHECKLIST.json").write_text(json.dumps(banked))
        rc, out, partial = self._run_main(tmp_path, monkeypatch, [
            ("tpu", None),
            (json.dumps({"pass": True, "cases": []}), None),
            # tunnel died mid-run: bench.py itself fell back to cpu
            (json.dumps({"backend": "cpu", "metric": "m"}), None),
        ])
        assert rc == 1
        assert json.loads(out.read_text()) == banked  # canonical untouched
        assert json.loads(partial.read_text())["bench"]["backend"] == "cpu"

    def test_pallas_failure_not_promoted(self, tmp_path, monkeypatch):
        import json

        banked = {"bench": {"backend": "tpu"}, "pallas_parity": {"pass": True}}
        (tmp_path / "TPU_CHECKLIST.json").write_text(json.dumps(banked))
        rc, out, partial = self._run_main(tmp_path, monkeypatch, [
            ("tpu", None),
            (None, "timeout after 600s"),  # pallas stage died
            (json.dumps({"backend": "tpu", "metric": "m"}), None),
        ])
        assert rc == 1
        assert json.loads(out.read_text()) == banked

    def test_probe_failure_touches_nothing_canonical(self, tmp_path,
                                                     monkeypatch):
        import json

        banked = {"bench": {"backend": "tpu"}}
        (tmp_path / "TPU_CHECKLIST.json").write_text(json.dumps(banked))
        rc, out, partial = self._run_main(tmp_path, monkeypatch, [
            (None, "timeout after 120s"),
        ])
        assert rc == 1
        assert json.loads(out.read_text()) == banked
        assert "error" in json.loads(partial.read_text())["probe"]["error"] \
            or json.loads(partial.read_text())["probe"]["error"]


class TestOpenLoopPlumbing:
    """--serving --open-loop arg plumbing: flags reach run_open_loop_bench
    parsed, and --open-loop alone is rejected (it has no meaning without
    the serving edge)."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"bench": "open_loop_serving", "sweep": []}

        monkeypatch.setattr(bench, "run_open_loop_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--serving", "--open-loop",
            "--open-loop-rates", "100,250.5",
            "--open-loop-duration", "1.5",
            "--open-loop-connections", "7",
            "--open-loop-budget-ms", "12.5",
            "--serving-entities", "123",
            "--serving-deadline-us", "300",
            "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["bench"] == "open_loop_serving"
        assert seen["rates"] == [100.0, 250.5]
        assert seen["duration_s"] == 1.5
        assert seen["n_connections"] == 7
        assert seen["budget_ms"] == 12.5
        assert seen["n_entities"] == 123
        assert seen["deadline_us"] == 300.0
        assert seen["out_path"] == "ignored.json"

    def test_empty_rates_mean_calibrated_multipliers(self, monkeypatch,
                                                     capsys):
        seen = {}
        monkeypatch.setattr(bench, "run_open_loop_bench",
                            lambda **kw: seen.update(kw) or {})
        monkeypatch.setattr(sys, "argv",
                            ["bench.py", "--serving", "--open-loop"])
        bench.main()
        assert seen["rates"] is None  # runner calibrates and picks rates

    def test_open_loop_requires_serving(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", ["bench.py", "--open-loop"])
        with pytest.raises(SystemExit) as ei:
            bench.main()
        assert ei.value.code == 2  # argparse error exit


class TestServingMeshPlumbing:
    """--serving --mesh arg plumbing: flags reach run_serving_mesh_bench
    parsed, and --mesh alone is rejected."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"metric": "serving_mesh_scaling", "shards": {}}

        monkeypatch.setattr(bench, "run_serving_mesh_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--serving", "--mesh",
            "--mesh-shard-counts", "1,2,4",
            "--serving-entities", "456",
            "--serving-requests", "77",
            "--serving-device-capacity", "32",
            "--zipf", "1.4",
            "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["metric"] == "serving_mesh_scaling"
        assert seen["shard_counts"] == (1, 2, 4)
        assert seen["n_entities"] == 456
        assert seen["n_requests"] == 77
        assert seen["per_shard_capacity"] == 32
        assert seen["zipf"] == 1.4
        assert seen["out_path"] == "ignored.json"

    def test_mesh_requires_serving(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", ["bench.py", "--mesh"])
        with pytest.raises(SystemExit) as ei:
            bench.main()
        assert ei.value.code == 2  # argparse error exit

    def test_unset_capacity_and_zipf_get_defaults(self, monkeypatch):
        seen = {}
        monkeypatch.setattr(bench, "run_serving_mesh_bench",
                            lambda **kw: seen.update(kw) or {})
        monkeypatch.setattr(sys, "argv", ["bench.py", "--serving", "--mesh"])
        bench.main()
        assert seen["per_shard_capacity"] is None  # runner derives n/10
        assert seen["zipf"] == 1.1  # mesh sweep is always skewed


class TestSkewSweepPlumbing:
    """--serving --skew-sweep arg plumbing: flags reach
    run_skew_sweep_bench parsed, and --skew-sweep alone is rejected."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"metric": "serving_skew_robustness"}

        monkeypatch.setattr(bench, "run_skew_sweep_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--serving", "--skew-sweep",
            "--skew-values", "0.9,1.3",
            "--skew-shards", "2",
            "--serving-device-capacity", "64",
            "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["metric"] == "serving_skew_robustness"
        assert seen["skews"] == (0.9, 1.3)
        assert seen["n_shards"] == 2
        assert seen["per_shard_capacity"] == 64
        assert seen["out_path"] == "ignored.json"

    def test_skew_sweep_requires_serving(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", ["bench.py", "--skew-sweep"])
        with pytest.raises(SystemExit) as ei:
            bench.main()
        assert ei.value.code == 2  # argparse error exit

    def test_unset_capacity_and_skews_get_defaults(self, monkeypatch):
        seen = {}
        monkeypatch.setattr(bench, "run_skew_sweep_bench",
                            lambda **kw: seen.update(kw) or {})
        monkeypatch.setattr(sys, "argv",
                            ["bench.py", "--serving", "--skew-sweep"])
        bench.main()
        assert seen["per_shard_capacity"] is None  # runner derives n/10
        assert seen["skews"] == (0.8, 1.0, 1.2, 1.5)  # the headline sweep


class TestFleetPlumbing:
    """--fleet arg plumbing (flags reach run_fleet_bench parsed) plus one
    real tiny run asserting the bench's own invariants hold and the JSON
    lands where --out points."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"bench": "fleet_serving", "compiles_after_warm": [0]}

        monkeypatch.setattr(bench, "run_fleet_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--fleet",
            "--fleet-models", "3",
            "--fleet-entities", "128",
            "--fleet-requests", "64",
            "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["bench"] == "fleet_serving"
        assert seen["n_models"] == 3
        assert seen["n_entities"] == 128
        assert seen["n_requests"] == 64
        assert seen["out_path"] == "ignored.json"

    def test_tiny_real_run_holds_invariants(self, tmp_path):
        import json

        out_path = str(tmp_path / "fleet.json")
        out = bench.run_fleet_bench(n_entities=48, d=4, n_requests=48,
                                    max_batch=8, n_models=2,
                                    out_path=out_path)
        # the Flare invariant the bench exists to watch: growing the
        # same-shape family compiled nothing after the first warm
        assert out["compiles_after_warm"] == [0, 0]
        assert out["recompiles_after_warm"] == 0
        assert out["shadow"]["pairs"] == 48
        assert out["shadow_overhead_ratio"] > 0
        assert out["canary"]["promote_settle_s"] > 0
        assert out["canary"]["rollback_reason"] == "score_drift"
        with open(out_path) as f:
            assert json.load(f)["bench"] == "fleet_serving"


class TestOnlineBenchCli:
    """--online arg plumbing: flags reach run_online_bench parsed."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"metric": "online_refit_entities_per_s"}

        monkeypatch.setattr(bench, "run_online_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--online", "--online-batches", "3",
            "--online-batch-size", "16", "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["metric"] == "online_refit_entities_per_s"
        assert seen["batches"] == 3
        assert seen["batch_size"] == 16
        assert seen["out_path"] == "ignored.json"


class TestReplBenchCli:
    """--repl arg plumbing: flags reach run_repl_bench parsed."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"metric": "repl_store_visible_freshness_ms_p99"}

        monkeypatch.setattr(bench, "run_repl_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--repl", "--repl-replicas", "3",
            "--repl-batches", "4", "--repl-batch-size", "16",
            "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["metric"] == \
            "repl_store_visible_freshness_ms_p99"
        assert seen["n_replicas"] == 3
        assert seen["batches"] == 4
        assert seen["batch_size"] == 16
        assert seen["out_path"] == "ignored.json"

    def test_defaults(self, monkeypatch, capsys):
        seen = {}
        monkeypatch.setattr(bench, "run_repl_bench",
                            lambda **kw: seen.update(kw) or {})
        monkeypatch.setattr(sys, "argv", ["bench.py", "--repl"])
        bench.main()
        assert seen["n_replicas"] == 2
        assert seen["batches"] == 8
        assert seen["batch_size"] == 32
        assert seen["out_path"] is None


class TestChaosBenchCli:
    """--chaos arg plumbing: flags reach run_chaos_bench parsed."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"metric": "chaos_time_to_ready_s_max"}

        monkeypatch.setattr(bench, "run_chaos_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--chaos", "--chaos-seed", "7",
            "--chaos-rounds", "12", "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["metric"] == "chaos_time_to_ready_s_max"
        assert seen["seed"] == 7
        assert seen["rounds"] == 12
        assert seen["out_path"] == "ignored.json"

    def test_defaults(self, monkeypatch, capsys):
        seen = {}
        monkeypatch.setattr(bench, "run_chaos_bench",
                            lambda **kw: seen.update(kw) or {})
        monkeypatch.setattr(sys, "argv", ["bench.py", "--chaos"])
        bench.main()
        assert seen["seed"] == 0
        # one coverage round per fault class (stall_dist joined in PR 20)
        assert seen["rounds"] == 10
        assert seen["out_path"] is None


class TestStreamBenchCli:
    """--stream arg plumbing: flags reach run_stream_bench parsed, and the
    early dispatch prints the runner's JSON line."""

    def test_flags_reach_runner_parsed(self, monkeypatch, capsys):
        import json

        seen = {}

        def fake_runner(**kw):
            seen.update(kw)
            return {"metric": "stream_ingest_mb_per_s"}

        monkeypatch.setattr(bench, "run_stream_bench", fake_runner)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--stream", "--stream-rows", "777",
            "--stream-batch-rows", "256", "--stream-workers", "3",
            "--out", "ignored.json"])
        bench.main()
        out = capsys.readouterr().out
        assert json.loads(out)["metric"] == "stream_ingest_mb_per_s"
        assert seen["n_rows"] == 777
        assert seen["batch_rows"] == 256
        assert seen["workers"] == 3
        assert seen["out_path"] == "ignored.json"

    def test_defaults(self, monkeypatch, capsys):
        seen = {}
        monkeypatch.setattr(bench, "run_stream_bench",
                            lambda **kw: seen.update(kw) or {})
        monkeypatch.setattr(sys, "argv", ["bench.py", "--stream"])
        bench.main()
        assert seen["n_rows"] == 50_000
        assert seen["batch_rows"] == 1024
        assert seen["workers"] == 2
        assert seen["out_path"] is None
