"""photonlint v3 dataflow suite (tier-1).

Covers the layers PR 17 added on top of the rule framework:

  1. ``FunctionFlow`` — the per-function CFG fixpoint: alias sets and
     reaching definitions through branches, loops, try/finally, and kills;
  2. ``ModuleCallGraph`` — event-loop reachability (async defs + scheduled
     callbacks, executor hand-offs exempt by construction) and lock-held
     regions;
  3. the four new rules (PL011 shard-spec-arity, PL012
     collective-without-mesh, PL013 blocking-in-async, PL014
     cross-module-donation) and the alias-aware PL005 v2, each with
     positive AND negative fixtures;
  4. ``--diff`` incremental mode: findings must equal a full run restricted
     to the changed files (exercised against a real throwaway git repo);
  5. the dataflow gate: the real package stays clean, the index builds
     inside its budget, and the JSON summary reports the dataflow cost.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.analysis import (analyze_source, build_rules,  # noqa: E402
                                    run_analysis)
from photon_ml_tpu.analysis.dataflow import (FunctionFlow,  # noqa: E402
                                             ModuleCallGraph)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "photon_ml_tpu")
HOT = "photon_ml_tpu/core/fixture.py"


def lint(src, rule=None, path=HOT):
    rules = build_rules([rule]) if rule else build_rules()
    kept, _ = analyze_source(path, textwrap.dedent(src), rules)
    return kept


# ---------------------------------------------------------------------------
# FunctionFlow: alias sets + reaching defs over the CFG
# ---------------------------------------------------------------------------

def _flow(src, name="f"):
    tree = ast.parse(textwrap.dedent(src))
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
              and n.name == name)
    return fn, FunctionFlow(fn)


def _call_named(fn, callee):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == callee:
            return node
    raise AssertionError(f"no call to {callee}")


class TestFunctionFlow:
    def test_straightline_alias_chain(self):
        fn, flow = _flow("""
            def f(self):
                s = self._store
                s2 = s
                sink(s2)
        """)
        at = _call_named(fn, "sink")
        assert flow.attr_aliases("s2", at) == frozenset({"_store"})
        assert flow.attr_aliases("s", at) == frozenset({"_store"})

    def test_branch_joins_union_aliases(self):
        fn, flow = _flow("""
            def f(self, cond):
                if cond:
                    x = self._primary
                else:
                    x = self._fallback
                sink(x)
        """)
        at = _call_named(fn, "sink")
        assert flow.attr_aliases("x", at) == \
            frozenset({"_primary", "_fallback"})

    def test_loop_reaches_fixpoint(self):
        fn, flow = _flow("""
            def f(self, xs):
                cur = self._head
                for _ in xs:
                    cur = self._next
                sink(cur)
        """)
        # after the loop either zero or more iterations ran: union
        at = _call_named(fn, "sink")
        assert flow.attr_aliases("cur", at) == \
            frozenset({"_head", "_next"})

    def test_reassignment_kills_alias(self):
        fn, flow = _flow("""
            def f(self):
                x = self._a
                x = make()
                sink(x)
        """)
        at = _call_named(fn, "sink")
        assert flow.attr_aliases("x", at) == frozenset()

    def test_reaching_defs_through_branch(self):
        fn, flow = _flow("""
            def f(cond):
                x = 1
                if cond:
                    x = 2
                sink(x)
        """)
        at = _call_named(fn, "sink")
        # both the initial def and the conditional redefinition reach
        assert len(flow.reaching_defs("x", at)) == 2

    def test_reaching_defs_straightline_kill(self):
        fn, flow = _flow("""
            def f():
                x = 1
                x = 2
                sink(x)
        """)
        at = _call_named(fn, "sink")
        assert len(flow.reaching_defs("x", at)) == 1

    def test_try_finally_sees_both_states(self):
        fn, flow = _flow("""
            def f(self):
                x = self._a
                try:
                    x = self._b
                    maybe_raise()
                finally:
                    sink(x)
        """)
        # the finally body runs whether or not the try completed: the
        # exception edges feed the pre-assignment state in too
        at = _call_named(fn, "sink")
        assert flow.attr_aliases("x", at) == frozenset({"_a", "_b"})

    def test_with_as_binds_context_aliases(self):
        fn, flow = _flow("""
            def f(self):
                with self._lock as held:
                    sink(held)
        """)
        at = _call_named(fn, "sink")
        assert flow.attr_aliases("held", at) == frozenset({"_lock"})

    def test_reverse_store_aliases_name(self):
        # `self.X = name` makes the NAME an alias of X from then on
        fn, flow = _flow("""
            def f(self, store):
                self._store = store
                sink(store)
        """)
        at = _call_named(fn, "sink")
        assert "_store" in flow.attr_aliases("store", at)


# ---------------------------------------------------------------------------
# ModuleCallGraph: event-loop + lock-held reachability
# ---------------------------------------------------------------------------

def _graph(src):
    return ModuleCallGraph(ast.parse(textwrap.dedent(src)))


def _fn_ids(graph, *names):
    out = set()
    for fn in graph.fns:
        if getattr(fn, "name", None) in names:
            out.add(id(fn))
    return out


class TestModuleCallGraph:
    def test_async_body_reaches_sync_helper(self):
        g = _graph("""
            def helper():
                pass

            def untouched():
                pass

            async def serve():
                helper()
        """)
        on_loop = g.event_loop_fns()
        assert _fn_ids(g, "serve", "helper") <= on_loop
        assert not (_fn_ids(g, "untouched") & on_loop)

    def test_executor_handoff_is_exempt(self):
        g = _graph("""
            import asyncio

            def work():
                pass

            async def serve(loop):
                await loop.run_in_executor(None, work)
        """)
        # work is passed as a REFERENCE, never called on the loop
        assert not (_fn_ids(g, "work") & g.event_loop_fns())

    def test_scheduled_callback_is_on_loop(self):
        g = _graph("""
            def callback():
                pass

            def arrange(loop):
                loop.call_soon_threadsafe(callback)
        """)
        assert _fn_ids(g, "callback") <= g.event_loop_fns()
        assert not (_fn_ids(g, "arrange") & g.event_loop_fns())

    def test_lock_held_reachability(self):
        g = _graph("""
            class C:
                def locked(self):
                    with self._lock:
                        self.flush()

                def flush(self):
                    pass

                def free(self):
                    pass
        """)
        held = g.lock_held_fns()
        assert _fn_ids(g, "flush") <= held
        assert not (_fn_ids(g, "free") & held)


# ---------------------------------------------------------------------------
# PL011 shard-spec-arity
# ---------------------------------------------------------------------------

class TestShardSpecArity:
    def test_positive_in_specs_arity_mismatch(self):
        vs = lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def local(a, b):
                return a + b

            f = shard_map(local, mesh=MESH, in_specs=(P("x"),),
                          out_specs=P("x"))
        """, "shard-spec-arity")
        assert len(vs) == 1 and "2 positional" in vs[0].message

    def test_positive_out_specs_arity_mismatch(self):
        vs = lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def local(a):
                return a, a

            f = shard_map(local, mesh=MESH, in_specs=(P("x"),),
                          out_specs=(P("x"),))
        """, "shard-spec-arity")
        assert len(vs) == 1 and "2-tuple" in vs[0].message

    def test_positive_duplicate_axis_in_spec(self):
        vs = lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def local(a):
                return a

            f = shard_map(local, mesh=MESH,
                          in_specs=(P("x", "x"),), out_specs=P("x"))
        """, "shard-spec-arity")
        assert len(vs) == 1 and "more than once" in vs[0].message

    def test_positive_axis_not_in_site_mesh(self):
        vs = lint("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(jax.devices(), ("data", "model"))

            def local(a):
                return a

            f = shard_map(local, mesh=mesh,
                          in_specs=(P("batch"),), out_specs=P("data"))
        """, "shard-spec-arity")
        assert len(vs) == 1 and "'batch'" in vs[0].message

    def test_negative_correct_site(self):
        assert lint("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(jax.devices(), ("data",))

            def local(a, b):
                return a + b

            f = shard_map(local, mesh=mesh,
                          in_specs=(P("data"), P()), out_specs=P("data"))
        """, "shard-spec-arity") == []

    def test_negative_pytree_prefix_and_variadic_stay_quiet(self):
        assert lint("""
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def local(*parts):
                return parts

            # non-tuple in_specs is a valid pytree prefix; variadic target
            # accepts any arity
            f = shard_map(local, mesh=MESH, in_specs=P("x"),
                          out_specs=P("x"))
            g = shard_map(local, mesh=MESH, in_specs=(P("x"), P("x")),
                          out_specs=P("x"))
        """, "shard-spec-arity") == []


# ---------------------------------------------------------------------------
# PL012 collective-without-mesh
# ---------------------------------------------------------------------------

class TestCollectiveContext:
    def test_positive_collective_under_bare_jit(self):
        vs = lint("""
            import jax

            @jax.jit
            def f(x):
                return jax.lax.psum(x, "i")
        """, "collective-without-mesh")
        assert len(vs) == 1 and "psum" in vs[0].message

    def test_positive_reached_through_helper(self):
        vs = lint("""
            import jax

            def reduce_it(x):
                return jax.lax.psum(x, "i")

            @jax.jit
            def f(x):
                return reduce_it(x)
        """, "collective-without-mesh")
        assert len(vs) == 1

    def test_negative_inside_shard_map_target(self):
        assert lint("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def local(x):
                return jax.lax.psum(x, "data")

            @jax.jit
            def f(x):
                return shard_map(local, mesh=MESH, in_specs=(P("data"),),
                                 out_specs=P())(x)
        """, "collective-without-mesh") == []

    def test_negative_under_mesh_with_block(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                with mesh:
                    return jax.lax.psum(x, "i")
        """, "collective-without-mesh") == []

    def test_negative_untraced_code_is_quiet(self):
        assert lint("""
            import jax

            def helper(x):
                return jax.lax.psum(x, "i")
        """, "collective-without-mesh") == []

    def test_negative_pmap_target_reachable(self):
        assert lint("""
            import jax

            def local(x):
                return jax.lax.psum(x, "batch")

            g = jax.pmap(local, axis_name="batch")
        """, "collective-without-mesh") == []


# ---------------------------------------------------------------------------
# PL013 blocking-in-async
# ---------------------------------------------------------------------------

class TestBlockingInAsync:
    def test_positive_sleep_in_async_def(self):
        vs = lint("""
            import time

            async def handler():
                time.sleep(0.1)
        """, "blocking-in-async")
        assert len(vs) == 1 and "time.sleep" in vs[0].message

    def test_positive_through_call_graph(self):
        vs = lint("""
            def helper():
                with open("f") as fh:
                    return fh.read()

            async def handler():
                return helper()
        """, "blocking-in-async")
        assert len(vs) == 1 and "call graph" in vs[0].message

    def test_positive_future_result_in_scheduled_callback(self):
        vs = lint("""
            def _scored(pending, fut):
                return fut.result()

            def dispatch(loop, pending, fut):
                loop.call_soon_threadsafe(_scored, pending, fut)
        """, "blocking-in-async")
        assert len(vs) == 1 and "result()" in vs[0].message

    def test_negative_awaited_acquire_is_the_asyncio_form(self):
        assert lint("""
            async def guard(lock):
                await lock.acquire()
        """, "blocking-in-async") == []

    def test_positive_sync_acquire_in_async(self):
        vs = lint("""
            async def guard(lock):
                lock.acquire()
        """, "blocking-in-async")
        assert len(vs) == 1 and "acquire" in vs[0].message

    def test_negative_executor_handoff(self):
        assert lint("""
            import time

            def work():
                time.sleep(1.0)

            async def handler(loop):
                await loop.run_in_executor(None, work)
        """, "blocking-in-async") == []

    def test_negative_sync_module_is_quiet(self):
        assert lint("""
            import time

            def batch_job():
                time.sleep(5.0)
        """, "blocking-in-async") == []


# ---------------------------------------------------------------------------
# PL014 cross-module-donation (whole-program fixtures)
# ---------------------------------------------------------------------------

DONOR_MOD = """
    import jax

    def update(w, g):
        return w - g

    fit = jax.jit(update, donate_argnums=0)
"""


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return str(tmp_path)


def _by_rule(result, rule):
    return [v for v in result.violations if v.rule == rule]


class TestCrossModuleDonation:
    def _run(self, root):
        return run_analysis([os.path.join(root, "pkg")], root=root)

    def test_positive_read_after_imported_donor_call(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "donor.py": DONOR_MOD,
            "user.py": """
                from pkg.donor import fit

                def step(w, g):
                    out = fit(w, g)
                    return out + w
            """,
        })
        vs = _by_rule(self._run(root), "cross-module-donation")
        assert len(vs) == 1
        assert vs[0].path.endswith("user.py") and "w" in vs[0].message

    def test_positive_dotted_module_alias_reference(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "donor.py": DONOR_MOD,
            "user.py": """
                from pkg import donor

                def step(w, g):
                    out = donor.fit(w, g)
                    return out + w
            """,
        })
        vs = _by_rule(self._run(root), "cross-module-donation")
        assert len(vs) == 1

    def test_positive_donation_through_forwarding_wrapper(self, tmp_path):
        # donor exports a plain function that FORWARDS into the jitted
        # donor — the program-wide fixpoint must carry the spec through
        root = _write_pkg(tmp_path, {
            "donor.py": DONOR_MOD + """
    def apply(w, g):
        return fit(w, g)
""",
            "user.py": """
                from pkg.donor import apply

                def step(w, g):
                    out = apply(w, g)
                    return out + w
            """,
        })
        vs = _by_rule(self._run(root), "cross-module-donation")
        assert len(vs) == 1

    def test_negative_rebound_buffer_is_fine(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "donor.py": DONOR_MOD,
            "user.py": """
                from pkg.donor import fit

                def step(w, g):
                    w = fit(w, g)
                    return w + g
            """,
        })
        assert _by_rule(self._run(root), "cross-module-donation") == []

    def test_local_donors_stay_pl006_jurisdiction(self, tmp_path):
        # same-module read-after-donate: PL006's finding, not PL014's
        root = _write_pkg(tmp_path, {
            "donor.py": DONOR_MOD + """
    def local_step(w, g):
        out = fit(w, g)
        return out + w
""",
        })
        result = self._run(root)
        assert _by_rule(result, "cross-module-donation") == []
        # PL006 reports both its findings: the read-after-donate error and
        # the donated-parameter boundary warning
        pl006 = _by_rule(result, "donation-after-use")
        assert {v.severity for v in pl006} == {"error", "warning"}


# ---------------------------------------------------------------------------
# PL005 v2: alias-aware lock discipline
# ---------------------------------------------------------------------------

class TestLockAliasTracking:
    def test_positive_mutation_through_local_alias(self):
        vs = lint("""
            import threading

            class Store:
                def __init__(self, table):
                    self._lock = threading.Lock()
                    self._table = table

                def put(self, k, v):
                    with self._lock:
                        self._table[k] = v

                def leak(self, k, v):
                    table = self._table
                    table[k] = v
        """, "lock-discipline")
        assert len(vs) == 1 and "_table" in vs[0].message
        assert vs[0].line > 0

    def test_positive_chained_attribute_roots_at_self(self):
        vs = lint("""
            import threading

            class Store:
                def __init__(self, state):
                    self._lock = threading.Lock()
                    self._state = state

                def put(self, v):
                    with self._lock:
                        self._state.update(v)

                def leak(self, k, v):
                    self._state.table[k] = v
        """, "lock-discipline")
        assert len(vs) == 1 and "_state" in vs[0].message

    def test_negative_lock_held_through_alias(self):
        assert lint("""
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._table = {}

                def put(self, k, v):
                    with self._lock:
                        self._table[k] = v

                def also_fine(self, k, v):
                    lock = self._lock
                    with lock:
                        self._table[k] = v
        """, "lock-discipline") == []

    def test_negative_unrelated_local_object(self):
        assert lint("""
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._table = {}

                def put(self, k, v):
                    with self._lock:
                        self._table[k] = v

                def scratch(self, k, v):
                    fresh = {}
                    fresh[k] = v
                    return fresh
        """, "lock-discipline") == []


# ---------------------------------------------------------------------------
# --diff incremental mode (real git repo)
# ---------------------------------------------------------------------------

CLEAN_MOD = """
def add(a, b):
    return a + b
"""

BLOCKY_MOD = """
import time


async def handler():
    time.sleep(0.1)
"""


def _git(root, *args):
    subprocess.run(["git", "-C", root, "-c", "user.email=t@t",
                    "-c", "user.name=t", *args],
                   check=True, capture_output=True, text=True)


def _cli(root, *args):
    return subprocess.run(
        [sys.executable, "-m", "tools.photonlint", "--root", root,
         "--no-baseline", "--format", "json", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)


@pytest.fixture
def diff_repo(tmp_path):
    pkg = tmp_path / "photon_ml_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "steady.py").write_text(BLOCKY_MOD)   # committed violation
    (pkg / "mod.py").write_text(CLEAN_MOD)
    root = str(tmp_path)
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")
    return root


class TestDiffMode:
    def test_no_changes_is_clean_exit_zero(self, diff_repo):
        proc = _cli(diff_repo, "--diff", "HEAD")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "nothing to lint" in proc.stdout

    def test_diff_equals_full_run_restricted_to_changed(self, diff_repo):
        # introduce a violation in mod.py; steady.py's committed violation
        # must appear in the full run but NOT in the diff run
        with open(os.path.join(diff_repo, "photon_ml_tpu", "mod.py"),
                  "w") as f:
            f.write(BLOCKY_MOD)
        full = _cli(diff_repo, os.path.join(diff_repo, "photon_ml_tpu"))
        diff = _cli(diff_repo, "--diff", "HEAD")
        assert full.returncode == 1 and diff.returncode == 1
        full_new = json.loads(full.stdout)["new"]
        diff_new = json.loads(diff.stdout)["new"]
        changed = {"photon_ml_tpu/mod.py"}
        want = {(v["rule"], v["path"], v["line"]) for v in full_new
                if v["path"] in changed}
        got = {(v["rule"], v["path"], v["line"]) for v in diff_new}
        assert want and got == want
        assert any(v["path"] == "photon_ml_tpu/steady.py" for v in full_new)
        assert all(v["path"] != "photon_ml_tpu/steady.py" for v in diff_new)

    def test_untracked_files_are_linted(self, diff_repo):
        with open(os.path.join(diff_repo, "photon_ml_tpu", "fresh.py"),
                  "w") as f:
            f.write(BLOCKY_MOD)
        proc = _cli(diff_repo, "--diff", "HEAD")
        assert proc.returncode == 1
        paths = {v["path"] for v in json.loads(proc.stdout)["new"]}
        assert paths == {"photon_ml_tpu/fresh.py"}

    def test_diff_rejects_explicit_paths(self, diff_repo):
        proc = _cli(diff_repo, "--diff", "HEAD",
                    os.path.join(diff_repo, "photon_ml_tpu"))
        assert proc.returncode == 2

    def test_bad_ref_is_usage_error(self, diff_repo):
        proc = _cli(diff_repo, "--diff", "no-such-ref")
        assert proc.returncode == 2
        assert "git failed" in proc.stderr


# ---------------------------------------------------------------------------
# the dataflow gate over the real package
# ---------------------------------------------------------------------------

class TestDataflowGate:
    def test_package_clean_and_index_inside_budget(self):
        result = run_analysis([PKG_DIR], root=REPO_ROOT)
        assert result.violations == [], \
            "\n".join(f"{v.path}:{v.line}: {v.rule}: {v.message}"
                      for v in result.violations)
        assert result.index_build_s < 5.0
        # the dataflow pass ran and was accounted separately
        assert result.dataflow_s >= 0.0

    def test_json_summary_reports_dataflow_cost(self):
        from photon_ml_tpu.analysis import render_json
        result = run_analysis([PKG_DIR], root=REPO_ROOT)
        payload = json.loads(render_json([], [], [], result))
        assert "dataflow_s" in payload["summary"]
        assert payload["summary"]["dataflow_s"] >= 0.0

    def test_new_rules_are_registered(self):
        from photon_ml_tpu.analysis import registered_rules
        registry = registered_rules()
        codes = {cls.code for cls in registry.values()}
        assert {"PL011", "PL012", "PL013", "PL014"} <= codes
