"""Serving hot-path v2 tests: async deadline batching, frequency-ranked
device hot set, streaming coefficient deltas.

The contracts under test (ISSUE 4 / ROADMAP serving follow-ons):
  - AsyncBatcher: thread-safe submit -> future; flushes on a full bucket OR
    the deadline; shutdown drains pending futures; every future's score is
    bitwise the synchronous single-request score (padding parity).
  - Hot set: promotion/demotion tracks EWMA request frequency, is
    deterministic for a fixed trace, never changes a table shape (zero
    recompiles), and never changes a score (hot and cold tiers are
    bitwise-identical by construction).
  - Deltas: apply_delta rewrites one live row (device scatter when hot,
    archive + LRU invalidation always) and serves exactly what a fresh
    store built from the patched model would serve.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.serving.batcher import AsyncBatcher, BucketedBatcher, Request
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     HotSetManager,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.types import TaskType

N_ENT = 40
D = 4
NAMES = [f"f{j}" for j in range(D)]


def _model(seed=0):
    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    return GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    }), task


def _store(model, task, capacity, lru=16, decay=0.5, metrics=None,
           max_moves=None):
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    return CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=capacity, lru_capacity=lru,
                           hot_decay=decay, hot_max_moves=max_moves),
        version="synthetic", metrics=metrics)


def _engine(capacity=None, max_batch=8, seed=0, metrics=None, decay=0.5):
    model, task = _model(seed)
    metrics = metrics or ServingMetrics()
    store = _store(model, task, capacity, metrics=metrics, decay=decay)
    eng = ScoringEngine(store, BucketedBatcher(max_batch), metrics=metrics)
    eng.warm()
    return eng, model, task


def _req(rng, uid=0, user=None):
    feats = [{"name": n, "term": "", "value": float(v)}
             for n, v in zip(NAMES, rng.normal(size=D))]
    user = user if user is not None else int(rng.integers(0, N_ENT))
    return Request(uid=uid, features=feats, ids={"userId": f"user{user}"})


# ---------------------------------------------------------------------------
# async deadline batcher
# ---------------------------------------------------------------------------
class TestAsyncBatcher:
    def test_full_flush_parity(self):
        eng, _, _ = _engine(max_batch=4)
        rng = np.random.default_rng(1)
        reqs = [_req(rng, uid=i) for i in range(8)]
        with eng.async_batcher(deadline_s=10.0) as ab:
            futs = [ab.submit(r) for r in reqs]
            got = [f.result(timeout=30) for f in futs]
        # every future resolves to ITS request's score.  The async batcher
        # may group arrivals into any bucket size, and XLA's reduction
        # order differs by one ulp across bucket shapes — so compare at
        # float tolerance here; the bitwise same-list guarantee is held by
        # tests/test_serving.py's parity property
        for r, s in zip(reqs, got):
            assert s == pytest.approx(float(eng.score_requests([r])[0]),
                                      rel=1e-9, abs=1e-12)
        # 8 submits at threshold 4 with an un-hittable deadline: only full
        # flushes fire
        assert eng.metrics.counter("flushes_full") >= 1
        assert eng.metrics.counter("flushes_deadline") == 0

    def test_deadline_flush_low_qps(self):
        eng, _, _ = _engine(max_batch=8)
        rng = np.random.default_rng(2)
        with eng.async_batcher(deadline_s=0.01) as ab:
            futs = [ab.submit(_req(rng, uid=i)) for i in range(3)]
            got = [f.result(timeout=30) for f in futs]  # no bucket ever fills
        assert all(np.isfinite(got))
        assert eng.metrics.counter("flushes_deadline") >= 1

    def test_concurrent_submits(self):
        eng, _, _ = _engine(max_batch=8)
        per_thread = 25
        results = {}

        def worker(tid):
            rng = np.random.default_rng(100 + tid)
            pairs = []
            with_futs = []
            for i in range(per_thread):
                r = _req(rng, uid=(tid, i))
                with_futs.append((r, ab.submit(r)))
            for r, f in with_futs:
                pairs.append((r, f.result(timeout=60)))
            results[tid] = pairs

        with eng.async_batcher(deadline_s=0.002) as ab:
            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        assert all(not t.is_alive() for t in threads)
        assert sum(len(v) for v in results.values()) == 4 * per_thread
        # interleaved multi-producer traffic still scores each request
        # correctly (tolerance: bucket-shape reduction-order ulps, see
        # test_full_flush_parity)
        for pairs in results.values():
            for r, s in pairs:
                assert s == pytest.approx(float(eng.score_requests([r])[0]),
                                          rel=1e-9, abs=1e-12)

    def test_flush_forces_pending(self):
        eng, _, _ = _engine(max_batch=8)
        rng = np.random.default_rng(3)
        ab = eng.async_batcher(deadline_s=60.0)
        try:
            futs = [ab.submit(_req(rng, uid=i)) for i in range(3)]
            assert ab.flush() == futs
            for f in futs:
                assert np.isfinite(f.result(timeout=30))
            assert eng.metrics.counter("flushes_forced") >= 1
        finally:
            ab.shutdown()

    def test_shutdown_drains_pending_futures(self):
        eng, _, _ = _engine(max_batch=8)
        rng = np.random.default_rng(4)
        ab = eng.async_batcher(deadline_s=60.0)  # deadline can never fire
        futs = [ab.submit(_req(rng, uid=i)) for i in range(5)]
        ab.shutdown(drain=True)
        assert all(f.done() and not f.cancelled() for f in futs)
        assert all(np.isfinite(f.result()) for f in futs)
        with pytest.raises(RuntimeError):
            ab.submit(_req(rng))
        ab.shutdown()  # idempotent

    def test_shutdown_no_drain_cancels(self):
        eng, _, _ = _engine(max_batch=8)
        rng = np.random.default_rng(5)
        ab = eng.async_batcher(deadline_s=60.0)
        futs = [ab.submit(_req(rng, uid=i)) for i in range(3)]
        ab.shutdown(drain=False)
        assert all(f.cancelled() for f in futs)

    def test_score_error_resolves_futures(self):
        def boom(reqs):
            raise RuntimeError("scorer down")

        ab = AsyncBatcher(boom, flush_threshold=2, deadline_s=0.005)
        try:
            f1 = ab.submit(Request(uid=1))
            f2 = ab.submit(Request(uid=2))
            with pytest.raises(RuntimeError, match="scorer down"):
                f1.result(timeout=30)
            with pytest.raises(RuntimeError, match="scorer down"):
                f2.result(timeout=30)
        finally:
            ab.shutdown()

    def test_validation(self):
        with pytest.raises(ValueError):
            AsyncBatcher(lambda r: [], flush_threshold=0)
        with pytest.raises(ValueError):
            AsyncBatcher(lambda r: [], flush_threshold=1, deadline_s=-1.0)


class TestBatcherIntrospection:
    """pending_count / flush_cost_estimate / queue_wait_estimate — the
    backlog predictor the front end's admission controller reads."""

    @staticmethod
    def _dummy(flush_threshold, deadline_s):
        return AsyncBatcher(lambda reqs: np.zeros(len(reqs)),
                            flush_threshold=flush_threshold,
                            deadline_s=deadline_s)

    def test_estimate_wave_arithmetic_before_any_flush(self):
        # nothing observed yet: the flush-cost EWMA floors at the deadline,
        # so every term of the estimate is exact arithmetic
        ab = self._dummy(flush_threshold=4, deadline_s=10.0)
        try:
            assert ab.pending_count() == 0
            assert ab.flush_cost_estimate() == 10.0
            # empty queue: the arriving request is a non-full tail wave —
            # one flush cost plus the residual deadline wait
            assert ab.queue_wait_estimate() == pytest.approx(20.0)
            # 3 ahead + itself = exactly one full wave: no deadline wait
            assert ab.queue_wait_estimate(extra=3) == pytest.approx(10.0)
            # 8 ahead + itself = 2 full waves + a tail
            assert ab.queue_wait_estimate(extra=8) == pytest.approx(40.0)
        finally:
            ab.shutdown(drain=False)

    def test_pending_count_tracks_submits_and_flush(self):
        ab = self._dummy(flush_threshold=64, deadline_s=10.0)
        try:
            futs = [ab.submit(Request(uid=i, features=[], ids={}))
                    for i in range(3)]
            assert ab.pending_count() == 3
            ab.flush()
            for f in futs:
                f.result(timeout=30)
            assert ab.pending_count() == 0
            # that flush was observed: the EWMA left its deadline floor
            assert ab.flush_cost_estimate() < 10.0
        finally:
            ab.shutdown()

    def test_ewma_converges_to_observed_flush_cost(self):
        delay = 0.005

        def slow_score(reqs):
            time.sleep(delay)
            return np.zeros(len(reqs))

        ab = AsyncBatcher(slow_score, flush_threshold=1, deadline_s=10.0)
        try:
            for i in range(10):
                ab.submit(Request(uid=i, features=[], ids={})) \
                  .result(timeout=30)
            est = ab.flush_cost_estimate()
            assert delay * 0.8 < est < delay * 10
            # empty queue + threshold 1: the next request is one full wave
            wait = ab.queue_wait_estimate()
            assert delay * 0.8 < wait < delay * 10
        finally:
            ab.shutdown()

    def test_shutdown_under_concurrent_submit_never_drops(self):
        """The drain contract under racing producers: every submit either
        raises RuntimeError (batcher closed) or returns a future that
        RESOLVES to a score — no future is ever silently dropped or
        cancelled by shutdown(drain=True)."""
        eng, _, _ = _engine(max_batch=4)
        ab = eng.async_batcher(deadline_s=0.0005)
        accepted = []

        def producer(tid):
            rng = np.random.default_rng(500 + tid)
            mine = []
            for i in range(50):
                try:
                    mine.append(ab.submit(_req(rng, uid=f"{tid}-{i}")))
                except RuntimeError:
                    break  # closed under us: the expected race outcome
            accepted.extend(mine)  # one atomic extend per thread

        threads = [threading.Thread(target=producer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.005)  # let producers race the drain
        ab.shutdown(drain=True)
        for t in threads:
            t.join(60)
        assert accepted, "no submits landed before shutdown?"
        for f in accepted:
            assert np.isfinite(f.result(timeout=30))


# ---------------------------------------------------------------------------
# frequency-ranked hot set
# ---------------------------------------------------------------------------
def _trace(engine, users, rng, repeats=3):
    """Score a deterministic trace concentrated on ``users``."""
    for _ in range(repeats):
        reqs = [_req(rng, uid=i, user=u) for i, u in enumerate(users)]
        engine.score_requests(reqs)


class TestHotSet:
    def test_promotion_tracks_traffic(self):
        metrics = ServingMetrics()
        model, task = _model()
        store = _store(model, task, capacity=8, metrics=metrics)
        eng = ScoringEngine(store, BucketedBatcher(8), metrics=metrics)
        eng.warm()
        compiles = eng.compile_count
        coord = store.coordinates["user"]
        assert set(coord.hot_slot_of) == set(range(8))  # training-slot order

        hot_users = list(range(30, 38))
        rng = np.random.default_rng(7)
        _trace(eng, hot_users, rng)
        moves = store.rebalance()
        assert moves["user"] == (8, 8)  # full turnover to the traffic
        assert set(coord.hot_slot_of) == set(hot_users)
        assert metrics.counter("hot_promotions") == 8
        assert metrics.counter("rebalances") == 1

        # residency moved; scores must not — and no recompile either
        ref_eng, _, _ = _engine(capacity=None)  # all-hot reference
        rng2 = np.random.default_rng(8)
        reqs = [_req(rng2, uid=i) for i in range(13)]
        np.testing.assert_array_equal(eng.score_requests(reqs),
                                      ref_eng.score_requests(reqs))
        assert eng.compile_count == compiles
        assert store.signature() == _store(model, task, capacity=8).signature()

    def test_hot_set_deterministic_for_fixed_trace(self):
        def run():
            model, task = _model()
            store = _store(model, task, capacity=6)
            eng = ScoringEngine(store, BucketedBatcher(8))
            rng = np.random.default_rng(11)
            _trace(eng, [3, 17, 17, 25, 25, 25, 31, 9, 9, 40 % N_ENT], rng)
            store.rebalance()
            _trace(eng, [17, 25, 31, 31, 31, 2], rng)
            store.rebalance()
            return dict(store.coordinates["user"].hot_slot_of)

        first, second = run(), run()
        assert first == second  # identical entities AND device rows

    def test_ewma_ages_out_stale_entities(self):
        model, task = _model()
        store = _store(model, task, capacity=4, decay=0.5)
        eng = ScoringEngine(store, BucketedBatcher(8))
        coord = store.coordinates["user"]
        rng = np.random.default_rng(13)
        _trace(eng, [20, 21, 22, 23], rng, repeats=2)
        store.rebalance()
        assert set(coord.hot_slot_of) == {20, 21, 22, 23}
        # traffic moves entirely; the old set decays below the new one
        for _ in range(4):
            _trace(eng, [30, 31, 32, 33], rng, repeats=2)
            store.rebalance()
        assert set(coord.hot_slot_of) == {30, 31, 32, 33}

    def test_max_moves_caps_turnover(self):
        model, task = _model()
        store = _store(model, task, capacity=8, max_moves=3)
        eng = ScoringEngine(store, BucketedBatcher(8))
        rng = np.random.default_rng(17)
        _trace(eng, list(range(30, 38)), rng)
        assert store.rebalance()["user"] == (3, 3)

    def test_rebalance_noop_when_all_hot_or_all_cold(self):
        model, task = _model()
        for capacity in (None, 0):
            store = _store(model, task, capacity=capacity)
            eng = ScoringEngine(store, BucketedBatcher(8))
            rng = np.random.default_rng(19)
            _trace(eng, [1, 2, 3], rng)
            assert store.rebalance()["user"] == (0, 0)

    def test_padding_rows_not_counted_as_misses(self):
        metrics = ServingMetrics()
        model, task = _model()
        store = _store(model, task, capacity=None, metrics=metrics)
        eng = ScoringEngine(store, BucketedBatcher(8), metrics=metrics)
        rng = np.random.default_rng(23)
        eng.score_requests([_req(rng, uid=i) for i in range(3)])  # bucket 4
        assert metrics.counter("entity_misses") == 0  # padding row is silent
        assert metrics.counter("hot_hits") == 3
        assert metrics.snapshot()["hot_set_hit_rate"] == 1.0

    def test_hot_set_manager_background(self):
        model, task = _model()
        store = _store(model, task, capacity=4)
        eng = ScoringEngine(store, BucketedBatcher(8))
        rng = np.random.default_rng(29)
        mgr = HotSetManager(lambda: eng.store, interval_s=0.01).start()
        try:
            deadline = time.time() + 20
            while (set(store.coordinates["user"].hot_slot_of) != {30, 31, 32,
                                                                  33}
                   and time.time() < deadline):
                _trace(eng, [30, 31, 32, 33], rng)
        finally:
            mgr.stop(timeout=10)
        assert set(store.coordinates["user"].hot_slot_of) == {30, 31, 32, 33}


# ---------------------------------------------------------------------------
# streaming coefficient deltas
# ---------------------------------------------------------------------------
class TestDeltas:
    def _patched_reference(self, model, task, user, row, capacity=None):
        """Fresh engine built from the model with ``user``'s row replaced —
        what serving must match after an in-place delta."""
        import dataclasses

        patched, _ = _model()  # same seed -> identical weights
        re_model = patched.models["user"]
        stack = np.array(re_model.w_stack)
        stack[user] = row
        patched = GameModel(models={
            "fixed": patched.models["fixed"],
            "user": dataclasses.replace(re_model, w_stack=stack),
        })
        store = _store(patched, task, capacity)
        eng = ScoringEngine(store, BucketedBatcher(8))
        return eng

    def test_delta_hot_entity_scatters_device_row(self):
        eng, model, task = _engine(capacity=None)
        compiles = eng.compile_count
        rng = np.random.default_rng(31)
        req = _req(rng, uid=1, user=5)
        before = eng.score_requests([req])[0]
        new_row = np.full(D, 0.25, np.float64)
        assert eng.store.apply_delta("user", "user5", new_row) is True
        after = eng.score_requests([req])[0]
        assert after != before
        ref = self._patched_reference(model, task, 5, new_row)
        np.testing.assert_array_equal(eng.score_requests([req]),
                                      ref.score_requests([req]))
        assert eng.compile_count == compiles  # no shape change, no compile

    def test_delta_cold_entity_invalidates_lru(self):
        metrics = ServingMetrics()
        model, task = _model()
        store = _store(model, task, capacity=4, metrics=metrics)
        eng = ScoringEngine(store, BucketedBatcher(8), metrics=metrics)
        rng = np.random.default_rng(37)
        req = _req(rng, uid=1, user=20)  # slot 20 >= capacity 4: cold
        eng.score_requests([req])          # pulls the row into the LRU
        eng.score_requests([req])
        assert metrics.counter("lru_hits") >= 1
        new_row = np.linspace(-1, 1, D)
        assert store.apply_delta("user", "user20", new_row) is True
        ref = self._patched_reference(model, task, 20, new_row, capacity=4)
        np.testing.assert_array_equal(eng.score_requests([req]),
                                      ref.score_requests([req]))
        assert metrics.counter("delta_updates") == 1

    def test_delta_survives_rebalance_both_directions(self):
        """A delta'd row keeps serving its new value through promotion AND
        demotion (archive and device table stay coherent)."""
        model, task = _model()
        store = _store(model, task, capacity=4)
        eng = ScoringEngine(store, BucketedBatcher(8))
        rng = np.random.default_rng(41)
        new_row = np.full(D, -0.5)
        store.apply_delta("user", "user20", new_row)  # cold at apply time
        req = _req(rng, uid=1, user=20)
        ref = self._patched_reference(model, task, 20, new_row, capacity=4)
        want = ref.score_requests([req])
        np.testing.assert_array_equal(eng.score_requests([req]), want)
        # hammer user20 so it promotes, then verify the DEVICE copy is new
        _trace(eng, [20, 20, 20, 20], rng)
        store.rebalance()
        assert 20 in store.coordinates["user"].hot_slot_of
        np.testing.assert_array_equal(eng.score_requests([req]), want)

    def test_delta_rejections(self):
        eng, _, _ = _engine(capacity=None)
        store = eng.store
        assert store.apply_delta("user", "no-such-user", np.zeros(D)) is False
        with pytest.raises(ValueError, match="fixed"):
            store.apply_delta("fixed", "user1", np.zeros(D))
        with pytest.raises(ValueError, match="unknown coordinate"):
            store.apply_delta("nope", "user1", np.zeros(D))
        with pytest.raises(ValueError, match="shape"):
            store.apply_delta("user", "user1", np.zeros(D + 1))

    def test_swapper_delta_version(self):
        eng, _, _ = _engine(capacity=None)
        swapper = HotSwapper(eng)
        assert swapper.delta_version == 0
        assert swapper.apply_delta("user", "user3", np.zeros(D)) is True
        assert swapper.apply_delta("user", "user4", np.ones(D)) is True
        assert swapper.delta_version == 2
        # rejected deltas never bump the version
        assert swapper.apply_delta("user", "ghost", np.zeros(D)) is False
        assert swapper.apply_delta("fixed", "user1", np.zeros(D)) is False
        assert swapper.delta_version == 2
        assert eng.metrics.counter("delta_rejects") == 2
        assert eng.metrics.counter("delta_updates") == 2


# ---------------------------------------------------------------------------
# the async JSON-lines driver end to end
# ---------------------------------------------------------------------------
N_USERS = 6
FEATURES = ["g0", "g1", "g2", "ux"]


def _write_fixture(path, n=250, seed=0):
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE

    rng = np.random.default_rng(seed)
    uw = rng.normal(size=(N_USERS, 1)) * 1.5
    gw = np.asarray([0.8, -1.2, 0.5])
    records = []
    for i in range(n):
        u = int(rng.integers(0, N_USERS))
        xg = rng.normal(size=3)
        xu = rng.normal(size=1)
        logit = xg @ gw + xu @ uw[u]
        y = float(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        feats = [{"name": f"g{j}", "term": "", "value": float(xg[j])}
                 for j in range(3)]
        feats.append({"name": "ux", "term": "", "value": float(xu[0])})
        records.append({"uid": i, "response": y, "label": None,
                        "features": feats, "weight": None, "offset": None,
                        "metadataMap": {"userId": f"user{u}"}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    from photon_ml_tpu.cli import train as train_cli

    tmp = tmp_path_factory.mktemp("serving_async")
    data = str(tmp / "train.avro")
    _write_fixture(data, n=250, seed=1)
    out = str(tmp / "model")
    rc = train_cli.run([
        "--train-data", data, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--id-tags", "userId", "--coordinate-descent-iterations", "2",
        "--output-dir", out])
    assert rc == 0
    return out


class TestServeCliAsync:
    def test_async_stream_delta_rebalance(self, model_dir, tmp_path, capsys):
        from photon_ml_tpu.cli import serve as serve_cli

        feats = [[f, 0.5] for f in FEATURES]
        lines = [
            json.dumps({"uid": 0, "features": feats,
                        "ids": {"userId": "user3"}}),
            json.dumps({"uid": 1, "features": feats,
                        "ids": {"userId": "user1"}}),
            "",  # force-flush + drain
            # the trained shard has 5 columns (4 features + intercept)
            json.dumps({"cmd": "delta", "coordinate": "user",
                        "entity": "user3", "row": [2.0, 2.0, 2.0, 2.0, 2.0]}),
            json.dumps({"uid": 2, "features": feats,
                        "ids": {"userId": "user3"}}),
            json.dumps({"cmd": "rebalance"}),
            json.dumps({"cmd": "metrics"}),
            json.dumps({"cmd": "swap", "model_dir": model_dir}),
        ]
        req_file = tmp_path / "requests.jsonl"
        req_file.write_text("\n".join(lines) + "\n")
        metrics_file = str(tmp_path / "metrics.json")

        rc = serve_cli.run(["--model-dir", model_dir, "--max-batch", "8",
                            "--deadline-us", "2000",
                            "--requests", str(req_file),
                            "--metrics-json", metrics_file])
        assert rc == 0
        out = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
        scores = {o["uid"]: o["score"] for o in out if "score" in o}
        assert sorted(scores) == [0, 1, 2]
        # uid 2 rescored user3 AFTER the delta rewrote its row
        assert scores[2] != scores[0]
        deltas = [o for o in out if "delta" in o]
        assert deltas == [{"delta": "ok", "delta_version": 1}]
        rebalances = [o for o in out if "rebalance" in o]
        assert len(rebalances) == 1 and "user" in rebalances[0]["rebalance"]
        swaps = [o for o in out if "swap" in o]
        assert swaps[0]["swap"] == "ok"
        assert swaps[0]["delta_version"] == 0  # swap resets the counter
        exported = json.load(open(metrics_file))
        assert exported["counters"]["requests"] == 3
        assert exported["counters"]["delta_updates"] == 1
        assert exported["counters"]["flushes_forced"] >= 1
        assert "bucket_occupancy" in exported
        assert "hot_set_hit_rate" in exported

    def test_sync_batcher_flag_still_works(self, model_dir, tmp_path, capsys):
        from photon_ml_tpu.cli import serve as serve_cli

        lines = [json.dumps({"uid": i, "features": [[f, 0.1] for f in FEATURES],
                             "ids": {"userId": f"user{i}"}})
                 for i in range(3)]
        req_file = tmp_path / "requests.jsonl"
        req_file.write_text("\n".join(lines) + "\n")
        rc = serve_cli.run(["--model-dir", model_dir, "--max-batch", "8",
                            "--sync-batcher",
                            "--requests", str(req_file)])
        assert rc == 0
        out = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
        assert [o["uid"] for o in out if "score" in o] == [0, 1, 2]
