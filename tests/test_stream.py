"""photonstream tests: out-of-core streaming ingest + double-buffered feed.

The contracts from the streaming data plane:
  - ``stream_game_data`` produces a GameData BITWISE-equal to the eager
    ``read_game_data_avro`` on RAM-sized data — scalars, entity indexes,
    design matrices, and full FE+RE fits through the estimator.
  - A dataset >= 4x the configured resident-batch budget streams to a
    completed multi-coordinate fit with host allocation peak bounded by
    the pipeline window + in-flight batches, far below the in-memory
    path's [n, d] materialization — and with ZERO update-program
    recompiles on a second identically-shaped pass.
  - Malformed input is a clean per-chunk error under either policy knob:
    ``raise`` surfaces it, ``skip`` keeps row counts honest (lost rows
    inert at weight 0) — never a hang, never a silent short epoch.
  - ``EntityStats`` replicates ``_group_rows`` exactly (rows, entity
    order, rescale floats) in both full and capped accumulation modes.
"""

import os
import threading

import numpy as np
import pytest

import jax

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.avro import read_block, scan_container_blocks
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import (EntityIndex, read_game_data_avro,
                                       read_libsvm)
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.game.config import (FixedEffectConfig, GameConfig,
                                       RandomEffectConfig)
from photon_ml_tpu.game.estimator import GameEstimator
from photon_ml_tpu.obs import trace as _trace
from photon_ml_tpu.obs.probe import get_probe
from photon_ml_tpu.obs.registry import (MetricsRegistry, get_registry,
                                        set_registry)
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.opt import streamfold
from photon_ml_tpu.parallel.bucketing import _group_rows
from photon_ml_tpu.stream import (ChunkPipeline, EntityStats,
                                  stream_game_data, stream_libsvm)
from photon_ml_tpu.stream.chunks import AvroStreamSource
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import transfer


# ---------------------------------------------------------------------------
# dataset helpers
# ---------------------------------------------------------------------------

def _example(uid, y, feats, weight=None, offset=None, meta=None):
    return {
        "uid": uid, "response": y, "label": None,
        "features": [{"name": n, "term": t, "value": v} for n, t, v in feats],
        "weight": weight, "offset": offset, "metadataMap": meta,
    }


def _write_dataset(dirpath, n_rows, n_users=13, n_feats=6, n_files=2,
                   block_records=64, seed=0, codec="deflate", max_k=None):
    """Synthetic TrainingExampleAvro files + the index map covering them.

    ``max_k`` caps features per record — small records against a wide map
    keep the out-of-core test's Python-object weight off the host-memory
    measurement."""
    rng = np.random.default_rng(seed)
    names = [f"f{j}" for j in range(n_feats)]
    os.makedirs(dirpath, exist_ok=True)
    per_file = n_rows // n_files
    uid = 0
    for fi in range(n_files):
        records = []
        n_here = per_file if fi < n_files - 1 else n_rows - uid
        for _ in range(n_here):
            k = int(rng.integers(1, (max_k or n_feats) + 1))
            idx = rng.choice(n_feats, size=k, replace=False)
            records.append(_example(
                uid, float(rng.integers(0, 2)),
                [(names[j], "", float(v))
                 for j, v in zip(idx, rng.normal(size=k))],
                weight=float(rng.uniform(0.5, 2.0)),
                offset=float(rng.normal() * 0.1),
                meta={"userId": f"u{int(rng.integers(0, n_users))}"}))
            uid += 1
        avro_io.write_container(os.path.join(dirpath, f"part-{fi:05d}.avro"),
                                TRAINING_EXAMPLE, records,
                                block_records=block_records, codec=codec)
    imap = IndexMap.from_features([(nm, "") for nm in names],
                                  add_intercept=True)
    return {"global": imap}


def _fit(data, active_cap=None, min_active=1, iters=2):
    solver = SolverConfig(max_iters=25, tolerance=1e-8)
    cfg = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="global", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": RandomEffectConfig(
                random_effect_type="userId", feature_shard="global",
                solver=solver, reg=Regularization(l2=1.0),
                active_cap=active_cap, min_active_samples=min_active),
        },
        num_outer_iterations=iters)
    return GameEstimator().fit(data, [cfg])[0]


# ---------------------------------------------------------------------------
# container block scan (the chunk boundary source)
# ---------------------------------------------------------------------------

class TestBlockScan:
    def test_scan_counts_offsets_roundtrip(self, tmp_path):
        path = str(tmp_path / "d.avro")
        records = [_example(i, 1.0, [("f", "", float(i))])
                   for i in range(1000)]
        avro_io.write_container(path, TRAINING_EXAMPLE, records,
                                block_records=128)
        info = scan_container_blocks(path)
        assert info.num_records == 1000
        assert [b.count for b in info.blocks] == [128] * 7 + [104]
        assert all(not b.torn for b in info.blocks)
        offs = [b.offset for b in info.blocks]
        assert offs == sorted(offs) and offs[0] > 0
        br = avro_io._Reader(
            read_block(path, info.blocks[3], info.codec, info.sync))
        got = [avro_io.decode(info.schema, br, {}) for _ in range(128)]
        assert got == records[3 * 128: 4 * 128]

    def test_payload_torn_block_keeps_count(self, tmp_path):
        path = str(tmp_path / "d.avro")
        avro_io.write_container(
            path, TRAINING_EXAMPLE,
            [_example(i, 1.0, [("f", "", 1.0)]) for i in range(300)],
            block_records=100)
        good = scan_container_blocks(path)
        raw = open(path, "rb").read()
        # cut INSIDE the last block's payload: header (count+size) intact
        last = good.blocks[-1]
        open(path, "wb").write(raw[:last.offset + last.size // 2])
        info = scan_container_blocks(path)
        assert info.blocks[-1].torn and info.blocks[-1].count == 100
        assert info.num_records == 300  # torn-but-counted rows stay in n
        with pytest.raises(ValueError, match="torn block"):
            read_block(path, info.blocks[-1], info.codec, info.sync)

    def test_header_torn_block_excluded_from_n(self, tmp_path):
        path = str(tmp_path / "d.avro")
        avro_io.write_container(
            path, TRAINING_EXAMPLE,
            [_example(i, 1.0, [("f", "", 1.0)]) for i in range(300)],
            block_records=100)
        good = scan_container_blocks(path)
        raw = open(path, "rb").read()
        # cut MID-VARINT in the last block's header: count unknowable
        prev_end = good.blocks[-2].offset + good.blocks[-2].size + 16
        open(path, "wb").write(raw[:prev_end + 1])
        info = scan_container_blocks(path)
        assert info.blocks[-1].torn and info.blocks[-1].count == -1
        assert info.num_records == 200  # honest exclusion, no guessing


# ---------------------------------------------------------------------------
# bitwise parity with the eager reader
# ---------------------------------------------------------------------------

class TestStreamParity:
    def test_game_data_bitwise_equal(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 700, block_records=96, seed=3)
        eager, eidx = read_game_data_avro(
            sorted(os.path.join(d, p) for p in os.listdir(d)),
            index_maps, id_tag_names=["userId"])
        streamed, sidx = stream_game_data(d, index_maps,
                                          id_tag_names=["userId"],
                                          batch_rows=128)
        assert np.array_equal(streamed.y, eager.y)
        assert np.array_equal(streamed.offset, eager.offset)
        assert np.array_equal(streamed.weight, eager.weight)
        assert np.array_equal(streamed.uids, eager.uids)
        assert np.array_equal(streamed.id_tags["userId"],
                              eager.id_tags["userId"])
        assert sidx["userId"]._fwd == eidx["userId"]._fwd
        x = np.asarray(streamed.features["global"])
        assert x.dtype == np.float32
        assert np.array_equal(x, eager.features["global"])

    @pytest.mark.parametrize("active_cap", [None, 7])
    def test_full_fit_bitwise_equal(self, tmp_path, active_cap):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 600, n_users=9, block_records=80,
                                    seed=11)
        paths = sorted(os.path.join(d, p) for p in os.listdir(d))
        eager, _ = read_game_data_avro(paths, index_maps,
                                       id_tag_names=["userId"])
        streamed, _ = stream_game_data(
            d, index_maps, id_tag_names=["userId"], batch_rows=128,
            active_caps={"userId": active_cap} if active_cap else None)
        re = _fit(eager, active_cap=active_cap)
        rs = _fit(streamed, active_cap=active_cap)
        assert np.array_equal(
            np.asarray(rs.model["fixed"].coefficients.means),
            np.asarray(re.model["fixed"].coefficients.means))
        assert np.array_equal(np.asarray(rs.model["per-user"].w_stack),
                              np.asarray(re.model["per-user"].w_stack))

    def test_libsvm_stream_parity(self, tmp_path):
        path = str(tmp_path / "a.t")
        rng = np.random.default_rng(5)
        with open(path, "w") as f:
            for _ in range(300):
                lbl = "+1" if rng.random() < 0.5 else "-1"
                pairs = sorted(rng.choice(20, size=4, replace=False) + 1)
                f.write(lbl + " " + " ".join(
                    f"{j}:{rng.normal():.6g}" for j in pairs) + "\n")
        xe, ye, ie = read_libsvm(path, num_features=20)
        xs, ys, is_ = stream_libsvm(path, num_features=20, batch_rows=64)
        assert (ie, is_) == (0, 0)
        assert np.array_equal(ys, ye)
        assert np.array_equal(np.asarray(xs), xe)


# ---------------------------------------------------------------------------
# out-of-core: memory bound + fixed-shape (no recompile) contract
# ---------------------------------------------------------------------------

class TestOutOfCore:
    def test_memory_bounded_multi_coordinate_fit(self, tmp_path):
        """Stream a dataset >= 4x the resident-batch budget: the fit
        completes, and the host allocation peak (tracemalloc — the design
        matrix itself lives on device) stays far under the eager path's
        [n, d] host materialization."""
        import tracemalloc

        d = str(tmp_path / "big")
        n, n_feats, batch_rows = 8192, 256, 256
        index_maps = _write_dataset(d, n, n_users=17, n_feats=n_feats,
                                    n_files=4, block_records=256, seed=7,
                                    max_k=4)
        dim = index_maps["global"].size
        matrix_bytes = n * dim * 4
        # resident budget: 2 in-flight [batch_rows, dim] buffers + the
        # decoded-chunk window; the dataset's design is >= 4x that
        budget = 2 * batch_rows * dim * 4
        assert matrix_bytes >= 4 * budget

        paths = sorted(os.path.join(d, p) for p in os.listdir(d))
        tracemalloc.start()
        eager, _ = read_game_data_avro(paths, index_maps,
                                       id_tag_names=["userId"])
        _, eager_peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        del eager

        tracemalloc.start()
        streamed, _ = stream_game_data(d, index_maps,
                                       id_tag_names=["userId"],
                                       batch_rows=batch_rows)
        _, stream_peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()

        assert stream_peak < eager_peak
        assert stream_peak < matrix_bytes / 2  # never holds [n, d] on host
        res = _fit(streamed, iters=1)
        assert np.isfinite(
            np.asarray(res.model["fixed"].coefficients.means)).all()
        assert np.isfinite(np.asarray(res.model["per-user"].w_stack)).all()

    def test_zero_recompiles_on_second_pass(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 500, block_records=64, seed=2)
        stream_game_data(d, index_maps, id_tag_names=["userId"],
                         batch_rows=128)  # warm: batch + ragged tail
        before = transfer._UPDATE._cache_size()
        stream_game_data(d, index_maps, id_tag_names=["userId"],
                         batch_rows=128)
        assert transfer._UPDATE._cache_size() == before


# ---------------------------------------------------------------------------
# malformed input: policy knob, no hangs, no silent short epochs
# ---------------------------------------------------------------------------

def _corrupt_middle_block(d):
    """Destroy one middle block's trailing sync marker in the first file
    (a deterministic "corrupt block" read failure — the scan's varint walk
    is untouched, so the block's row count stays known).  Returns that
    block's row count and its starting global row."""
    path = sorted(os.path.join(d, p) for p in os.listdir(d))[0]
    info = scan_container_blocks(path)
    i = len(info.blocks) // 2
    span = info.blocks[i]
    raw = bytearray(open(path, "rb").read())
    for off in range(span.offset + span.size, span.offset + span.size + 16):
        raw[off] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    return span.count, sum(b.count for b in info.blocks[:i])


class TestMalformedInput:
    def test_raise_policy_surfaces_corrupt_chunk(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 400, block_records=50, seed=4)
        _corrupt_middle_block(d)
        with pytest.raises(ValueError, match="corrupt|sync|torn"):
            stream_game_data(d, index_maps, id_tag_names=["userId"],
                             batch_rows=64)

    def test_skip_policy_keeps_row_count_honest(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 400, block_records=50, seed=4)
        paths = sorted(os.path.join(d, p) for p in os.listdir(d))
        eager, _ = read_game_data_avro(paths, index_maps,
                                       id_tag_names=["userId"])
        lost, lost_start = _corrupt_middle_block(d)

        prev = get_registry()
        reg = MetricsRegistry()
        set_registry(reg)
        try:
            streamed, _ = stream_game_data(d, index_maps,
                                           id_tag_names=["userId"],
                                           batch_rows=64, on_error="skip")
        finally:
            set_registry(prev)
        # n preserved; lost rows inert at weight 0; everything else intact
        assert streamed.num_samples == eager.num_samples
        sl = slice(lost_start, lost_start + lost)
        assert np.all(streamed.weight[sl] == 0.0)
        keep = np.ones(eager.num_samples, bool)
        keep[sl] = False
        assert np.array_equal(streamed.y[keep], eager.y[keep])
        assert np.array_equal(streamed.weight[keep], eager.weight[keep])
        x_s = np.asarray(streamed.features["global"])
        assert np.array_equal(x_s[keep], eager.features["global"][keep])
        assert np.all(x_s[sl] == 0.0)
        assert reg.counter("stream_chunk_errors_total") == 1
        assert reg.counter("stream_skipped_rows_total") == lost
        assert reg.counter("stream_chunks_total") > 0

    def test_sparse_shards_rejected(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 50, block_records=25)
        with pytest.raises(ValueError, match="sparse"):
            stream_game_data(d, index_maps, sparse_shards=["global"])

    def test_pipeline_rejects_unknown_policy(self, tmp_path):
        d = str(tmp_path / "train")
        _write_dataset(d, 50, block_records=25)
        src = AvroStreamSource(d)
        with pytest.raises(ValueError, match="on_error"):
            ChunkPipeline(src, on_error="bogus")

    def test_validate_flags_nonfinite_features(self, tmp_path):
        path = str(tmp_path / "x.avro")
        records = [_example(0, 1.0, [("f0", "", 1.0)]),
                   _example(1, 0.0, [("f0", "", float("nan"))])]
        avro_io.write_container(path, TRAINING_EXAMPLE, records)
        imap = IndexMap.from_features([("f0", "")], add_intercept=True)
        with pytest.raises(ValueError, match="non-finite"):
            stream_game_data(path, {"g": imap}, validate=True)

    def test_cli_streamed_train_respects_policy(self, tmp_path):
        """The whole --stream run honors the malformed-chunk policy end to
        end: the index-map pre-pass streams under the same knob (the eager
        scan would raise before the policy applied), and data validation
        accepts the skip policy's inert weight-0 rows (nonnegative rule).
        skip -> model trained over surviving rows; raise -> loud failure."""
        from photon_ml_tpu.cli import train as train_cli

        d = str(tmp_path / "train")
        _write_dataset(d, 400, block_records=50, seed=4)
        _corrupt_middle_block(d)
        base = ["--train-data", d, "--feature-shards", "global",
                "--id-tags", "userId",
                "--coordinate", "name=fixed,feature.shard=global,reg.weights=1",
                "--coordinate", ("name=user,random.effect.type=userId,"
                                 "feature.shard=global,reg.weights=1"),
                "--coordinate-descent-iterations", "1",
                "--stream", "--stream-batch-rows", "64"]
        out = str(tmp_path / "out_skip")
        rc = train_cli.run(base + ["--output-dir", out,
                                   "--stream-on-error", "skip"])
        assert rc == 0
        assert os.path.exists(os.path.join(out, "training-summary.json"))
        with pytest.raises(ValueError, match="corrupt|sync|torn"):
            train_cli.run(base + ["--output-dir", str(tmp_path / "out_raise"),
                                  "--stream-on-error", "raise"])


# ---------------------------------------------------------------------------
# EntityIndex thread safety (streaming decode workers share the index)
# ---------------------------------------------------------------------------

class TestEntityIndexConcurrency:
    def test_concurrent_get_or_add_no_duplicate_ids(self):
        idx = EntityIndex()
        names = [f"e{i % 97}" for i in range(3000)]
        out = [None] * 8

        def work(t):
            out[t] = [idx.get_or_add(nm) for nm in names]

        threads = [threading.Thread(target=work, args=(t,)) for t in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        # every thread resolved every name to the SAME id, ids dense 0..96
        for t in range(1, 8):
            assert out[t] == out[0]
        assert sorted(idx._fwd.values()) == list(range(97))
        for nm, i in idx._fwd.items():
            assert idx.name_of(i) == nm


# ---------------------------------------------------------------------------
# EntityStats == _group_rows (the streamed random-effect grouping)
# ---------------------------------------------------------------------------

def _assert_groups_equal(got, want):
    rows_g, ents_g, scale_g = got
    rows_w, ents_w, scale_w = want
    assert ents_g == ents_w
    assert scale_g == scale_w  # exact float equality: same operands
    assert len(rows_g) == len(rows_w)
    for a, b in zip(rows_g, rows_w):
        assert np.array_equal(a, b)


class TestEntityStats:
    @pytest.mark.parametrize("cap,min_active,keys", [
        (None, 1, None), (None, 4, None), (5, 1, None), (5, 3, None),
        (5, 4, frozenset({0, 2, 3})),
    ])
    def test_matches_group_rows(self, cap, min_active, keys):
        rng = np.random.default_rng(9)
        ids = rng.integers(-1, 12, size=400).astype(np.int64)
        want = _group_rows(ids, cap, min_active, seed=13,
                           existing_model_keys=keys)
        for acc_cap in (None, cap):  # full AND capped accumulation modes
            st = EntityStats(active_cap=acc_cap, seed=13)
            for base in range(0, 400, 77):  # ragged chunk sizes
                st.update(ids[base:base + 77], base)
            got = st.groups(cap, min_active, seed=13,
                            existing_model_keys=keys)
            assert got is not None
            _assert_groups_equal(got, want)

    def test_capped_accumulator_declines_other_settings(self):
        st = EntityStats(active_cap=5, seed=13)
        st.update(np.zeros(20, np.int64), 0)
        assert st.groups(6, 1, seed=13) is None   # different cap
        assert st.groups(5, 1, seed=14) is None   # different seed
        assert st.groups(5, 1, seed=13) is not None


# ---------------------------------------------------------------------------
# streaming fixed-effect fold (sufficient statistics over the batch stream)
# ---------------------------------------------------------------------------

class TestStreamFold:
    def test_ridge_matches_direct_solve_one_program(self):
        rng = np.random.default_rng(21)
        n, d, B = 1000, 8, 256
        x = rng.normal(size=(n, d)).astype(np.float64)
        y = rng.normal(size=n).astype(np.float64)
        off = rng.normal(size=n).astype(np.float64) * 0.1
        w = rng.uniform(0.5, 2.0, size=n).astype(np.float64)

        fold = streamfold.StreamingFixedEffectFold(d, l2=0.7,
                                                   dtype=np.float64)
        before = streamfold._ACCUM._cache_size()
        for lo in range(0, n, B):
            rows = min(B, n - lo)
            xb = np.zeros((B, d), np.float64)
            xb[:rows] = x[lo:lo + rows]
            fold.accumulate(jax.numpy.asarray(xb), y[lo:lo + rows],
                            off[lo:lo + rows], w[lo:lo + rows], rows)
        # one program for full batches AND the ragged tail (rows is traced)
        assert streamfold._ACCUM._cache_size() - before == 1
        assert (fold.batches, fold.rows) == (4, n)

        g = (x * w[:, None]).T @ x + 0.7 * np.eye(d)
        b = x.T @ (w * (y - off))
        want = np.linalg.solve(g, b)
        np.testing.assert_allclose(np.asarray(fold.solve()), want,
                                   rtol=1e-9, atol=1e-12)

    def test_ingest_folds_in_the_same_pass(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 300, block_records=50, seed=6)
        dim = index_maps["global"].size
        fold = streamfold.StreamingFixedEffectFold(dim, l2=1.0)
        data, _ = stream_game_data(d, index_maps, id_tag_names=["userId"],
                                   batch_rows=64,
                                   folds={"global": fold})
        assert fold.rows == data.num_samples
        x = np.asarray(data.features["global"], np.float64)
        w = np.asarray(data.weight, np.float64)
        g = (x * w[:, None]).T @ x
        np.testing.assert_allclose(np.asarray(fold.gram(), np.float64), g,
                                   rtol=1e-4, atol=1e-3)


# ---------------------------------------------------------------------------
# photonscope wiring (spans, gauges, probe site accounting)
# ---------------------------------------------------------------------------

class TestStreamObservability:
    def test_spans_gauges_and_probe_site(self, tmp_path):
        d = str(tmp_path / "train")
        index_maps = _write_dataset(d, 200, block_records=50, seed=8)

        prev_reg = get_registry()
        reg = MetricsRegistry()
        set_registry(reg)
        prev_tr = _trace.set_tracer(_trace.Tracer(capacity=4096,
                                                  enabled=True))
        bytes_before = get_probe().transfer_bytes(direction="h2d",
                                                  site="stream_feed")
        try:
            stream_game_data(d, index_maps, id_tag_names=["userId"],
                             batch_rows=64)
            names = {r["name"] for r in _trace.get_tracer().records()}
        finally:
            _trace.set_tracer(prev_tr)
            set_registry(prev_reg)

        assert {"stream.decode", "stream.upload"} <= names
        assert reg.counter("stream_chunks_total") == 4
        assert reg.counter("stream_chunk_errors_total") == 0
        assert reg.gauge("stream_buffer_depth") == 0  # reset on drain
        assert reg.gauge("stream_stall_seconds") >= 0.0
        moved = get_probe().transfer_bytes(
            direction="h2d", site="stream_feed") - bytes_before
        assert moved > 0  # every upload routed through utils/transfer
