"""Per-entity solve-path tests: fused validated sweeps, pallas kernels
(interpret mode), and native sparse/compact serving.

Three contracts from the raw-speed pass:
  - ``FusedSweep.run_validated`` reproduces the host-paced
    ``CoordinateDescent`` + validation suite exactly: same best-model
    selection, tolerance-equal metrics, same per-update held-out losses.
  - The pallas kernels (ops/soa_newton, ops/compact_score) are the same
    math as their XLA references — verified in interpret mode on CPU,
    including padded/weightless lanes and line-search-rejection lanes.
  - A ``CompactRandomEffectModel`` serves end-to-end (resolve -> AOT
    execute -> delta -> rebalance -> swap) without any ``.to_dense()``:
    scores BITWISE-equal to the compact batch path (the engine contract),
    tolerance-equal to ``.to_dense()`` dense serving (different summation
    order: k observed columns vs the d-wide einsum).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core.losses import logistic_loss, poisson_loss
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.evaluation.evaluator import EvaluationSuite
from photon_ml_tpu.game.config import FixedEffectConfig, RandomEffectConfig
from photon_ml_tpu.game.coordinate import build_coordinate
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.descent import CoordinateDescent
from photon_ml_tpu.game.fused import FusedSweep
from photon_ml_tpu.models.game import (CompactRandomEffectModel,
                                       FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.opt.newton_soa import (_cholesky_solve_soa, _hess,
                                          _value_grad, solve_newton_soa)
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.ops import compact_score, soa_newton
from photon_ml_tpu.serving.batcher import (BucketedBatcher, Request,
                                           densify_features)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     CompactRandomCoordinate,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.types import TaskType

TASK = TaskType.LOGISTIC_REGRESSION


# ---------------------------------------------------------------------------
# fused validated sweeps
# ---------------------------------------------------------------------------

def _glmix(rng, n_users=16, per_user=40, d_global=5, d_user=3):
    n = n_users * per_user
    xg = rng.normal(size=(n, d_global))
    xu = rng.normal(size=(n, d_user))
    uid = np.repeat(np.arange(n_users) * 2 + 7, per_user)
    wg = rng.normal(size=d_global) * 0.8
    wu = rng.normal(size=(n_users, d_user))
    logits = xg @ wg + np.einsum(
        "nd,nd->n", xu, wu[np.repeat(np.arange(n_users), per_user)])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(float)
    return GameData(y=y, features={"global": xg, "per_user": xu},
                    id_tags={"userId": uid})


def _coords(data, num_l2=1.0):
    solver = SolverConfig(max_iters=80, tolerance=1e-8)
    cfgs = {
        "fixed": FixedEffectConfig(feature_shard="global", solver=solver,
                                   reg=Regularization(l2=num_l2)),
        "per-user": RandomEffectConfig(random_effect_type="userId",
                                       feature_shard="per_user",
                                       solver=solver,
                                       reg=Regularization(l2=num_l2)),
    }
    return {cid: build_coordinate(cid, data, c, TASK)
            for cid, c in cfgs.items()}


class TestFusedValidated:
    def test_matches_host_descent(self, rng):
        """Best-model selection + metrics parity vs the host loop with a
        validation suite, over multiple outer iterations."""
        data = _glmix(rng)
        val = _glmix(rng, per_user=15)
        coords = _coords(data)
        suite = EvaluationSuite.from_specs(["auc", "logistic_loss"])

        host_model, hist, host_ev = CoordinateDescent(
            coords, num_iterations=3, validation=(val, suite)).run()
        sweep = FusedSweep(coords, num_iterations=3)
        plan = sweep.validation_plan(val, suite)
        fmodel, evals, best_ev, losses = sweep.run_validated(plan)

        assert len(evals) == 3
        for k, v in host_ev.values.items():
            np.testing.assert_allclose(best_ev.values[k], v, rtol=1e-6)
        np.testing.assert_allclose(fmodel["fixed"].coefficients.means,
                                   host_model["fixed"].coefficients.means,
                                   rtol=1e-6, atol=1e-9)
        np.testing.assert_allclose(fmodel["per-user"].w_stack,
                                   host_model["per-user"].w_stack,
                                   rtol=1e-6, atol=1e-9)

    def test_per_update_losses_match_host_validation(self, rng):
        """The in-program per-(iteration, coordinate) held-out losses equal
        the host loop's per-update logistic_loss evaluations (the host
        metric is the weighted SUM; the program emits the weighted MEAN)."""
        data = _glmix(rng, n_users=10)
        val = _glmix(rng, n_users=10, per_user=12)
        coords = _coords(data)
        suite = EvaluationSuite.from_specs(["logistic_loss"])
        _, hist, _ = CoordinateDescent(
            coords, num_iterations=2, validation=(val, suite)).run()
        sweep = FusedSweep(coords, num_iterations=2)
        _, _, _, losses = sweep.run_validated(
            sweep.validation_plan(val, suite))
        assert losses.shape == (2, 2)
        host = np.asarray([s["validation"].values["logistic_loss"]
                           for s in hist.steps]).reshape(2, 2)
        wt_sum = float(np.sum(val.weight))
        np.testing.assert_allclose(losses * wt_sum, host, rtol=1e-5)

    def test_warm_start_with_carried_entities(self, rng):
        """A warm-start model with entities the training data never sees:
        the carried rows must ride the held-out base (constant) and the
        best model must merge them — host-loop parity end to end."""
        data = _glmix(rng, n_users=8)
        val = _glmix(rng, n_users=8, per_user=10)
        coords = _coords(data)
        d_user = 3
        # initial model: every trained entity + one carried stranger (id 999
        # appears in NEITHER data nor val — plus id 9 which is in val only
        # via... keep it simple: 999 carried, contributes where it appears)
        re0 = coords["per-user"]
        slot_of = dict(re0._slot_of)
        w0 = rng.normal(size=(len(slot_of), d_user)) * 0.1
        slot_of[999] = len(slot_of)
        w0 = np.vstack([w0, rng.normal(size=(1, d_user))])
        init = GameModel(models={
            "fixed": FixedEffectModel(
                coefficients=Coefficients(
                    means=rng.normal(size=5) * 0.1),
                feature_shard="global", task=TASK),
            "per-user": RandomEffectModel(
                w_stack=w0.astype(np.float32), slot_of=slot_of,
                random_effect_type="userId", feature_shard="per_user",
                task=TASK),
        })
        suite = EvaluationSuite.from_specs(["auc", "logistic_loss"])
        host_model, _, host_ev = CoordinateDescent(
            coords, num_iterations=2, validation=(val, suite)).run(
                initial=init)
        sweep = FusedSweep(coords, num_iterations=2)
        fmodel, _, best_ev, _ = sweep.run_validated(
            sweep.validation_plan(val, suite), initial=init)
        for k, v in host_ev.values.items():
            np.testing.assert_allclose(best_ev.values[k], v, rtol=1e-5)
        # the carried stranger survives into the published model
        assert 999 in fmodel["per-user"].slot_of
        np.testing.assert_allclose(
            fmodel["per-user"].w_stack[fmodel["per-user"].slot_of[999]],
            w0[-1], rtol=1e-6)

    def test_estimator_routes_validated_fused(self, rng):
        """GameEstimator.fit with a validation suite runs the validated
        program (empty per-update history) and returns host-equal metrics."""
        from photon_ml_tpu.game.config import GameConfig
        from photon_ml_tpu.game.estimator import GameEstimator

        data = _glmix(rng, n_users=8)
        suite = EvaluationSuite.from_specs(["auc"])
        solver = SolverConfig(max_iters=60, tolerance=1e-8)
        cfg = GameConfig(task=TASK, coordinates={
            "fixed": FixedEffectConfig(feature_shard="global", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": RandomEffectConfig(
                random_effect_type="userId", feature_shard="per_user",
                solver=solver, reg=Regularization(l2=1.0)),
        }, num_outer_iterations=2)
        r_fused = GameEstimator(validation_suite=suite, fused=True).fit(
            data, [cfg], validation_data=data)[0]
        r_host = GameEstimator(validation_suite=suite, fused=False).fit(
            data, [cfg], validation_data=data)[0]
        assert r_fused.history.steps == []      # one program, no host steps
        assert len(r_host.history.steps) == 4   # 2 coords x 2 iterations
        np.testing.assert_allclose(r_fused.evaluation.primary,
                                   r_host.evaluation.primary, rtol=1e-6)

    def test_variances_fall_back_to_host(self, rng):
        """run_validated refuses variance-computing sweeps; the estimator
        falls back to the host loop (which still attaches variances)."""
        import dataclasses

        from photon_ml_tpu.game.config import GameConfig
        from photon_ml_tpu.game.estimator import GameEstimator
        from photon_ml_tpu.types import VarianceComputationType

        data = _glmix(rng, n_users=6)
        coords = _coords(data)
        coords["fixed"] = coords["fixed"].rebind(dataclasses.replace(
            coords["fixed"].config,
            variance=VarianceComputationType.SIMPLE))
        sweep = FusedSweep(coords, num_iterations=1)
        suite = EvaluationSuite.from_specs(["auc"])
        with pytest.raises(NotImplementedError):
            sweep.run_validated(sweep.validation_plan(data, suite))
        solver = SolverConfig(max_iters=40, tolerance=1e-7)
        cfg = GameConfig(task=TASK, coordinates={
            "fixed": FixedEffectConfig(
                feature_shard="global", solver=solver,
                reg=Regularization(l2=1.0),
                variance=VarianceComputationType.SIMPLE),
            "per-user": RandomEffectConfig(
                random_effect_type="userId", feature_shard="per_user",
                solver=solver, reg=Regularization(l2=1.0)),
        }, num_outer_iterations=1)
        r = GameEstimator(validation_suite=suite).fit(
            data, [cfg], validation_data=data)[0]
        assert r.evaluation is not None
        assert r.model["fixed"].coefficients.variances is not None
        assert len(r.history.steps) == 2  # host loop ran


# ---------------------------------------------------------------------------
# pallas kernels (interpret mode) vs XLA references
# ---------------------------------------------------------------------------

def _soa_problem(rng, loss, d=5, cap=12, lanes=256, dtype=np.float64):
    w = jnp.asarray(rng.normal(size=(d, lanes)) * 0.1, dtype)
    x = jnp.asarray(rng.normal(size=(cap, d, lanes)), dtype)
    if loss is poisson_loss:
        y = jnp.asarray(rng.poisson(2.0, size=(cap, lanes)).astype(dtype))
    else:
        y = jnp.asarray((rng.random((cap, lanes)) < 0.5).astype(dtype))
    off = jnp.asarray(rng.normal(size=(cap, lanes)) * 0.1, dtype)
    wt = jnp.asarray(rng.uniform(0.5, 2.0, size=(cap, lanes)), dtype)
    # weightless / padded lanes: whole lanes with zero weight (H = l2 I)
    wt = wt.at[:, :37].set(0.0)
    # padded SAMPLE slots inside real lanes
    wt = wt.at[cap - 2:, 40:90].set(0.0)
    l2 = jnp.asarray(rng.uniform(0.1, 2.0, size=lanes), dtype)
    return w, x, y, off, wt, l2


class TestSoaNewtonKernel:
    @pytest.mark.parametrize("loss", [logistic_loss, poisson_loss],
                             ids=lambda l: l.name)
    def test_newton_step_parity(self, rng, loss):
        """Kernel step == _hess + _cholesky_solve_soa chain (incl. the
        jitter rule), with weightless lanes and padded sample slots."""
        w, x, y, off, wt, l2 = _soa_problem(rng, loss)
        d = w.shape[0]
        _, g = _value_grad(loss, w, x, y, off, wt, l2)
        hh = _hess(loss, w, x, y, off, wt, l2)
        eps = jnp.asarray(np.finfo(np.float64).eps)
        jit_vec = eps * (jnp.abs(jnp.stack(
            [hh[i][i] for i in range(d)])).max(0) + 1.0)
        ref = _cholesky_solve_soa(hh, g, jit_vec)
        got = soa_newton.newton_step(loss, w, g, x, y, off, wt, l2,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-11, atol=1e-12)

    def test_full_solver_parity_including_rejection_lanes(self, rng):
        """solve_newton_soa with the kernel (interpret knob) == pure XLA,
        end to end — convergence reasons, iterates and line-search
        REJECTION lanes included (max_linesearch=1 + an aggressive
        objective makes some lanes reject and stall)."""
        w, x, y, off, wt, l2 = _soa_problem(rng, logistic_loss, lanes=128)
        # blow up some lanes' curvature scale so a full Newton step
        # overshoots and the single backtracking trial rejects
        off = off.at[:, 100:].add(25.0)
        cfg = SolverConfig(max_iters=8, tolerance=1e-10, max_linesearch=1)
        ref = solve_newton_soa(logistic_loss, w, x, y, off, wt, l2, cfg)
        from photon_ml_tpu.types import ConvergenceReason

        assert (np.asarray(ref.reason)
                == int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)).any(), \
            "fixture no longer produces line-search-rejection lanes"
        os.environ["PHOTON_SOA_PALLAS_INTERPRET"] = "1"
        try:
            assert soa_newton.eligible(w.shape[0], w.shape[1])
            got = solve_newton_soa(logistic_loss, w, x, y, off, wt, l2, cfg)
        finally:
            del os.environ["PHOTON_SOA_PALLAS_INTERPRET"]
        np.testing.assert_array_equal(np.asarray(got.reason),
                                      np.asarray(ref.reason))
        np.testing.assert_array_equal(np.asarray(got.iterations),
                                      np.asarray(ref.iterations))
        np.testing.assert_allclose(np.asarray(got.w), np.asarray(ref.w),
                                   rtol=1e-9, atol=1e-11)

    def test_gating(self):
        assert not soa_newton.eligible(4, 100)  # not lane-aligned
        os.environ["PHOTON_SOA_DISABLE_PALLAS"] = "1"
        try:
            assert not soa_newton.eligible(4, 256, interpret=True)
        finally:
            del os.environ["PHOTON_SOA_DISABLE_PALLAS"]
        with pytest.raises(ValueError, match="eligible"):
            soa_newton.newton_step(
                logistic_loss, jnp.zeros((4, 100)), jnp.zeros((4, 100)),
                jnp.zeros((3, 4, 100)), jnp.zeros((3, 100)),
                jnp.zeros((3, 100)), jnp.ones((3, 100)), jnp.ones(100))


class TestCompactScoreKernel:
    def _compact_model_arrays(self, rng, E=40, k_m=6, dim=60, dtype=np.float64):
        w_idx = np.full((E, k_m), dim, np.int32)
        w_val = np.zeros((E, k_m), dtype)
        for e in range(E):
            nn = int(rng.integers(1, k_m + 1))
            cols = np.sort(rng.choice(dim, size=nn, replace=False))
            w_idx[e, :nn] = cols
            w_val[e, :nn] = rng.normal(size=nn)
        return w_idx, w_val

    def test_match_dot_parity(self, rng):
        """Kernel == the searchsorted/take_along_axis chain: missing
        entities, dim-padded model rows, zero-valued padded feature slots
        and DUPLICATE feature ids (which accumulate) all covered."""
        from photon_ml_tpu.models.game import _score_sparse_compact

        dim, k_f, n = 60, 9, 300
        w_idx, w_val = self._compact_model_arrays(rng, dim=dim)
        slots = rng.integers(-1, 40, size=n).astype(np.int32)
        f_idx = rng.integers(0, dim, size=(n, k_f))
        f_idx[:, 3] = f_idx[:, 2]  # duplicates accumulate
        f_val = rng.normal(size=(n, k_f))
        f_val[:, -2:] = 0.0        # padded COO slots carry value 0
        ref = _score_sparse_compact(
            jnp.asarray(w_idx), jnp.asarray(w_val), jnp.asarray(slots),
            jnp.asarray(np.asarray(f_idx, np.int32)), jnp.asarray(f_val))
        got = compact_score.score_sparse_compact(
            jnp.asarray(w_idx), jnp.asarray(w_val), jnp.asarray(slots),
            jnp.asarray(np.asarray(f_idx, np.int32)), jnp.asarray(f_val),
            interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)

    def test_gating(self):
        assert not compact_score.eligible(128, 128)  # match work too big
        os.environ["PHOTON_COMPACT_DISABLE_PALLAS"] = "1"
        try:
            assert not compact_score.eligible(4, 4, interpret=True)
        finally:
            del os.environ["PHOTON_COMPACT_DISABLE_PALLAS"]


# ---------------------------------------------------------------------------
# native sparse/compact serving
# ---------------------------------------------------------------------------

def _compact_fixture(rng, d=24, E=60, density=0.2):
    names = [f"f{j}" for j in range(d)]
    imap = IndexMap({feature_key(n): j for j, n in enumerate(names)})
    eidx = EntityIndex()
    for i in range(E):
        eidx.get_or_add(f"user{i}")
    w = rng.normal(size=(E, d)) * (rng.random((E, d)) < density)
    dense_re = RandomEffectModel(
        w_stack=w.astype(np.float32), slot_of={i: i for i in range(E)},
        random_effect_type="userId", feature_shard="all", task=TASK)
    fixed = FixedEffectModel(
        coefficients=Coefficients(means=rng.normal(size=d).astype(np.float32)),
        feature_shard="all", task=TASK)
    return names, imap, eidx, fixed, dense_re, dense_re.to_compact()


def _requests(rng, names, E, n):
    out = []
    for i in range(n):
        feats = [{"name": nm, "term": "", "value": float(v)}
                 for nm, v in zip(names, rng.normal(size=len(names)))]
        u = int(rng.integers(0, E + 5))  # some unknown entities
        out.append(Request(uid=i, features=feats, ids={"userId": f"user{u}"}))
    return out


def _engine_for(model, eidx, imap, cap=None, metrics=None):
    store = CoefficientStore.from_model(
        model, TASK, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=cap), metrics=metrics)
    engine = ScoringEngine(store, BucketedBatcher(16), metrics=metrics)
    n = engine.warm()
    return store, engine, n


class TestCompactServing:
    def test_serving_parity_and_lifecycle(self, rng):
        """resolve -> AOT execute -> delta -> rebalance, dense vs compact on
        the same request stream: compact serving is BITWISE the compact
        batch score (the engine<->batch contract) and tolerance-equal to
        .to_dense() dense serving (k-column vs d-wide summation order)."""
        names, imap, eidx, fixed, dense_re, compact_re = _compact_fixture(rng)
        E, d = dense_re.w_stack.shape
        dense_m = GameModel(models={"fixed": fixed, "per_user": dense_re})
        compact_m = GameModel(models={"fixed": fixed, "per_user": compact_re})
        st_d, eng_d, _ = _engine_for(dense_m, eidx, imap)
        # capacity 20/60: hot, cold (LRU) and unknown paths all exercised
        st_c, eng_c, n_warm = _engine_for(compact_m, eidx, imap, cap=20)
        assert isinstance(st_c.coordinates["per_user"],
                          CompactRandomCoordinate)

        reqs = _requests(rng, names, E, 50)
        reqs[0].ids["userId"] = "user3"  # the delta target must be scored
        s_dense = eng_d.score_requests(reqs)
        s_compact = eng_c.score_requests(reqs)
        np.testing.assert_allclose(s_compact, s_dense, rtol=2e-5, atol=1e-6)

        # bitwise vs the compact BATCH path on the same densified features,
        # on a bucket-aligned stream (one chunk, engine shapes == batch
        # shapes; at other chunkings the FIXED effect's [b, d] @ [d] matvec
        # rounds shape-sensitively on XLA CPU — a pre-existing property of
        # the engine<->batch contract, not of the compact path)
        bs = reqs[:16]
        xs = densify_features(bs, {"all": imap}, len(bs))
        ids = np.asarray([eidx.get(r.ids["userId"]) for r in bs], np.int64)
        gd = GameData(y=np.zeros(len(bs)), features={"all": xs["all"]},
                      id_tags={"userId": ids})
        np.testing.assert_array_equal(
            eng_c.score_requests(bs),
            np.asarray(compact_m.score(gd), s_compact.dtype))

        # per-coordinate: the engine's compact margins (resolve + the shared
        # gather kernel, hot + cold tiers) are BITWISE the compact batch
        # score at ANY chunk shape
        from photon_ml_tpu.models.game import score_compact_dense

        allx = densify_features(reqs, {"all": imap}, len(reqs))["all"]
        allids = np.asarray([eidx.get(r.ids["userId"]) for r in reqs],
                            np.int64)
        hs, sl, (ov_i, ov_v) = st_c.resolve(
            "per_user", [r.ids.get("userId") for r in reqs])
        got = np.asarray(
            score_compact_dense(hs.indices, hs.values, jnp.asarray(sl),
                                jnp.asarray(allx))
            + score_compact_dense(jnp.asarray(ov_i), jnp.asarray(ov_v),
                                  jnp.arange(len(reqs), dtype=jnp.int32),
                                  jnp.asarray(allx)))
        gd_all = GameData(y=np.zeros(len(reqs)), features={"all": allx},
                          id_tags={"userId": allids})
        np.testing.assert_array_equal(
            got, np.asarray(compact_re.score(gd_all), got.dtype))

        # streaming delta: dense row on the wire, compacted in the store;
        # both stores patched -> still equal, and the score actually moved
        row = (rng.normal(size=d) * (rng.random(d) < 0.2)).astype(np.float32)
        row[0] = 1.5  # guarantee a visible, capacity-respecting change
        assert st_c.apply_delta("per_user", "user3", row)
        assert st_d.apply_delta("per_user", "user3", row)
        s_d2, s_c2 = eng_d.score_requests(reqs), eng_c.score_requests(reqs)
        np.testing.assert_allclose(s_c2, s_d2, rtol=2e-5, atol=1e-6)
        assert not np.array_equal(s_dense, s_d2)

        # frequency rebalance: residency moves, scores don't
        st_c.rebalance()
        np.testing.assert_array_equal(eng_c.score_requests(reqs), s_c2)

        # zero recompiles through the whole lifecycle
        assert eng_c.compile_count == n_warm

        # an over-capacity delta is refused loudly (k would have to grow)
        with pytest.raises(ValueError, match="capacity"):
            st_c.apply_delta("per_user", "user3", np.ones(d, np.float32))

    def test_compact_swap_end_to_end(self, rng, tmp_path):
        """Hot swap a compact model directory in: load -> warm -> flip,
        (generation, delta_version) identity reset — no .to_dense()."""
        from photon_ml_tpu.serving.swap import HotSwapper
        from photon_ml_tpu.storage.model_io import save_game_model

        names, imap, eidx, fixed, dense_re, compact_re = _compact_fixture(rng)
        E, d = dense_re.w_stack.shape

        def _save(m, sub):
            out = str(tmp_path / sub)
            save_game_model(m, out, {"all": imap}, {"userId": eidx}, TASK,
                            fmt="columnar")
            imap.save(os.path.join(out, "all.idx"))
            eidx.save(os.path.join(out, "userId.entities.json"))
            return out

        m1 = GameModel(models={"fixed": fixed, "per_user": compact_re})
        w2 = rng.normal(size=(E, d)) * (rng.random((E, d)) < 0.2)
        re2 = RandomEffectModel(
            w_stack=w2.astype(np.float32), slot_of=dict(dense_re.slot_of),
            random_effect_type="userId", feature_shard="all",
            task=TASK).to_compact(k=compact_re.indices.shape[1])
        m2 = GameModel(models={"fixed": fixed, "per_user": re2})
        dir2 = _save(m2, "gen2")

        metrics = ServingMetrics()
        st1, engine, n_warm = _engine_for(m1, eidx, imap, cap=20,
                                          metrics=metrics)
        swapper = HotSwapper(engine)
        reqs = _requests(rng, names, E, 30)
        s1 = engine.score_requests(reqs)
        row = (rng.normal(size=d) * (rng.random(d) < 0.1)).astype(np.float32)
        assert swapper.apply_delta("per_user", "user1", row)
        assert swapper.delta_version == 1

        assert swapper.swap(dir2) is True
        assert swapper.delta_version == 0  # fresh generation
        assert isinstance(engine.store.coordinates["per_user"],
                          CompactRandomCoordinate)
        s2 = engine.score_requests(reqs)
        assert not np.array_equal(s1, s2)
        # the new generation serves EXACTLY what a fresh engine built from
        # the in-memory m2 serves (disk roundtrip + swap changed nothing)
        _, eng_ref, _ = _engine_for(m2, eidx, imap, cap=20)
        np.testing.assert_array_equal(s2, eng_ref.score_requests(reqs))
        # ... and the batch scores to float tolerance
        xs = densify_features(reqs, {"all": imap}, len(reqs))
        ids = np.asarray([eidx.get(r.ids["userId"]) for r in reqs], np.int64)
        gd = GameData(y=np.zeros(len(reqs)), features={"all": xs["all"]},
                      id_tags={"userId": ids})
        np.testing.assert_allclose(s2, np.asarray(m2.score(gd), s2.dtype),
                                   rtol=2e-5, atol=1e-6)
        # same-shape swap reused the warm executables: zero new compiles
        assert engine.compile_count == n_warm

    def test_compact_compile_accounting_parity(self, rng):
        """Every compact AOT executable is counted by the runtime probe
        under the serving.engine site (jax_compiles_total parity)."""
        from photon_ml_tpu import obs
        from photon_ml_tpu.obs.registry import MetricsRegistry

        names, imap, eidx, fixed, dense_re, compact_re = _compact_fixture(rng)
        reg = MetricsRegistry()
        prev = obs.set_registry(reg)
        try:
            m = GameModel(models={"fixed": fixed, "per_user": compact_re})
            _, engine, n_warm = _engine_for(m, eidx, imap)
            total = sum(reg.counter_series("jax_compiles_total").values())
            assert n_warm > 0 and total == engine.compile_count == n_warm
        finally:
            obs.set_registry(prev)

    def test_device_copy_cache(self, rng):
        """Model score() uploads the coefficient arrays once per instance;
        dataclasses.replace (the mutation idiom) invalidates naturally."""
        import dataclasses

        names, imap, eidx, fixed, dense_re, compact_re = _compact_fixture(rng)
        n, d = 20, dense_re.w_stack.shape[1]
        gd = GameData(y=np.zeros(n),
                      features={"all": rng.normal(size=(n, d))},
                      id_tags={"userId": rng.integers(0, 10, size=n)})
        s1 = np.asarray(compact_re.score(gd))
        cache1 = compact_re._dev_cache
        s2 = np.asarray(compact_re.score(gd))
        assert compact_re._dev_cache is cache1  # reused, not rebuilt
        np.testing.assert_array_equal(s1, s2)
        patched = dataclasses.replace(
            compact_re, values=compact_re.values * 2.0)
        assert getattr(patched, "_dev_cache", None) is None
        s3 = np.asarray(patched.score(gd))
        assert not np.array_equal(s1, s3)
        # dense twin caches too
        dense_re.score(gd)
        c = dense_re._dev_cache
        dense_re.score(gd)
        assert dense_re._dev_cache is c


# ---------------------------------------------------------------------------
# obs wiring: solve-latency histogram family bounds
# ---------------------------------------------------------------------------

class TestSolveObs:
    def test_solve_bucket_histogram_uses_family_bounds(self, rng):
        from photon_ml_tpu import obs
        from photon_ml_tpu.obs.registry import (MetricsRegistry,
                                                family_bounds)

        reg = MetricsRegistry()
        prev = obs.set_registry(reg)
        try:
            data = _glmix(rng, n_users=6, per_user=20)
            coords = _coords(data)
            coords["per-user"].update(np.zeros(data.num_samples))
            series = reg.histogram_series("solve_bucket_seconds")
            assert series, "no solve_bucket_seconds histogram recorded"
            # the registered family ladder (100µs..~7min), not the default
            assert family_bounds("solve_bucket_seconds")[0] == 1e-4
            snap = reg.snapshot()["histograms"]
            assert any(k.startswith("solve_bucket_seconds") for k in snap)
        finally:
            obs.set_registry(prev)

    def test_family_bounds_applied_to_new_series(self):
        from photon_ml_tpu.obs.registry import (MetricsRegistry,
                                                set_family_bounds)

        set_family_bounds("solve_path_test_seconds", [0.1, 1.0, 10.0])
        reg = MetricsRegistry()
        reg.observe("solve_path_test_seconds", 0.5)
        h = reg._histograms[("solve_path_test_seconds", ())]
        assert h.bounds == (0.1, 1.0, 10.0)
        assert h.counts == [0, 1, 0, 0]
        # prometheus exposition uses the per-family ladder
        text = reg.to_prometheus()
        assert 'solve_path_test_seconds_bucket{le="0.1"} 0' in text
        assert 'solve_path_test_seconds_bucket{le="1.0"} 1' in text
