"""Native Avro loader: columnar decode parity vs the Python record path."""

import time

import numpy as np
import pytest

import photon_ml_tpu.data.native_avro as na
from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.index_map import IndexMap, build_index_maps_from_avro, feature_key
from photon_ml_tpu.data.reader import EntityIndex, read_game_data_avro
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE


def _fixture(path, n=400, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    records = []
    for i in range(n):
        feats = [{"name": f"f{j}", "term": f"t{j % 3}",
                  "value": float(rng.normal())} for j in rng.integers(0, 6, size=3)]
        feats.append({"name": "dup", "term": "", "value": 1.0})
        feats.append({"name": "dup", "term": "", "value": 0.5})  # accumulation
        rec = {"uid": i, "response": float(rng.random() < 0.5),
               "label": None,
               "offset": None if (with_nulls and i % 3 == 0) else float(rng.normal() * 0.1),
               "weight": None if (with_nulls and i % 4 == 0) else float(rng.uniform(0.5, 2)),
               "features": feats,
               "metadataMap": {"userId": f"u{i % 7}", "other": f"x{i % 2}"}}
        records.append(rec)
    avro_io.write_container(path, TRAINING_EXAMPLE, records)
    return records


@pytest.fixture(scope="module")
def avro_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("avro") / "train.avro")
    return path, _fixture(path)


def test_native_lib_compiles():
    assert na.native_available(), "g++ compile of avro_loader.cpp failed"


def test_columnar_matches_python_codec(avro_path):
    path, records = avro_path
    c = na.load_columnar(path)
    assert c is not None and c.n == len(records)
    for i in (0, 3, 4, 57):
        rec = records[i]
        assert c.numeric["response"][i] == rec["response"]
        assert bool(c.numeric_valid["offset"][i]) == (rec["offset"] is not None)
        if rec["offset"] is not None:
            assert c.numeric["offset"][i] == pytest.approx(rec["offset"])
        assert c.uids[i] == rec["uid"]
        assert c.feat_counts[i] == len(rec["features"])
    # interning: every (name, term) appears exactly once in the table
    assert len(set(c.feat_table)) == len(c.feat_table)
    assert "dup\x1f" in c.feat_table


def test_game_data_parity_fast_vs_fallback(avro_path, monkeypatch):
    """read_game_data_avro must produce IDENTICAL GameData either way."""
    path, _ = avro_path
    imap = build_index_maps_from_avro([path], {"all": []})["all"]
    fast, eidx_fast = read_game_data_avro([path], {"all": imap},
                                          id_tag_names=["userId"])
    assert na.native_available()

    monkeypatch.setattr(na, "_lib", None)
    monkeypatch.setattr(na, "_lib_tried", True)
    slow, eidx_slow = read_game_data_avro([path], {"all": imap},
                                          id_tag_names=["userId"])

    np.testing.assert_array_equal(fast.y, slow.y)
    np.testing.assert_allclose(fast.offset, slow.offset, rtol=1e-6)
    np.testing.assert_allclose(fast.weight, slow.weight, rtol=1e-6)
    np.testing.assert_allclose(fast.features["all"], slow.features["all"], rtol=1e-6)
    # entity ids may be assigned in different order; compare via names
    names_fast = [eidx_fast["userId"].name_of(i) for i in fast.id_tags["userId"]]
    names_slow = [eidx_slow["userId"].name_of(i) for i in slow.id_tags["userId"]]
    assert names_fast == names_slow
    assert list(fast.uids) == list(slow.uids)


def test_multiple_files_concatenate(tmp_path):
    p1, p2 = str(tmp_path / "a.avro"), str(tmp_path / "b.avro")
    r1, r2 = _fixture(p1, n=50, seed=1), _fixture(p2, n=30, seed=2)
    imap = build_index_maps_from_avro([p1, p2], {"all": []})["all"]
    data, _ = read_game_data_avro([p1, p2], {"all": imap}, id_tag_names=["userId"])
    assert data.num_samples == 80
    assert data.y[0] == r1[0]["response"] and data.y[50] == r2[0]["response"]


def test_ineligible_schema_falls_back(tmp_path):
    """A non-TrainingExample schema decodes via the Python codec path."""
    weird = {"type": "record", "name": "W", "fields": [
        {"name": "a", "type": {"type": "array", "items": {
            "type": "array", "items": "long"}}}]}
    path = str(tmp_path / "weird.avro")
    avro_io.write_container(path, weird, [{"a": [[1, 2], [3]]}])
    # generic walk handles nested arrays; eligibility only rejects recursion —
    # columnar decode succeeds but captures nothing
    c = na.load_columnar(path)
    assert c is None or c.feat_counts.sum() == 0


def test_recursive_schema_rejected(tmp_path):
    rec = {"type": "record", "name": "Node", "fields": [
        {"name": "next", "type": ["null", "Node"]}]}
    path = str(tmp_path / "rec.avro")
    avro_io.write_container(path, rec, [{"next": None}])
    assert na.load_columnar(path) is None  # falls back, no crash


def test_string_uids_not_in_feature_table(tmp_path):
    """String uids intern into their OWN table — natively-built index maps
    must not grow a column per distinct uid."""
    schema = {"type": "record", "name": "T", "fields": [
        {"name": "uid", "type": ["null", "string", "long"]},
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "F", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string"},
                {"name": "value", "type": "double"}]}}}]}
    path = str(tmp_path / "s.avro")
    avro_io.write_container(path, schema, [
        {"uid": "user-abc", "response": 1.0,
         "features": [{"name": "f", "term": "t", "value": 2.0}]},
        {"uid": 42, "response": 0.0, "features": []},
    ])
    c = na.load_columnar(path)
    assert c is not None
    assert list(c.uids) == ["user-abc", 42]
    assert c.feat_table == [feature_key("f", "t")]  # no uid pollution
    keys = build_index_maps_from_avro([path], {"all": []})["all"]
    assert keys.get_index("user-abc") == -1


def test_long_typed_feature_values(tmp_path):
    """int/long feature values must capture (not silently decode to 0)."""
    schema = {"type": "record", "name": "T", "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "F", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string"},
                {"name": "value", "type": "long"}]}}}]}
    path = str(tmp_path / "l.avro")
    avro_io.write_container(path, schema, [
        {"response": 1.0, "features": [{"name": "a", "term": "", "value": 7}]}])
    c = na.load_columnar(path)
    assert c is not None
    np.testing.assert_array_equal(c.feat_values, [7.0])


def test_columnar_cache_single_decode(tmp_path, monkeypatch):
    """Index building + GameData assembly share one decode per file."""
    import photon_ml_tpu.data.native_avro as mod

    path = str(tmp_path / "c.avro")
    _fixture(path, n=20, seed=4)
    mod.clear_columnar_cache()
    opens = []
    lib = mod._native_lib()
    real_open = lib.avl_open
    monkeypatch.setattr(lib, "avl_open",
                        lambda *a: (opens.append(1), real_open(*a))[1])
    imap = build_index_maps_from_avro([path], {"all": []})["all"]
    read_game_data_avro([path], {"all": imap})
    assert len(opens) == 1
    mod.clear_columnar_cache()


def test_native_speedup_smoke(tmp_path):
    """The native decode should beat the Python codec comfortably."""
    path = str(tmp_path / "big.avro")
    _fixture(path, n=4000, seed=3)
    imap = build_index_maps_from_avro([path], {"all": []})["all"]

    t0 = time.perf_counter()
    read_game_data_avro([path], {"all": imap}, id_tag_names=["userId"])
    t_fast = time.perf_counter() - t0

    import photon_ml_tpu.data.native_avro as mod
    orig, tried = mod._lib, mod._lib_tried
    try:
        mod._lib, mod._lib_tried = None, True
        t0 = time.perf_counter()
        read_game_data_avro([path], {"all": imap}, id_tag_names=["userId"])
        t_slow = time.perf_counter() - t0
    finally:
        mod._lib, mod._lib_tried = orig, tried
    assert t_fast < t_slow, (t_fast, t_slow)


# --- native BayesianLinearModelAvro codec (native/model_codec.cpp) ---

def test_model_codec_cross_parity(tmp_path):
    """The native fixed-effect model codec must interoperate with the
    generic python codec in BOTH directions (same wire format), skip zero
    means (sparse NTV storage), and carry variances + union fields."""
    import numpy as np

    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.schemas import BAYESIAN_LINEAR_MODEL
    from photon_ml_tpu.storage import native_model_codec as nmc
    from photon_ml_tpu.storage.model_io import (_coeff_to_record,
                                                _read_fixed_avro_fast)

    if not nmc.available():
        import pytest
        pytest.skip("native codec unavailable (no g++)")

    imap = IndexMap({feature_key(f"f{j}", "t" if j % 3 else ""): j
                     for j in range(50)})
    rng = np.random.default_rng(0)
    means = rng.normal(size=50)
    means[[3, 7]] = 0.0
    var = rng.random(50)
    blob, off = imap.key_blob()
    body = nmc.encode_record("mid", "cls.Name", "logistic_regression",
                             blob, off, means, var)
    fast = str(tmp_path / "fast.avro")
    avro_io.write_container_raw(fast, BAYESIAN_LINEAR_MODEL, [body])

    # generic python decoder reads the native-encoded file
    rec = next(iter(avro_io.read_container(fast)))
    assert rec["modelId"] == "mid" and rec["modelClass"] == "cls.Name"
    assert rec["lossFunction"] == "logistic_regression"
    assert len(rec["means"]) == 48  # zeros skipped
    back = np.zeros(50)
    for ntv in rec["means"]:
        back[imap.get_index(ntv["name"], ntv["term"])] = ntv["value"]
    np.testing.assert_allclose(back, means)

    # native decoder reads the generic-python-encoded file
    gen = str(tmp_path / "gen.avro")
    avro_io.write_container(
        gen, BAYESIAN_LINEAR_MODEL,
        [_coeff_to_record("mid", means, var, imap, "logistic_regression")])
    c = _read_fixed_avro_fast(gen, imap)
    assert c is not None and c.variances is not None
    np.testing.assert_allclose(c.means, means)
    nz = means != 0
    np.testing.assert_allclose(c.variances[nz], var[nz])

    # native round trip, no variances / null unions
    body2 = nmc.encode_record("m2", None, None, blob, off, means, None)
    f2 = str(tmp_path / "f2.avro")
    avro_io.write_container_raw(f2, BAYESIAN_LINEAR_MODEL, [body2])
    rec2 = next(iter(avro_io.read_container(f2)))
    assert rec2["modelClass"] is None and rec2["variances"] is None
    c2 = _read_fixed_avro_fast(f2, imap)
    assert c2.variances is None
    np.testing.assert_allclose(c2.means, means)


def test_model_codec_through_model_io(tmp_path):
    """save_game_model/load_game_model take the native path transparently
    (fixed-effect coordinate) with identical results to the generic path."""
    import numpy as np

    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.storage import native_model_codec as nmc
    from photon_ml_tpu.storage.model_io import load_game_model, save_game_model
    from photon_ml_tpu.types import TaskType

    imap = IndexMap({feature_key(f"f{j}", ""): j for j in range(40)})
    means = np.random.default_rng(1).normal(size=40)
    model = GameModel(models={"g": FixedEffectModel(
        coefficients=Coefficients(means=means), feature_shard="s",
        task=TaskType.LINEAR_REGRESSION)})
    d = str(tmp_path / "m")
    save_game_model(model, d, {"s": imap}, task=TaskType.LINEAR_REGRESSION)
    back, _ = load_game_model(d, {"s": imap})
    np.testing.assert_allclose(back["g"].coefficients.means, means)
    if nmc.available():
        # and the generic reader agrees with what the native writer wrote
        import photon_ml_tpu.storage.native_model_codec as mod
        saved = mod._lib
        mod._lib = None
        try:
            back2, _ = load_game_model(d, {"s": imap})
        finally:
            mod._lib = saved
        np.testing.assert_allclose(back2["g"].coefficients.means, means)


def test_model_codec_all_zero_means(tmp_path):
    """Regression: an all-zero coefficient vector encodes an EMPTY means
    array (single terminator, not count=0 twice) — every following field
    must survive."""
    import numpy as np

    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.schemas import BAYESIAN_LINEAR_MODEL
    from photon_ml_tpu.storage import native_model_codec as nmc

    if not nmc.available():
        import pytest
        pytest.skip("native codec unavailable")
    imap = IndexMap({feature_key(f"f{j}", ""): j for j in range(5)})
    blob, off = imap.key_blob()
    zeros = np.zeros(5)
    body = nmc.encode_record("mid", "cls", "poisson_regression",
                             blob, off, zeros, np.ones(5))
    p = str(tmp_path / "z.avro")
    avro_io.write_container_raw(p, BAYESIAN_LINEAR_MODEL, [body])
    rec = next(iter(avro_io.read_container(p)))
    assert rec["means"] == []
    assert rec["variances"] == []  # variances of zero means are skipped too
    assert rec["modelClass"] == "cls"
    assert rec["lossFunction"] == "poisson_regression"

    # and the native loader agrees with the generic one on empty variances
    from photon_ml_tpu.storage.model_io import (_read_fixed_avro_fast,
                                                _record_to_coeff)
    c_native = _read_fixed_avro_fast(p, imap)
    c_generic = _record_to_coeff(rec, imap)
    assert c_native.variances is None and c_generic.variances is None
    np.testing.assert_array_equal(c_native.means, np.zeros(5))


def test_model_codec_re_cross_parity(tmp_path):
    """Random-effect multi-record files: native-written reads identically
    through the GENERIC codec and vice versa (wire-format interop), incl.
    per-entity variances, multi-block files, and malformed-input safety."""
    import numpy as np

    import photon_ml_tpu.storage.native_model_codec as nmc
    from photon_ml_tpu.data.index_map import IndexMap, feature_key
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.models.game import GameModel, RandomEffectModel
    from photon_ml_tpu.storage.model_io import load_game_model, save_game_model
    from photon_ml_tpu.types import TaskType

    if not nmc.available():
        import pytest
        pytest.skip("native codec unavailable")
    E, d = 9000, 5  # > one 4096-record block
    imap = IndexMap({feature_key(f"f{j}", ""): j for j in range(d)})
    eidx = EntityIndex()
    slot_of = {eidx.get_or_add(f"u{i}"): i for i in range(E)}
    rng = np.random.default_rng(3)
    w = rng.normal(size=(E, d))
    w[5, :] = 0.0  # one all-zero entity (empty means array)
    var = rng.random((E, d))
    m = RandomEffectModel(w_stack=w, slot_of=slot_of, random_effect_type="t",
                          feature_shard="s", task=TaskType.LOGISTIC_REGRESSION,
                          variances=var)
    g = GameModel(models={"u": m})

    def roundtrip(save_native, load_native, out):
        saved = nmc._lib
        try:
            nmc._lib = saved if save_native else None
            save_game_model(g, out, {"s": imap}, {"t": eidx},
                            TaskType.LOGISTIC_REGRESSION)
            nmc._lib = saved if load_native else None
            back, _ = load_game_model(out, {"s": imap}, {"t": EntityIndex()})
        finally:
            nmc._lib = saved
        return back["u"]

    combos = {(sn, ln): roundtrip(sn, ln, str(tmp_path / f"m{sn}{ln}"))
              for sn in (True, False) for ln in (True, False)}
    ref = combos[(False, False)]
    for key, got in combos.items():
        assert len(got.slot_of) == E, key
        for i in (0, 5, 4096, E - 1):
            rs = ref.w_stack[ref.slot_of[i]] if i in ref.slot_of else None
            gs = got.w_stack[got.slot_of[i]]
            np.testing.assert_allclose(gs, ref.w_stack[ref.slot_of[i]],
                                       rtol=1e-12, err_msg=str(key))
        nz = ref.w_stack != 0
        np.testing.assert_allclose(got.variances[nz], ref.variances[nz],
                                   rtol=1e-12, err_msg=str(key))

    # malformed RECORD BODIES degrade to None (no SIGSEGV from huge varint
    # lengths — the bounds checks are overflow-safe); corrupt CONTAINER
    # framing raises from the shared framing code, same as the generic path
    assert nmc.decode_record(b"\xfe\xff\xff\xff\xff\xff\xff\xff\xff\x01" * 3) is None
    assert nmc.decode_block(b"\xfe\xff\xff\xff\xff\xff\xff\xff\xff\x01" * 3, 5) is None
