"""Pod-slice serving tests: mesh-sharded coefficient store + shard-local
scoring (photon_ml_tpu/serving/* with StoreConfig.mesh_shards > 0).

The contract under test, in order of importance:
  1. a 1-shard mesh serves BITWISE the unsharded scores (the layout
     collapse that makes sharding a pure deployment knob);
  2. an N-shard mesh matches the unsharded scores to fp tolerance under
     realistic (zipf) traffic — the psum reorders one addition, nothing
     else, and on these sizes the reduction is exact;
  3. rebalance, streaming deltas and hot swap all preserve that parity AND
     the zero-recompiles-after-warm invariant (no table shape or layout
     ever changes within a generation);
  4. residency is shard-local: rows never cross the shard boundary, and
     aggregate hot capacity scales linearly with the shard count under a
     fixed per-shard budget — the capacity story pod-slice serving exists
     for.

Runs on the 8 virtual CPU devices conftest forces; every mesh here is a
host-local device mesh (the sharding/collective machinery is identical on
a real pod slice, minus ICI).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (CompactRandomEffectModel,
                                       FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.parallel.mesh import SHARD_AXIS, serving_mesh
from photon_ml_tpu.serving.batcher import BucketedBatcher, Request
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     ShardSpec, StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.types import TaskType

N_ENTITIES = 100
DIM = 6
FEATURES = [f"f{j}" for j in range(DIM)]


def _index_map():
    return IndexMap({feature_key(n): j for j, n in enumerate(FEATURES)})


def _entity_index():
    eidx = EntityIndex()
    for i in range(N_ENTITIES):
        eidx.get_or_add(f"user{i}")
    return eidx


def _model(seed=7, compact=False):
    """Synthetic fixed + per_user GLMix model; ``compact`` swaps the dense
    random effect for the sparse-row container."""
    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    models = {"fixed": FixedEffectModel(
        coefficients=Coefficients(means=rng.normal(size=DIM)),
        feature_shard="all", task=task)}
    if compact:
        k = 3
        idx = np.sort(rng.integers(0, DIM, size=(N_ENTITIES, k)
                                   ).astype(np.int32), axis=1)
        models["per_user"] = CompactRandomEffectModel(
            indices=idx, values=rng.normal(size=(N_ENTITIES, k)) * 0.1,
            dim=DIM, slot_of={i: i for i in range(N_ENTITIES)},
            random_effect_type="userId", feature_shard="all", task=task)
    else:
        models["per_user"] = RandomEffectModel(
            w_stack=rng.normal(size=(N_ENTITIES, DIM)) * 0.1,
            slot_of={i: i for i in range(N_ENTITIES)},
            random_effect_type="userId", feature_shard="all", task=task)
    return GameModel(models=models), task


def _engine(mesh_shards, device_capacity, seed=7, compact=False,
            max_batch=16, load_aware=True, replicate_top_k=0):
    model, task = _model(seed=seed, compact=compact)
    metrics = ServingMetrics()
    store = CoefficientStore.from_model(
        model, task, {"userId": _entity_index()}, {"all": _index_map()},
        config=StoreConfig(device_capacity=device_capacity,
                           mesh_shards=mesh_shards,
                           load_aware_routing=load_aware,
                           replicate_top_k=replicate_top_k),
        version=f"mesh{mesh_shards}", metrics=metrics)
    return ScoringEngine(store, BucketedBatcher(max_batch),
                         metrics=metrics), metrics


def _requests(k, seed, zipf=0.0, unknown_frac=0.1):
    """Random requests; ``zipf`` skews entity draws (rank ~ archive slot)."""
    rng = np.random.default_rng(seed)
    if zipf:
        w = (np.arange(N_ENTITIES) + 1.0) ** -zipf
        ids = rng.choice(N_ENTITIES, size=k, p=w / w.sum())
    else:
        ids = rng.integers(0, N_ENTITIES, size=k)
    unknown = rng.random(k) < unknown_frac
    reqs = []
    for i in range(k):
        u = N_ENTITIES + i if unknown[i] else int(ids[i])
        feats = [{"name": n, "term": "", "value": float(v)}
                 for n, v in zip(FEATURES, rng.normal(size=DIM))]
        reqs.append(Request(uid=i, features=feats,
                            ids={"userId": f"user{u}"}))
    return reqs


# ---------------------------------------------------------------------------
# mesh + spec plumbing
# ---------------------------------------------------------------------------
class TestServingMesh:
    def test_serving_mesh_axis_and_sizes(self, devices):
        m = serving_mesh(4)
        assert m.axis_names == (SHARD_AXIS,)
        assert m.shape[SHARD_AXIS] == 4
        assert serving_mesh(1).shape[SHARD_AXIS] == 1

    def test_serving_mesh_validation(self, devices):
        with pytest.raises(ValueError, match="n_shards >= 1"):
            serving_mesh(0)
        with pytest.raises(ValueError, match="devices"):
            serving_mesh(len(devices) + 1)

    def test_shard_spec_routing(self, devices):
        spec = ShardSpec(mesh=serving_mesh(4), n_shards=4, cap=5)
        slots = np.arange(20)
        np.testing.assert_array_equal(spec.shard_of_archive_slot(slots),
                                      slots % 4)
        assert spec.sharding.mesh.axis_names == (SHARD_AXIS,)

    def test_store_carries_mesh_and_signature(self, devices):
        eng, _ = _engine(4, 8)
        eng0, _ = _engine(0, 32)
        assert eng.store.mesh is not None and eng0.store.mesh is None
        # mesh shape is part of the executable cache key: a sharded and an
        # unsharded store must never share an AOT executable
        assert eng.store.signature() != eng0.store.signature()

    def test_per_shard_capacity_scales_aggregate(self, devices):
        """Fixed per-shard budget => aggregate hot rows scale with the
        mesh — the pod-slice capacity story."""
        cap = 8
        for n in (1, 2, 4, 8):
            eng, _ = _engine(n, cap)
            c = eng.store.coordinates["per_user"]
            assert c.hot_capacity == cap * n
            assert c.table.shape[0] == cap * n
            assert len(c.hot_slot_of) == min(cap * n, N_ENTITIES)

    def test_capacity_clamped_to_shard_population(self, devices):
        """cap beyond ceil(E/N) would only pin dead rows — it is clamped."""
        eng, _ = _engine(4, 1000)
        c = eng.store.coordinates["per_user"]
        assert c.shard_spec.cap == -(-N_ENTITIES // 4)
        assert len(c.hot_slot_of) == N_ENTITIES  # everything fits hot


# ---------------------------------------------------------------------------
# score parity
# ---------------------------------------------------------------------------
class TestShardedScoringParity:
    def test_one_shard_bitwise(self, devices):
        """A 1-shard mesh is a deployment no-op: scores are bitwise the
        unsharded engine's, across every bucket the plan touches."""
        eng0, _ = _engine(0, 30)
        eng1, _ = _engine(1, 30)
        for k in (1, 5, 16, 50):
            reqs = _requests(k, seed=100 + k)
            np.testing.assert_array_equal(eng1.score_requests(reqs),
                                          eng0.score_requests(reqs))

    @pytest.mark.parametrize("shards", [2, 4, 8])
    def test_nshard_parity_zipf(self, devices, shards):
        """N shards under zipf traffic: the only arithmetic difference is
        the psum's add order, exact at these magnitudes."""
        eng0, _ = _engine(0, 32)
        engN, _ = _engine(shards, -(-32 // shards))
        reqs = _requests(64, seed=3, zipf=1.1)
        np.testing.assert_allclose(engN.score_requests(reqs),
                                   eng0.score_requests(reqs),
                                   rtol=1e-12, atol=1e-12)

    def test_compact_coordinate_parity(self, devices):
        eng0, _ = _engine(0, 30, compact=True)
        eng1, _ = _engine(1, 30, compact=True)
        eng4, _ = _engine(4, 8, compact=True)
        reqs = _requests(48, seed=5, zipf=1.1)
        base = eng0.score_requests(reqs)
        np.testing.assert_array_equal(eng1.score_requests(reqs), base)
        np.testing.assert_allclose(eng4.score_requests(reqs), base,
                                   rtol=1e-12, atol=1e-12)

    def test_all_hot_vs_cold_split_parity(self, devices):
        """Parity must hold whatever the hot/cold split: all-hot,
        tiny-hot (cold overflow dominant), and zero-hot."""
        reqs = _requests(40, seed=9, zipf=1.0)
        base = _engine(0, None)[0].score_requests(reqs)
        for cap in (None, 2, 0):
            engN, _ = _engine(4, cap)
            np.testing.assert_allclose(engN.score_requests(reqs), base,
                                       rtol=1e-12, atol=1e-12)


# ---------------------------------------------------------------------------
# mutation parity: rebalance / deltas / hot swap
# ---------------------------------------------------------------------------
class TestShardedMutation:
    def test_rebalance_parity_and_shard_locality(self, devices):
        eng0, _ = _engine(0, 32)
        eng8, _ = _engine(8, 4)
        # skewed traffic toward the archive tail (initially cold), then one
        # promotion pass everywhere
        hot_tail = _requests(200, seed=21, zipf=1.3)
        for eng in (eng0, eng8):
            eng.score_requests(hot_tail)
            eng.store.rebalance()
        c = eng8.store.coordinates["per_user"]
        spec = c.shard_spec
        owned = []
        for eid, row in c.hot_slot_of.items():
            rows = c.hot_replicas.get(eid, (row,))
            owned.extend(rows)
            # the primary row sits on the shard the LIVE routing table
            # homes the entity on whenever a row exists there (a
            # rerouted incumbent may retain its old row until a
            # promotion overwrites it — placement hysteresis)
            if any(r // spec.cap == c.route_of(eid) for r in rows):
                assert row // spec.cap == c.route_of(eid)
        assert len(owned) == len(set(owned))  # no row double-ownership
        reqs = _requests(64, seed=22, zipf=1.3)
        np.testing.assert_allclose(eng8.score_requests(reqs),
                                   eng0.score_requests(reqs),
                                   rtol=1e-12, atol=1e-12)

    def test_delta_parity_hot_and_cold(self, devices):
        eng0, _ = _engine(0, 20)
        eng4, _ = _engine(4, 5)
        rng = np.random.default_rng(31)
        hot_row, cold_row = rng.normal(size=DIM), rng.normal(size=DIM)
        for eng in (eng0, eng4):
            assert eng.store.apply_delta("per_user", "user3", hot_row)
            assert eng.store.apply_delta("per_user", "user90", cold_row)
            assert not eng.store.apply_delta("per_user", "nobody", hot_row)
        reqs = _requests(64, seed=32)
        np.testing.assert_allclose(eng4.score_requests(reqs),
                                   eng0.score_requests(reqs),
                                   rtol=1e-12, atol=1e-12)

    def test_hot_swap_preserves_sharding_and_parity(self, devices, tmp_path):
        from photon_ml_tpu.storage.model_io import save_game_model

        imap, eidx = _index_map(), _entity_index()
        for seed, name in ((7, "v1"), (8, "v2")):
            model, task = _model(seed=seed)
            out = tmp_path / name
            save_game_model(model, str(out), {"all": imap},
                            entity_indexes={"userId": eidx}, task=task)
            imap.save(str(out / "all.idx"))
            eidx.save(str(out / "userId.entities.json"))
        metrics = ServingMetrics()
        from photon_ml_tpu.storage.model_io import load_model_bundle
        store = CoefficientStore.from_bundle(
            load_model_bundle(str(tmp_path / "v1")),
            config=StoreConfig(device_capacity=8, mesh_shards=4),
            metrics=metrics)
        engine = ScoringEngine(store, BucketedBatcher(16), metrics=metrics)
        engine.warm()
        swapper = HotSwapper(engine)
        assert swapper.swap(str(tmp_path / "v2"), version="v2")
        new = engine.store
        # the config (and with it the mesh layout) rides the swap
        assert new.config.mesh_shards == 4 and new.mesh is not None
        assert new.coordinates["per_user"].shard_spec is not None
        reqs = _requests(48, seed=41)
        eng0, _ = _engine(0, 32, seed=8)
        np.testing.assert_allclose(engine.score_requests(reqs),
                                   eng0.score_requests(reqs),
                                   rtol=1e-12, atol=1e-12)

    def test_zero_recompiles_after_warm(self, devices):
        """The invariant the whole design protects: traffic, rebalance and
        deltas never change a shape or layout, so nothing recompiles."""
        eng, _ = _engine(4, 8)
        eng.warm()
        warmed = eng.compile_count
        eng.score_requests(_requests(64, seed=51, zipf=1.2))
        eng.store.rebalance()
        eng.store.apply_delta(
            "per_user", "user1", np.random.default_rng(52).normal(size=DIM))
        eng.score_requests(_requests(32, seed=53, zipf=1.2))
        assert eng.compile_count == warmed


# ---------------------------------------------------------------------------
# traffic-aware placement: routing table, hot-row replication, determinism
# ---------------------------------------------------------------------------
def _head_requests(users, k, seed):
    """Requests cycling over ``users`` — a traffic head with no tail."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(k):
        feats = [{"name": n, "term": "", "value": float(v)}
                 for n, v in zip(FEATURES, rng.normal(size=DIM))]
        reqs.append(Request(uid=i, features=feats,
                            ids={"userId": f"user{users[i % len(users)]}"}))
    return reqs


class TestTrafficAwarePlacement:
    def test_replica_delta_coherence(self, devices):
        """A zipf-head entity replicated across 3+ shards stays BITWISE
        coherent through a streaming delta: apply_delta fans the row out
        to every replica under one (generation, delta_version)."""
        eng, _ = _engine(4, 8, replicate_top_k=2)
        store = eng.store
        c = store.coordinates["per_user"]
        # user0 dominates traffic 4:1 -> an unambiguous replication head
        eng.score_requests(_head_requests([0, 0, 0, 0, 1], 100, seed=91))
        store.rebalance()
        eid = store.entity_id("userId", "user0")
        rows = c.hot_replicas.get(eid, ())
        assert len(rows) >= 3, f"head not replicated: rows={rows}"
        new_row = np.random.default_rng(92).normal(size=DIM)
        assert store.apply_delta("per_user", "user0", new_row)
        tbl = np.asarray(c.table)
        for r in c.hot_replicas[eid]:
            np.testing.assert_array_equal(tbl[r], tbl[rows[0]])
        np.testing.assert_array_equal(
            tbl[rows[0]], np.asarray(new_row, dtype=tbl.dtype))
        # and the replicated state still scores like the unsharded store
        eng0, _ = _engine(0, 32)
        assert eng0.store.apply_delta("per_user", "user0", new_row)
        reqs = _requests(48, seed=93, zipf=1.2)
        np.testing.assert_allclose(eng.score_requests(reqs),
                                   eng0.score_requests(reqs),
                                   rtol=1e-12, atol=1e-12)

    def test_load_aware_rebalance_determinism(self, devices):
        """Identical traffic -> identical placement: slot_of, replicas and
        the routing table are pure functions of the observed trace."""
        placements = []
        for _ in range(2):
            eng, _ = _engine(4, 8, replicate_top_k=3)
            for batch in range(3):
                eng.score_requests(_requests(64, seed=300 + batch,
                                             zipf=1.2))
                eng.store.rebalance()
            c = eng.store.coordinates["per_user"]
            placements.append((
                dict(c.hot_slot_of), dict(c.hot_replicas),
                tuple(c.shard_of_slots(np.arange(N_ENTITIES)).tolist())))
        assert placements[0] == placements[1]

    def test_uniform_traffic_keeps_round_robin_route(self, devices):
        """Exactly balanced traffic gives the greedy bin-pack no reason
        to move anything: the route stays the slot % N it started as
        (and with it, the pre-PR placement exactly).  Sampled 'uniform'
        streams carry sampling noise, so the invariant is stated on
        equal EWMA counters — what perfectly balanced load looks like
        to the ranker."""
        eng, _ = _engine(4, 8)
        c = eng.store.coordinates["per_user"]
        c.record_hits({eid: 3 for eid in range(N_ENTITIES)})
        eng.store.rebalance()
        slots = np.arange(N_ENTITIES)
        np.testing.assert_array_equal(c.shard_of_slots(slots), slots % 4)
        assert c.hot_replicas == {}

    @pytest.mark.parametrize("shards,cap,zipf", [(1, 30, 1.2), (4, 8, 0.0)])
    def test_router_parity_one_shard_and_uniform(self, devices, shards,
                                                 cap, zipf):
        """The bitwise anchors: at 1 shard, and at N shards under uniform
        traffic, the traffic-aware router scores EXACTLY what the
        pre-placement router scores — resolution hands the kernels
        global rows, so placement policy can never touch a score."""
        drive = _requests(128, seed=400, zipf=zipf)
        probe = _requests(48, seed=401, zipf=zipf)
        scores = []
        for la, tk in ((False, 0), (True, 3)):
            eng, _ = _engine(shards, cap, load_aware=la,
                             replicate_top_k=tk)
            eng.score_requests(drive)
            eng.store.rebalance()
            scores.append(eng.score_requests(probe))
        np.testing.assert_array_equal(scores[0], scores[1])

    def test_zero_recompiles_with_replication(self, devices):
        """Promotion, demotion, replica fan-out and routing moves all ride
        snapshot swaps of FIXED-shape tables — nothing recompiles."""
        eng, _ = _engine(4, 8, replicate_top_k=3)
        eng.warm()
        warmed = eng.compile_count
        eng.score_requests(_requests(64, seed=601, zipf=1.3))
        eng.store.rebalance()
        eng.store.apply_delta(
            "per_user", "user0",
            np.random.default_rng(602).normal(size=DIM))
        eng.score_requests(_requests(32, seed=603, zipf=1.3))
        eng.store.rebalance()
        assert eng.compile_count == warmed


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
class TestShardMetrics:
    def test_occupancy_and_traffic_gauges(self, devices):
        eng, metrics = _engine(4, 8)
        eng.score_requests(_requests(64, seed=61, zipf=1.0))
        view = metrics.shard_view()["per_user"]
        assert sorted(view) == [0, 1, 2, 3]
        # initial residency fills every shard (cap 8 x 4 < 100 entities)
        assert all(cell["occupancy"] == 1.0 for cell in view.values())
        total_hot = sum(cell["hot_hits"] for cell in view.values())
        total_lookups = sum(cell["lookups"] for cell in view.values())
        assert total_hot == metrics.counter("hot_hits")
        assert 0 < total_hot <= total_lookups
        for cell in view.values():
            assert 0.0 <= cell["hit_rate"] <= 1.0

    def test_snapshot_wire_format_unchanged(self, devices):
        """Per-shard families must NOT leak into the snapshot()'s
        byte-compatible ``counters`` view."""
        eng, metrics = _engine(4, 8)
        eng.score_requests(_requests(32, seed=71))
        eng.store.rebalance()
        snap = metrics.snapshot()
        assert not any(k.startswith("serving_shard") for k in
                       snap["counters"])
        # but they DO ride the Prometheus exposition for scrapers
        prom = metrics.to_prometheus()
        assert "serving_shard_occupancy" in prom
        assert "serving_shard_lookups_total" in prom


# ---------------------------------------------------------------------------
# cli
# ---------------------------------------------------------------------------
class TestServeCliMesh:
    def test_mesh_shards_flag_parses(self):
        from photon_ml_tpu.cli.serve import build_parser

        args = build_parser().parse_args(
            ["--model-dir", "m", "--mesh-shards", "4"])
        assert args.mesh_shards == 4
        assert build_parser().parse_args(
            ["--model-dir", "m"]).mesh_shards == 0

    def test_build_server_mesh(self, devices, tmp_path):
        from photon_ml_tpu.cli.serve import build_server
        from photon_ml_tpu.storage.model_io import save_game_model

        model, task = _model(seed=7)
        imap, eidx = _index_map(), _entity_index()
        out = tmp_path / "m"
        save_game_model(model, str(out), {"all": imap},
                        entity_indexes={"userId": eidx}, task=task)
        imap.save(str(out / "all.idx"))
        eidx.save(str(out / "userId.entities.json"))
        engine, swapper = build_server(
            str(tmp_path / "m"), max_batch=8, device_entity_capacity=8,
            mesh_shards=2, warm=True)
        assert engine.store.config.mesh_shards == 2
        assert engine.store.mesh is not None
        reqs = _requests(12, seed=81)
        eng0, _ = _engine(0, 16, seed=7, max_batch=8)
        np.testing.assert_allclose(engine.score_requests(reqs),
                                   eng0.score_requests(reqs),
                                   rtol=1e-12, atol=1e-12)
