"""photonfront tests: the network serving edge (ISSUE 8 / ROADMAP item 2).

The contracts under test:
  - Wire framing: bounded line reads — an oversized or malformed line gets
    one {"error": ...} reply, the connection survives, and the stream
    realigns byte-exactly on the next line.
  - Admission: deadline-budget shedding with two-watermark hysteresis —
    deterministic for injected estimates (unit), engaged under an injected
    slow engine (integration), always recovering once the backlog drains.
  - Fairness: round-robin draining across per-client queues — strict
    alternation at the unit level; a firehose cannot starve a trickle
    client at the socket level.
  - Drain-on-swap: every admitted in-flight request resolves to a SCORE
    (zero dropped, zero errored) across a hot swap under concurrent load —
    the acceptance gate.
  - Parity: concurrently multiplexed clients get the same scores the sync
    engine produces for the same requests.
  - Scrape endpoint: GET /metrics serves the Prometheus exposition.
  - CLI: `serve --listen` end-to-end over a real localhost socket.
"""

import asyncio
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.serving.batcher import BucketedBatcher, Request
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.frontend import (AdmissionConfig,
                                            AdmissionController,
                                            BoundedLineReader, FairQueue,
                                            FrontendConfig, LineTooLong,
                                            ThreadedFrontend,
                                            ThreadedMetricsEndpoint,
                                            iter_bounded_lines)
from photon_ml_tpu.serving.frontend.admission import (SHED_DRAINING,
                                                      SHED_OVERLOAD)
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.types import TaskType

N_ENT = 40
D = 4
NAMES = [f"f{j}" for j in range(D)]


def _model(seed=0):
    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    return GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    }), task


def _index_parts():
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    return imap, eidx


def _engine(max_batch=8, seed=0):
    model, task = _model(seed)
    imap, eidx = _index_parts()
    metrics = ServingMetrics()
    store = CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=None), version="synthetic",
        metrics=metrics)
    eng = ScoringEngine(store, BucketedBatcher(max_batch), metrics=metrics)
    eng.warm()
    return eng


def _slow(engine, delay_s):
    """Instance-attr wrap: every score_requests call sleeps first.  Works
    because engine.async_batcher late-binds self.score_requests."""
    orig = engine.score_requests

    def slow(requests, predict_mean=False):
        time.sleep(delay_s)
        return orig(requests, predict_mean=predict_mean)

    engine.score_requests = slow
    return engine


def _wire_req(rng, uid, user=None):
    user = user if user is not None else int(rng.integers(0, N_ENT))
    return {"uid": uid,
            "features": [[n, float(v)]
                         for n, v in zip(NAMES, rng.normal(size=D))],
            "ids": {"userId": f"user{user}"}}


def _as_request(obj):
    from photon_ml_tpu.serving.batcher import request_from_json
    return request_from_json(obj)


class Client:
    """Blocking socket client speaking the JSON-lines wire protocol."""

    def __init__(self, port, timeout=60):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=timeout)
        self.f = self.sock.makefile("rw", encoding="utf-8", newline="\n")

    def send(self, obj):
        self.f.write(json.dumps(obj) + "\n")
        self.f.flush()

    def send_raw(self, text):
        self.f.write(text)
        self.f.flush()

    def recv(self):
        line = self.f.readline()
        assert line, "server closed the connection unexpectedly"
        return json.loads(line)

    def close(self):
        try:
            self.f.close()
        finally:
            self.sock.close()


def _front(engine, **over):
    """Start a ThreadedFrontend with test-friendly defaults: a generous
    budget (no accidental shedding) and a fast batcher deadline."""
    kw = dict(admission=AdmissionConfig(budget_s=30.0),
              batcher_deadline_s=0.002)
    kw.update(over)
    return ThreadedFrontend(engine, config=FrontendConfig(**kw)).start()


# ---------------------------------------------------------------------------
# wire framing units
# ---------------------------------------------------------------------------
class TestBoundedLineReader:
    def _reader(self, payload: bytes, limit: int) -> BoundedLineReader:
        buf = bytearray(payload)

        async def read(n):
            chunk = bytes(buf[:n])
            del buf[:n]
            return chunk

        return BoundedLineReader(read, max_line_bytes=limit)

    def _drain(self, reader):
        async def go():
            out = []
            while True:
                try:
                    line = await reader.readline()
                except LineTooLong as e:
                    out.append(e)
                    continue
                if line is None:
                    return out
                out.append(line)

        return asyncio.run(go())

    def test_oversized_line_realigns_stream(self):
        # one oversized line between two good ones: exactly one marker,
        # and the NEXT line parses from its first byte
        payload = b"good1\n" + b"x" * 100 + b"\n" + b"good2\n"
        out = self._drain(self._reader(payload, limit=16))
        assert out[0] == b"good1"
        assert isinstance(out[1], LineTooLong)
        assert out[1].nbytes == 101
        assert out[2] == b"good2"

    def test_trailing_line_without_newline(self):
        out = self._drain(self._reader(b"a\nb", limit=16))
        assert out == [b"a", b"b"]

    def test_oversized_tail_at_eof(self):
        out = self._drain(self._reader(b"ok\n" + b"y" * 50, limit=16))
        assert out[0] == b"ok"
        assert isinstance(out[1], LineTooLong)

    def test_exact_limit_passes(self):
        line = b"z" * 16
        out = self._drain(self._reader(line + b"\n", limit=16))
        assert out == [line]


class TestIterBoundedLines:
    def test_markers_and_realignment(self):
        import io
        f = io.StringIO("ok\n" + "x" * 100 + "\n" + "after\n")
        out = list(iter_bounded_lines(f, max_line_bytes=16))
        assert out[0] == "ok\n"
        assert isinstance(out[1], LineTooLong)
        assert out[2] == "after\n"

    def test_newline_on_probe_boundary(self):
        import io
        # content exactly at the bound, newline as byte limit+1: legal
        f = io.StringIO("a" * 16 + "\n")
        out = list(iter_bounded_lines(f, max_line_bytes=16))
        assert out == ["a" * 16 + "\n"]


# ---------------------------------------------------------------------------
# admission unit (the shed-determinism contract lives here: exact verdicts
# for injected estimates)
# ---------------------------------------------------------------------------
class TestAdmissionUnit:
    def test_hysteresis_latch_and_unlatch(self):
        ac = AdmissionController(AdmissionConfig(budget_s=0.010,
                                                 resume_fraction=0.5))
        assert ac.decide(0.005).admitted
        v = ac.decide(0.011)  # over budget: latch
        assert not v.admitted and v.reason == SHED_OVERLOAD
        assert ac.shedding
        # between watermarks: STILL shedding (the hysteresis)
        assert not ac.decide(0.008).admitted
        assert not ac.decide(0.0051).admitted
        # at/below the low watermark: unlatch and admit
        assert ac.decide(0.005).admitted
        assert not ac.shedding

    def test_retry_after_floor_is_budget(self):
        ac = AdmissionController(AdmissionConfig(budget_s=0.010,
                                                 resume_fraction=0.5))
        # even a marginal overload advises at least one budget of backoff
        assert ac.retry_after_ms(0.011) >= 10.0
        # deep overload advises the predicted drain time
        assert ac.retry_after_ms(0.100) >= 90.0

    def test_config_validation(self):
        with pytest.raises(ValueError):
            AdmissionConfig(budget_s=0.0)
        with pytest.raises(ValueError):
            AdmissionConfig(resume_fraction=1.0)
        with pytest.raises(ValueError):
            AdmissionConfig(resume_fraction=0.0)

    def test_shedding_gauge_tracks_latch(self):
        from photon_ml_tpu.obs.registry import MetricsRegistry
        reg = MetricsRegistry()
        ac = AdmissionController(AdmissionConfig(budget_s=0.010),
                                 registry=reg)
        ac.decide(0.020)
        assert reg.gauge("front_shedding") == 1
        ac.decide(0.001)
        assert reg.gauge("front_shedding") == 0


# ---------------------------------------------------------------------------
# fairness unit
# ---------------------------------------------------------------------------
class TestFairQueueUnit:
    def test_round_robin_alternation(self):
        q = FairQueue()
        for i in range(3):
            q.enqueue("a", f"a{i}")
        for i in range(2):
            q.enqueue("b", f"b{i}")
        got = [q.next_item() for _ in range(5)]
        # strict alternation while both have work, then the survivor
        assert got == [("a", "a0"), ("b", "b0"), ("a", "a1"),
                       ("b", "b1"), ("a", "a2")]
        assert q.next_item() is None
        assert q.depth() == 0

    def test_reentry_after_empty(self):
        q = FairQueue()
        q.enqueue("a", 1)
        assert q.next_item() == ("a", 1)
        q.enqueue("a", 2)  # re-enters the rotation cleanly
        assert q.next_item() == ("a", 2)

    def test_drop_client_returns_orphans_and_skips_rotation(self):
        q = FairQueue()
        q.enqueue("a", 1)
        q.enqueue("b", 2)
        q.enqueue("a", 3)
        assert q.drop_client("a") == [1, 3]
        assert q.depth() == 1
        assert q.depth_of("a") == 0
        assert q.next_item() == ("b", 2)  # stale "a" rotation entry skipped
        assert q.next_item() is None

    def test_firehose_cannot_starve(self):
        q = FairQueue()
        for i in range(1000):
            q.enqueue("hose", i)
        q.enqueue("drip", "x")
        # the trickle item is at worst SECOND out, not 1001st
        first_two = [q.next_item()[0] for _ in range(2)]
        assert "drip" in first_two


# ---------------------------------------------------------------------------
# socket integration (real engine)
# ---------------------------------------------------------------------------
class TestFrontendScoring:
    def test_multi_client_parity_vs_sync_engine(self):
        eng = _engine(max_batch=8)
        tf = _front(eng)
        results = {}
        errors = []

        def client_worker(cid):
            try:
                rng = np.random.default_rng(100 + cid)
                c = Client(tf.port)
                wires = [_wire_req(rng, uid=f"{cid}-{i}") for i in range(12)]
                for w in wires:
                    c.send(w)
                c.send_raw("\n")  # flush
                got = {}
                for _ in wires:
                    rep = c.recv()
                    assert "score" in rep, rep
                    got[rep["uid"]] = rep["score"]
                c.close()
                results[cid] = (wires, got)
            except Exception as e:  # surface in the main thread
                errors.append(e)

        threads = [threading.Thread(target=client_worker, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        tf.stop()
        assert not errors, errors
        assert len(results) == 3
        for cid, (wires, got) in results.items():
            for w in wires:
                want = float(eng.score_requests([_as_request(w)])[0])
                # bucket shape changes reorder reductions by ~1 ulp
                assert got[w["uid"]] == pytest.approx(want, rel=1e-9,
                                                      abs=1e-12)

    def test_malformed_and_oversized_lines_survive(self):
        eng = _engine(max_batch=8)
        tf = _front(eng, max_line_bytes=512)
        c = Client(tf.port)
        rng = np.random.default_rng(0)

        c.send_raw("this is not json\n")
        assert "error" in c.recv()

        c.send_raw("x" * 2000 + "\n")  # over the 512-byte bound
        rep = c.recv()
        assert "error" in rep and "line too long" in rep["error"]

        c.send_raw("[1, 2, 3]\n")  # valid JSON, wrong shape
        assert "error" in c.recv()

        # the connection is still serving after all three
        c.send(_wire_req(rng, uid=7))
        c.send_raw("\n")
        rep = c.recv()
        assert rep["uid"] == 7 and "score" in rep
        c.close()
        tf.stop()
        reg = eng.metrics.registry
        assert reg.counter("front_protocol_errors_total", kind="json") >= 1
        assert reg.counter("front_protocol_errors_total",
                           kind="oversize") >= 1

    def test_blank_line_flushes_partial_batch(self):
        eng = _engine(max_batch=8)
        # deadline far away: only the blank line can flush 3 < 8 requests
        tf = _front(eng, batcher_deadline_s=60.0)
        c = Client(tf.port)
        rng = np.random.default_rng(1)
        for i in range(3):
            c.send(_wire_req(rng, uid=i))
        c.send_raw("\n")
        uids = sorted(c.recv()["uid"] for _ in range(3))
        assert uids == [0, 1, 2]
        c.close()
        tf.stop()

    def test_metrics_and_prometheus_commands(self):
        eng = _engine(max_batch=8)
        tf = _front(eng)
        c = Client(tf.port)
        rng = np.random.default_rng(2)
        c.send(_wire_req(rng, uid=0))
        c.send_raw("\n")
        assert "score" in c.recv()
        c.send({"cmd": "metrics"})
        snap = c.recv()
        assert "counters" in snap and snap["counters"]["requests"] >= 1
        c.send({"cmd": "metrics", "format": "prometheus"})
        prom = c.recv()["prometheus"]
        assert "front_connections_total" in prom
        assert "front_requests_total" in prom
        c.send({"cmd": "nonsense"})
        assert "unknown cmd" in c.recv()["error"]
        c.close()
        tf.stop()

    def test_shutdown_cmd_drains_and_closes(self):
        eng = _engine(max_batch=8)
        tf = _front(eng)
        c = Client(tf.port)
        rng = np.random.default_rng(3)
        c.send(_wire_req(rng, uid=1))
        c.send_raw("\n")
        assert "score" in c.recv()
        c.send({"cmd": "shutdown"})
        assert c.recv()["shutdown"] == "ok"
        # server is gone: the socket reaches EOF
        assert c.f.readline() == ""
        c.close()
        tf._thread.join(30)
        assert not tf._thread.is_alive()


class TestShedUnderOverload:
    def test_slow_engine_sheds_and_recovers(self):
        eng = _slow(_engine(max_batch=4), delay_s=0.005)
        tf = _front(eng,
                    admission=AdmissionConfig(budget_s=0.030,
                                              resume_fraction=0.5),
                    batcher_deadline_s=0.001,
                    flush_threshold=4)
        c = Client(tf.port)
        rng = np.random.default_rng(4)
        # prime the flush-cost EWMA with one observed (slow) flush
        c.send(_wire_req(rng, uid="prime"))
        c.send_raw("\n")
        assert "score" in c.recv()

        # burst far past what a 30ms budget admits at ~5ms/4-req wave
        n = 120
        for i in range(n):
            c.send(_wire_req(rng, uid=i))
        c.send_raw("\n")
        scored, shed = {}, {}
        for _ in range(n):
            rep = c.recv()
            if "score" in rep:
                scored[rep["uid"]] = rep["score"]
            else:
                assert rep["error"] == "overloaded"
                assert rep["reason"] == SHED_OVERLOAD
                assert rep["retry_after_ms"] >= 30.0  # floored at budget
                shed[rep["uid"]] = rep
        # every request got exactly one reply; the burst forced shedding
        # but admission kept accepting SOME work (no total lockout)
        assert len(scored) + len(shed) == n
        assert shed, "a 120-request burst at ~800 qps capacity must shed"
        assert scored, "admission must not shed the entire burst"
        reg = eng.metrics.registry
        assert reg.counter("requests_shed_total",
                           reason=SHED_OVERLOAD) == len(shed)

        # recovery: once the backlog drains, a fresh request is admitted
        deadline = time.time() + 30
        while time.time() < deadline:
            c.send(_wire_req(rng, uid="post"))
            c.send_raw("\n")
            rep = c.recv()
            if "score" in rep:
                break
            time.sleep(0.05)
        else:
            pytest.fail("admission never recovered after the backlog "
                        "drained")
        c.close()
        tf.stop()


class TestFairness:
    def test_trickle_client_not_starved_by_firehose(self):
        eng = _slow(_engine(max_batch=4), delay_s=0.002)
        # small window keeps the firehose backlog in the fair queue,
        # where round-robin applies; huge budget so nothing sheds
        tf = _front(eng, flush_threshold=4, dispatch_window=8,
                    batcher_deadline_s=0.001)
        hose = Client(tf.port)
        rng = np.random.default_rng(5)
        n_hose = 400  # >= 200ms of engine work at ~2ms per 4-wave
        for i in range(n_hose):
            hose.send(_wire_req(rng, uid=i))
        # a short beat so the server has buffered the firehose ahead of
        # the drip (but nowhere near long enough to drain it)
        time.sleep(0.03)
        drip = Client(tf.port)
        t0 = time.perf_counter()
        for i in range(5):
            drip.send(_wire_req(rng, uid=f"d{i}"))
        drip.send_raw("\n")
        for _ in range(5):
            assert "score" in drip.recv()
        t_drip = time.perf_counter() - t0
        hose.send_raw("\n")
        for _ in range(n_hose):
            assert "score" in hose.recv()
        t_hose = time.perf_counter() - t0
        drip.close()
        hose.close()
        tf.stop()
        # 400 firehose requests at ~2ms per 4-wave is >= 200ms of work;
        # round-robin interleaves the drip within its first few waves
        assert t_drip < t_hose, (t_drip, t_hose)
        assert t_drip < 0.5 * t_hose, (t_drip, t_hose)


class TestDrainOnSwap:
    def _save_model_dir(self, tmp_path, seed):
        from photon_ml_tpu.storage.model_io import save_game_model
        model, task = _model(seed)
        imap, eidx = _index_parts()
        out = str(tmp_path / f"model_seed{seed}")
        save_game_model(model, out, {"all": imap}, {"userId": eidx},
                        task=task)
        imap.save(os.path.join(out, "all.idx"))
        eidx.save(os.path.join(out, "userId.entities.json"))
        return out

    def test_swap_under_load_drops_nothing(self, tmp_path):
        new_dir = self._save_model_dir(tmp_path, seed=1)
        eng = _slow(_engine(max_batch=4, seed=0), delay_s=0.002)
        tf = _front(eng, flush_threshold=4, dispatch_window=8,
                    batcher_deadline_s=0.001)
        gen0 = eng.store.generation

        load = Client(tf.port)
        ctrl = Client(tf.port)
        rng = np.random.default_rng(6)
        n = 120
        replies = {}
        reader_err = []

        def read_load():
            try:
                for _ in range(n):
                    rep = load.recv()
                    replies[rep["uid"]] = rep
            except Exception as e:
                reader_err.append(e)

        rt = threading.Thread(target=read_load)
        rt.start()
        for i in range(n):
            load.send(_wire_req(rng, uid=i))
            if i == n // 2:
                # swap lands mid-burst, with half the load in flight
                ctrl.send({"cmd": "swap", "model_dir": new_dir})
        load.send_raw("\n")
        swap_rep = ctrl.recv()
        rt.join(120)
        assert not reader_err, reader_err
        assert swap_rep["swap"] == "ok", swap_rep
        assert swap_rep["generation"] == gen0 + 1

        # the acceptance gate: every admitted request resolved to a SCORE —
        # zero dropped, zero errored, across the drain/flip
        assert len(replies) == n
        bad = {u: r for u, r in replies.items() if "score" not in r}
        # requests arriving DURING the drain may be shed (that's admission
        # doing its job) — but only with the explicit draining reason, and
        # never silently dropped
        for u, r in bad.items():
            assert r.get("error") == "overloaded", r
            assert r.get("reason") in (SHED_DRAINING,), r
        scored = {u: r for u, r in replies.items() if "score" in r}
        assert scored, "swap drained every single request?"

        # post-swap traffic scores on the NEW generation
        ctrl.send(_wire_req(rng, uid="post"))
        ctrl.send_raw("\n")
        deadline = time.time() + 30
        rep = ctrl.recv()
        while "score" not in rep and time.time() < deadline:
            ctrl.send(_wire_req(rng, uid="post"))
            ctrl.send_raw("\n")
            rep = ctrl.recv()
        assert "score" in rep
        load.close()
        ctrl.close()
        tf.stop()


class TestScrapeEndpoint:
    def test_prometheus_golden(self):
        m = ServingMetrics()
        m.inc("requests", 3)
        m.registry.inc("requests_shed_total", reason="overload")
        m.registry.set_gauge("front_connections", 2)
        ep = ThreadedMetricsEndpoint(m).start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{ep.port}/metrics", timeout=10
            ).read().decode()
            assert "# TYPE requests counter" in body
            assert "requests 3" in body
            assert 'requests_shed_total{reason="overload"} 1' in body
            assert "front_connections 2" in body

            jbody = urllib.request.urlopen(
                f"http://127.0.0.1:{ep.port}/metrics.json", timeout=10
            ).read().decode()
            assert json.loads(jbody)["counters"]["requests"] == 3

            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ep.port}/nope", timeout=10)
            assert ei.value.code == 404
        finally:
            ep.stop()

    def test_openmetrics_exemplars_endpoint(self):
        from photon_ml_tpu.obs.pulse import context as pctx
        from photon_ml_tpu.obs.registry import enable_exemplars

        m = ServingMetrics()
        ctx = pctx.mint()
        enable_exemplars(True)
        try:
            with pctx.bind(ctx):
                m.registry.observe("solve_seconds", 0.004)
        finally:
            enable_exemplars(False)
        ep = ThreadedMetricsEndpoint(m, exemplars=True).start()
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{ep.port}/metrics", timeout=10)
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text; version=1.0.0")
            body = resp.read().decode()
            assert body.endswith("# EOF\n")
            assert f'# {{trace_id="{ctx[0]}"}}' in body
            assert "solve_seconds_bucket" in body
        finally:
            ep.stop()

    def test_scrape_sees_frontend_series(self):
        eng = _engine(max_batch=8)
        tf = _front(eng)
        ep = ThreadedMetricsEndpoint(eng.metrics).start()
        c = Client(tf.port)
        rng = np.random.default_rng(7)
        c.send(_wire_req(rng, uid=0))
        c.send_raw("\n")
        assert "score" in c.recv()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ep.port}/metrics", timeout=10
        ).read().decode()
        assert "front_connections_total 1" in body
        assert "front_requests_total 1" in body
        c.close()
        tf.stop()
        ep.stop()


class TestCliListenE2E:
    def test_listen_serves_and_shuts_down(self, tmp_path):
        from photon_ml_tpu.cli import serve as serve_cli

        model_dir = TestDrainOnSwap()._save_model_dir(tmp_path, seed=0)
        # pre-pick a free ephemeral port (the CLI's port-0 binding is
        # logged, not programmatically reachable from another thread)
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()

        rc = {}

        def serve_thread():
            rc["rc"] = serve_cli.run([
                "--model-dir", model_dir, "--max-batch", "8",
                "--listen", f"127.0.0.1:{port}"])

        st = threading.Thread(target=serve_thread, daemon=True)
        st.start()
        c = None
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                c = Client(port, timeout=60)
                break
            except OSError:
                assert st.is_alive(), f"serve CLI died: rc={rc}"
                time.sleep(0.2)
        assert c is not None, "serve CLI never opened the listen port"
        rng = np.random.default_rng(8)
        for i in range(4):
            c.send(_wire_req(rng, uid=i))
        c.send_raw("\n")
        uids = sorted(c.recv()["uid"] for _ in range(4))
        assert uids == [0, 1, 2, 3]
        c.send({"cmd": "metrics"})
        assert c.recv()["counters"]["requests"] >= 4
        c.send({"cmd": "shutdown"})
        assert c.recv()["shutdown"] == "ok"
        c.close()
        st.join(60)
        assert not st.is_alive()
        assert rc["rc"] == 0


# ---------------------------------------------------------------------------
# per-client admission budgets (ISSUE 9 satellite)
# ---------------------------------------------------------------------------
class TestPerClientAdmission:
    def _ctrl(self, registry=None):
        from photon_ml_tpu.serving.frontend.admission import SHED_CLIENT
        return SHED_CLIENT, AdmissionController(
            AdmissionConfig(budget_s=1.0, client_budget_s=0.1),
            registry=registry)

    def test_client_latch_is_per_client_and_hysteretic(self):
        SHED_CLIENT, ctrl = self._ctrl()
        v = ctrl.decide(0.01, client="a", client_wait_s=0.2)
        assert not v.admitted and v.reason == SHED_CLIENT
        assert ctrl.client_shedding("a") and not ctrl.shedding
        # the burning client's latch touches nobody else
        assert ctrl.decide(0.01, client="b", client_wait_s=0.01).admitted
        # hysteresis: above the resume watermark (0.5 * 0.1) stays shed...
        assert not ctrl.decide(0.01, client="a", client_wait_s=0.06).admitted
        # ...below it the latch opens and the request is admitted
        assert ctrl.decide(0.01, client="a", client_wait_s=0.04).admitted
        assert not ctrl.client_shedding("a")

    def test_retry_advice_floored_at_client_budget(self):
        _, ctrl = self._ctrl()
        v = ctrl.decide(0.0, client="a", client_wait_s=0.5)
        # predicted drain past the resume mark: 0.5 - 0.05 = 0.45s
        assert v.retry_after_ms == pytest.approx(450.0)
        v2 = ctrl.decide(0.0, client="b", client_wait_s=0.101)
        assert v2.retry_after_ms >= 100.0  # never below one client budget

    def test_forget_client_clears_latch_and_gauge(self):
        from photon_ml_tpu.obs.registry import MetricsRegistry
        reg = MetricsRegistry()
        _, ctrl = self._ctrl(registry=reg)
        ctrl.decide(0.0, client="a", client_wait_s=0.2)
        assert reg.gauge("front_client_shedding", client="a") == 1
        ctrl.forget_client("a")
        assert not ctrl.client_shedding("a")
        assert reg.gauge("front_client_shedding", client="a") == 0
        ctrl.forget_client("never-seen")  # no latch: a silent no-op

    def test_off_by_default(self):
        ctrl = AdmissionController(AdmissionConfig(budget_s=1.0))
        # client args are inert without a client budget configured
        assert ctrl.decide(0.01, client="a", client_wait_s=99.0).admitted

    def test_config_validation(self):
        with pytest.raises(ValueError, match="client_budget_s"):
            AdmissionConfig(budget_s=1.0, client_budget_s=0.0)

    def test_global_latch_still_wins_eventually(self):
        _, ctrl = self._ctrl()
        v = ctrl.decide(2.0, client="a", client_wait_s=0.01)
        assert not v.admitted and v.reason == SHED_OVERLOAD


# ---------------------------------------------------------------------------
# per-shard admission (traffic-aware placement: pressure to the edge)
# ---------------------------------------------------------------------------
class TestPerShardAdmission:
    def _ctrl(self, registry=None):
        from photon_ml_tpu.serving.frontend.admission import SHED_SHARD
        return SHED_SHARD, AdmissionController(
            AdmissionConfig(budget_s=1.0, shard_budget_s=0.1),
            registry=registry)

    def test_shard_latch_is_per_shard_and_hysteretic(self):
        SHED_SHARD, ctrl = self._ctrl()
        v = ctrl.decide(0.01, shard=2, shard_wait_s=0.2)
        assert not v.admitted and v.reason == SHED_SHARD
        assert ctrl.shard_shedding(2) and not ctrl.shedding
        # the hot shard's latch touches nobody else
        assert ctrl.decide(0.01, shard=0, shard_wait_s=0.01).admitted
        # hysteresis: above the resume watermark (0.5 * 0.1) stays shed...
        assert not ctrl.decide(0.01, shard=2, shard_wait_s=0.06).admitted
        # ...below it the latch opens and the request is admitted
        assert ctrl.decide(0.01, shard=2, shard_wait_s=0.04).admitted
        assert not ctrl.shard_shedding(2)

    def test_off_by_default_and_unsharded_inert(self):
        ctrl = AdmissionController(AdmissionConfig(budget_s=1.0))
        # shard args are inert without a shard budget configured
        assert ctrl.decide(0.01, shard=1, shard_wait_s=99.0).admitted
        _, ctrl2 = self._ctrl()
        # shard < 0 / None mean "unsharded store": the shard path skips
        assert ctrl2.decide(0.01, shard=-1, shard_wait_s=99.0).admitted
        assert ctrl2.decide(0.01, shard=None, shard_wait_s=99.0).admitted

    def test_gauge_tracks_latch(self):
        from photon_ml_tpu.obs.registry import MetricsRegistry
        reg = MetricsRegistry()
        _, ctrl = self._ctrl(registry=reg)
        ctrl.decide(0.0, shard=3, shard_wait_s=0.2)
        assert reg.gauge("front_shard_shedding", shard="3") == 1
        ctrl.decide(0.0, shard=3, shard_wait_s=0.01)
        assert reg.gauge("front_shard_shedding", shard="3") == 0

    def test_config_validation(self):
        with pytest.raises(ValueError, match="shard_budget_s"):
            AdmissionConfig(budget_s=1.0, shard_budget_s=0.0)


class TestClientOverloadFlightDump:
    def test_client_latch_edge_dumps_once(self, tmp_path):
        """A client budget breach snapshots the trace ring exactly once
        per latch EDGE — the evidence survives for /flightz without a
        flapping client filling the spool."""
        from photon_ml_tpu.obs.pulse.flight import (FlightRecorder,
                                                    set_flight)
        rec = FlightRecorder(str(tmp_path / "spool"), min_interval_s=0.0)
        set_flight(rec)
        try:
            ctrl = AdmissionController(
                AdmissionConfig(budget_s=1.0, client_budget_s=0.1))
            ctrl.decide(0.0, client="a", client_wait_s=0.2)  # latch edge
            ctrl.decide(0.0, client="a", client_wait_s=0.2)  # still latched
            dumps = [d for d in rec.index()
                     if d["reason"] == "client_overload"]
            assert len(dumps) == 1
            latest = rec.latest()
            assert latest["reason"] == "client_overload"
            assert latest["detail"]["client"] == "a"
            # unlatch, breach again: a NEW edge, a new dump
            ctrl.decide(0.0, client="a", client_wait_s=0.01)
            ctrl.decide(0.0, client="a", client_wait_s=0.2)
            assert len([d for d in rec.index()
                        if d["reason"] == "client_overload"]) == 2
        finally:
            set_flight(None)

    def test_no_recorder_no_crash(self):
        from photon_ml_tpu.obs.pulse.flight import set_flight
        set_flight(None)
        ctrl = AdmissionController(
            AdmissionConfig(budget_s=1.0, client_budget_s=0.1))
        assert not ctrl.decide(0.0, client="a",
                               client_wait_s=0.2).admitted


# ---------------------------------------------------------------------------
# connection cap (ISSUE 9 satellite)
# ---------------------------------------------------------------------------
class TestConnectionCap:
    def test_cap_refuses_cleanly_and_slot_frees_on_close(self):
        eng = _engine()
        front = _front(eng, max_connections=2)
        rng = np.random.default_rng(4)
        try:
            c1, c2 = Client(front.port), Client(front.port)
            c1.send(_wire_req(rng, uid=0))
            c1.send_raw("\n")
            assert c1.recv()["uid"] == 0  # admitted clients serve normally

            c3 = Client(front.port)
            reply = c3.recv()
            assert reply["error"] == "too_many_connections"
            assert reply["max_connections"] == 2
            assert c3.f.readline() == ""  # one reply, then a clean close
            c3.close()
            reg = eng.metrics.registry
            assert reg.counter("front_connections_refused_total") >= 1

            c2.close()  # frees a slot (server-side teardown is async)
            deadline = time.time() + 30
            admitted = False
            while time.time() < deadline and not admitted:
                c4 = Client(front.port)
                c4.send(_wire_req(rng, uid=9))
                c4.send_raw("\n")
                obj = c4.recv()
                admitted = obj.get("uid") == 9
                c4.close()
                if not admitted:
                    time.sleep(0.05)
            assert admitted, "freed slot never became admittable"
            c1.close()
        finally:
            front.stop()


# ---------------------------------------------------------------------------
# coordinated-omission correction in the load generator (ISSUE 9 satellite)
# ---------------------------------------------------------------------------
class TestCoordinatedOmission:
    def test_corrected_percentiles_dominate_raw(self):
        from photon_ml_tpu.serving.frontend import run_open_loop

        eng = _engine()
        front = _front(eng)
        rng = np.random.default_rng(5)
        pool = [_wire_req(rng, uid=None) for _ in range(32)]

        def make_request(uid):
            req = dict(pool[uid % len(pool)])
            req["uid"] = uid
            return req

        try:
            res = asyncio.run(run_open_loop(
                "127.0.0.1", front.port, 200.0, 0.5, make_request,
                n_connections=2, rng=np.random.default_rng(6)))
        finally:
            front.stop()
        assert res.completed > 0 and res.lost == 0
        # a request can never fire BEFORE its scheduled arrival, so the
        # schedule-clock latency dominates the send-clock latency pointwise
        # and therefore at every percentile
        for k in ("p50", "p99", "p999"):
            assert res.latency_corrected_ms[k] >= res.latency_ms[k] - 1e-6
        assert res.max_send_lag_ms >= 0.0
        out = res.to_json()
        assert set(out["latency_corrected_ms"]) == {"p50", "p99", "p999"}
        assert "max_send_lag_ms" in out  # BENCH_NET rows carry both clocks
