"""End-to-end runs on the REFERENCE'S OWN checked-in Avro fixtures.

The reference's integration tests run its drivers over fixtures under
photon-client/src/integTest/resources/DriverIntegTest/input (DriverTest.scala:
HEART_EXPECTED_NUM_FEATURES=14, HEART_EXPECTED_NUM_TRAINING_DATA=250, stage
flow, best-λ selection, malformed-weight failure cases).  These tests drive
OUR production reader and CLI over the very same files — wire-format parity
(pure-python Avro codec vs their Java-written containers) plus pipeline
behavior on real data, not synthetic look-alikes.

Skipped wholesale when the reference checkout is absent.
"""

import json
import os

import numpy as np
import pytest

_REF_INPUT = ("/root/reference/photon-client/src/integTest/resources/"
              "DriverIntegTest/input")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_REF_INPUT), reason="reference fixtures not present")


def _heart(*parts):
    return os.path.join(_REF_INPUT, *parts)


def test_production_reader_reads_reference_heart():
    """Our reader on their heart.avro: 250 rows, 13 features + intercept = 14
    (DriverTest.scala HEART_EXPECTED_* constants); schema uses 'label' (the
    TrainingExample writer's name), remapped via input columns exactly as the
    reference's InputColumnsNames machinery would."""
    from photon_ml_tpu.data.index_map import build_index_maps_from_records
    from photon_ml_tpu.data.avro import read_directory
    from photon_ml_tpu.data.reader import read_game_data_avro

    records = list(read_directory(_heart("heart.avro")))
    assert len(records) == 250

    index_maps = build_index_maps_from_records(records, {"all": None},
                                               add_intercept=True)
    data, _ = read_game_data_avro(
        [_heart("heart.avro")], index_maps, records=records,
        input_columns={"response": "label"})
    assert data.num_samples == 250
    assert data.features["all"].shape == (250, 14)
    labels = set(np.unique(np.asarray(data.y)))
    assert labels == {0.0, 1.0}
    # intercept column is all ones
    ii = index_maps["all"].intercept_index
    np.testing.assert_array_equal(data.features["all"][:, ii], 1.0)


def test_cli_e2e_on_reference_heart(tmp_path):
    """Full train driver on their heart.avro + heart_validation.avro over a
    λ grid with standard-deviation scaling — the reference's
    testRunWithValidation scenario (DriverTest.scala:110-150): all grid
    models trained, a best model selected by validation AUC, and the learned
    classifier actually separates the data."""
    from photon_ml_tpu.cli import train as train_cli

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", _heart("heart.avro"),
        "--validation-data", _heart("heart_validation.avro"),
        "--input-columns", "response=label",
        "--feature-shards", "all",
        "--coordinate", "name=global,feature.shard=all,reg.weights=0.1|1|10|100",
        "--evaluators", "auc,logistic_loss",
        "--normalization", "SCALE_WITH_STANDARD_DEVIATION",
        "--model-output-mode", "ALL",
        "--output-dir", out,
    ])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["train_samples"] == 250
    # Quality bar in the reference's captured-baseline style
    # (GameTrainingDriverIntegTest RMSE<=1.2): an independent scipy L-BFGS
    # solve of the same grid tops out at validation AUC 0.82292 (λ=100 on the
    # 20-row validation set) — our grid-best must match that ceiling.
    assert abs(summary["validation"]["auc"] - 0.8229167) < 2e-3, \
        summary["validation"]
    # all four grid models were trained and persisted (ModelOutputMode.ALL —
    # the reference's DriverTest asserts one text model per λ the same way)
    assert os.path.isdir(os.path.join(out, "best", "fixed-effect", "global"))
    model_dirs = os.listdir(os.path.join(out, "models"))
    assert len(model_dirs) == 4


@pytest.mark.parametrize("fixture", ["zero-weights.avro", "negative-weights.avro"])
def test_cli_rejects_reference_bad_weight_fixtures(tmp_path, fixture):
    """The reference ships malformed-weight fixtures and expects the driver
    to fail validation (SURVEY §4 'bad-input tests'); our VALIDATE_FULL path
    must reject the same files."""
    from photon_ml_tpu.cli import train as train_cli

    rc = train_cli.run([
        "--train-data", _heart("bad-weights", fixture),
        "--input-columns", "response=label",
        "--feature-shards", "all",
        "--coordinate", "name=global,feature.shard=all,reg.weights=1",
        "--output-dir", str(tmp_path / "o"),
    ])
    assert rc == 1


def test_cli_input_columns_on_reference_renamed_fixture(tmp_path):
    """Their different-column-names fixture (the_label/metadata/w/intercept)
    trains through --input-columns remapping (reference InputColumnsNames +
    different-column-names DriverTest case)."""
    from photon_ml_tpu.cli import train as train_cli

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", _heart("different-column-names", "diff-col-names.avro"),
        "--input-columns",
        "response=the_label,weight=w,offset=intercept,metadataMap=metadata",
        "--feature-shards", "all",
        "--coordinate", "name=global,feature.shard=all,reg.weights=10",
        "--output-dir", out,
    ])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["train_samples"] == 250


@pytest.mark.parametrize("stem,task,remap", [
    ("linear_regression", "LINEAR_REGRESSION", True),
    ("poisson", "POISSON_REGRESSION", False),  # poisson_test.avro already
    # uses 'response' (and omits weight/offset/metadataMap entirely)
])
def test_cli_regression_tasks_on_reference_fixtures(tmp_path, stem, task, remap):
    """Their linear/poisson fixtures train end-to-end under the matching task
    (legacy Driver covers all task types on these files)."""
    from photon_ml_tpu.cli import train as train_cli

    train = _heart(f"{stem}_train.avro")
    val = _heart(f"{stem}_val.avro")
    if not os.path.exists(train):
        train = _heart(f"{stem}_test.avro")
    args = [
        "--train-data", train,
        "--feature-shards", "all",
        "--task", task,
        "--coordinate", "name=global,feature.shard=all,reg.weights=1",
        "--evaluators", "rmse",
        "--output-dir", str(tmp_path / "out"),
    ]
    if remap:
        args[2:2] = ["--input-columns", "response=label"]
    if os.path.exists(val):
        args[2:2] = ["--validation-data", val]
    rc = train_cli.run(args)
    assert rc == 0


def test_a9a_quickstart_auc_parity():
    """BASELINE.md config #1 correctness gate: the reference README
    quick-start trains logistic regression on the bundled UCI adult (a9a)
    libsvm data; canonical test AUC for L2 logistic on a9a is ~0.902.  Our
    full estimator path must reach it (the perf bench rides this config)."""
    import jax.numpy as jnp

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.data.reader import read_libsvm
    from photon_ml_tpu.evaluation.metrics import auc_roc
    from photon_ml_tpu.game import FixedEffectConfig, GameData, GameEstimator
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    xtr, ytr, ii = read_libsvm(_heart("a9a"))
    xte, yte, _ = read_libsvm(_heart("a9a.t"), num_features=xtr.shape[1] - 1)
    assert xtr.shape == (32561, 124) and xte.shape == (16281, 124)

    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "g": FixedEffectConfig(feature_shard="all",
                               solver=SolverConfig(max_iters=100, tolerance=1e-7),
                               reg=Regularization(l2=1.0), intercept_index=ii)})
    res = GameEstimator().fit(GameData(y=ytr, features={"all": xtr},
                                       id_tags={}), [cfg])[0]
    w = np.asarray(res.model["g"].coefficients.means)
    auc = float(auc_roc(jnp.asarray(xte @ w), jnp.asarray(yte),
                        jnp.ones(len(yte))))
    assert auc > 0.895, auc


def test_a9a_sparse_shard_cli_e2e(tmp_path):
    """The huge-vocabulary path on real data: a9a converted to
    TrainingExampleAvro, trained via the CLI with --sparse-threshold so the
    shard loads as a row-padded SparseShard — same AUC as the dense path
    (the reference's scale story stores sparse features per LabeledPoint;
    SURVEY §2.7 maps it to our padded-COO layout)."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE

    def to_avro(src, dst, limit=None):
        records = []
        with open(src) as f:
            for i, line in enumerate(f):
                if limit is not None and i >= limit:
                    break
                parts = line.split()
                if not parts:
                    continue
                y = 1.0 if float(parts[0]) > 0 else 0.0
                feats = [{"name": tok.partition(":")[0], "term": "",
                          "value": float(tok.partition(":")[2])}
                         for tok in parts[1:]]
                records.append({"uid": i, "response": y, "label": None,
                                "features": feats, "weight": None,
                                "offset": None, "metadataMap": None})
        avro_io.write_container(dst, TRAINING_EXAMPLE, records)
        return len(records)

    train_path = str(tmp_path / "a9a_train.avro")
    val_path = str(tmp_path / "a9a_val.avro")
    n_tr = to_avro(_heart("a9a"), train_path, limit=8000)
    to_avro(_heart("a9a.t"), val_path, limit=4000)

    def run(sparse_threshold):
        out = str(tmp_path / f"out{sparse_threshold}")
        rc = train_cli.run([
            "--train-data", train_path, "--validation-data", val_path,
            "--feature-shards", "all",
            "--coordinate", "name=g,feature.shard=all,reg.weights=1",
            "--evaluators", "auc",
            "--sparse-threshold", str(sparse_threshold),
            "--output-dir", out,
        ])
        assert rc == 0
        return json.load(open(os.path.join(out, "training-summary.json")))

    dense = run(0)
    sparse = run(50)  # 123 features >= 50 -> SparseShard layout
    assert dense["train_samples"] == n_tr
    assert sparse["validation"]["auc"] > 0.89
    assert abs(sparse["validation"]["auc"] - dense["validation"]["auc"]) < 2e-3


def test_cli_smoothed_hinge_svm_on_reference_heart(tmp_path):
    """SMOOTHED_HINGE_LOSS_LINEAR_SVM end-to-end on their heart data (the
    reference's legacy Driver trains all four task types on these fixtures;
    TaskType.scala:25).  The smoothed-hinge margin classifier must separate
    heart comparably to the logistic run (same validation AUC ballpark)."""
    from photon_ml_tpu.cli import train as train_cli

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", _heart("heart.avro"),
        "--validation-data", _heart("heart_validation.avro"),
        "--input-columns", "response=label",
        "--feature-shards", "all",
        "--task", "SMOOTHED_HINGE_LOSS_LINEAR_SVM",
        "--coordinate", "name=global,feature.shard=all,reg.weights=0.1|1|10",
        "--evaluators", "auc",
        "--normalization", "SCALE_WITH_STANDARD_DEVIATION",
        "--output-dir", out,
    ])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.75, summary["validation"]


def test_diagnose_driver_on_reference_heart(tmp_path):
    """Diagnostics pipeline (bootstrap CIs, learning curve, Hosmer-Lemeshow
    calibration, feature importance, HTML report) over their heart fixtures —
    the legacy Driver's DIAGNOSED stage on the same data
    (Driver.scala:431, DriverStage.scala:50)."""
    from photon_ml_tpu.cli import diagnose as diag_cli
    from photon_ml_tpu.cli import train as train_cli

    model_out = str(tmp_path / "model")
    assert train_cli.run([
        "--train-data", _heart("heart.avro"),
        "--input-columns", "response=label",
        "--feature-shards", "all",
        "--coordinate", "name=global,feature.shard=all,reg.weights=10",
        "--output-dir", model_out]) == 0

    diag_out = str(tmp_path / "diag")
    rc = diag_cli.run([
        "--data", _heart("heart.avro"),
        "--holdout", _heart("heart_validation.avro"),
        "--input-columns", "response=label",
        "--model-dir", model_out,
        "--output-dir", diag_out,
        "--bootstrap-replicates", "8",
    ])
    assert rc == 0
    report = os.path.join(diag_out, "report.html")
    assert os.path.exists(report)
    html = open(report).read()
    # every diagnostics module shows up in the report tree: bootstrap,
    # fitting (learning curve), calibration, importance, residuals — plus
    # the index page and the model-summary chapter
    for section in ("Model summary", "Bootstrap", "Learning curve", "Hosmer",
                    "Feature importance", "Kendall tau", 'href="#s'):
        assert section.lower() in html.lower(), section


def test_import_reference_saved_game_model(tmp_path):
    """MIGRATION: load a GAME model saved by LinkedIn Photon ML ITSELF.  The
    reference ships one (GameIntegTest/gameModel — model-metadata.json +
    fixed-effect/globalShard/coefficients/part-00000.avro in the
    ModelProcessingUtils layout); we import it, rebuild index maps from the
    stored (name, term) triples, and score with it.  A random-effect
    coordinate in the same layout (written here with the reference's schema)
    imports alongside."""
    import shutil

    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import BAYESIAN_LINEAR_MODEL
    from photon_ml_tpu.storage.model_io import import_reference_game_model
    from photon_ml_tpu.types import TaskType

    src = ("/root/reference/photon-client/src/integTest/resources/"
           "GameIntegTest/gameModel")
    model_dir = str(tmp_path / "gameModel")
    shutil.copytree(src, model_dir)
    # their checked-in fixture has empty random-effect dirs (only id-info);
    # add per-entity records in the same layout/schema for the RE half
    re_dir = os.path.join(model_dir, "random-effect", "userId-userShard")
    recs = [
        {"modelId": "alice", "modelClass": "x", "lossFunction": "",
         "means": [{"name": "u", "term": "1", "value": 0.5},
                   {"name": "(INTERCEPT)", "term": "", "value": -1.0}],
         "variances": None},
        {"modelId": "bob", "modelClass": "x", "lossFunction": "",
         "means": [{"name": "u", "term": "2", "value": 2.0}],
         "variances": None},
    ]
    avro_io.write_container(os.path.join(re_dir, "part-00000.avro"),
                            BAYESIAN_LINEAR_MODEL, recs)

    model, task, index_maps, entity_indexes = \
        import_reference_game_model(model_dir)
    assert task == TaskType.LINEAR_REGRESSION

    # fixed effect: their stored coefficients come back by feature name
    fixed = model["globalShard"]
    imap = index_maps["globalShard"]
    ii = imap.get_index("(INTERCEPT)", "")
    np.testing.assert_allclose(fixed.coefficients.means[ii],
                               3.5525033712866567)
    ju = imap.get_index("u", "1")
    np.testing.assert_allclose(fixed.coefficients.means[ju],
                               -0.8386040284501038)

    # random effect: entity string ids -> slots; coefficients by name;
    # the type AND shard come from id-info (the authoritative source the
    # reference's own loader reads), not the directory name
    re_model = model["userId-userShard"]
    assert re_model.random_effect_type == "userId"
    assert re_model.feature_shard == "userShard"
    eidx = entity_indexes["userId"]
    re_imap = index_maps["userShard"]
    alice = re_model.w_stack[re_model.slot_of[eidx.get("alice")]]
    np.testing.assert_allclose(alice[re_imap.get_index("u", "1")], 0.5)
    np.testing.assert_allclose(alice[re_imap.get_index("(INTERCEPT)", "")], -1.0)
    bob = re_model.w_stack[re_model.slot_of[eidx.get("bob")]]
    np.testing.assert_allclose(bob[re_imap.get_index("u", "2")], 2.0)


def test_score_cli_with_reference_model(tmp_path):
    """Score driver over a REFERENCE-saved model (--model-format reference):
    data whose features use the model's (name, term) vocabulary scores
    through the imported model end-to-end — the GameScoringDriver migration
    path without retraining."""
    import shutil

    from photon_ml_tpu.cli import score as score_cli
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
    from photon_ml_tpu.storage.model_io import import_reference_game_model

    src = ("/root/reference/photon-client/src/integTest/resources/"
           "GameIntegTest/gameModel")
    model_dir = str(tmp_path / "gameModel")
    shutil.copytree(src, model_dir)

    # data in the model's own vocabulary (u/term and s/term features)
    rng = np.random.default_rng(8)
    records = []
    for i in range(60):
        feats = [{"name": "u", "term": str(int(rng.integers(1, 3))),
                  "value": float(rng.normal())},
                 {"name": "s", "term": str(int(rng.integers(0, 2))),
                  "value": float(rng.normal())}]
        records.append({"uid": i, "response": float(rng.random() < 0.5),
                        "label": None, "features": feats, "weight": None,
                        "offset": None, "metadataMap": None})
    data_path = str(tmp_path / "score_me.avro")
    avro_io.write_container(data_path, TRAINING_EXAMPLE, records)

    out = str(tmp_path / "scores")
    rc = score_cli.run([
        "--data", data_path,
        "--model-dir", model_dir,
        "--model-format", "reference",
        "--output-dir", out,
    ])
    assert rc == 0
    scores = list(avro_io.read_container(os.path.join(out, "scores.avro")))
    assert len(scores) == 60
    assert all(np.isfinite(s["predictionScore"]) for s in scores)
    # intercept-only sample would score the model's intercept; verify scores
    # actually use the imported coefficients (nonzero spread)
    vals = np.asarray([s["predictionScore"] for s in scores])
    assert vals.std() > 0.1


def test_train_cli_warm_start_from_reference_model(tmp_path):
    """Warm start / partial retraining FROM a reference-saved model
    (--model-input-format reference): the imported coefficients remap into
    this run's index maps by feature name, and --lock-coordinates keeps the
    imported coordinate fixed (re-scored only) while new ones train."""
    import shutil

    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
    from photon_ml_tpu.storage.model_io import load_game_model

    src = ("/root/reference/photon-client/src/integTest/resources/"
           "GameIntegTest/gameModel")
    model_dir = str(tmp_path / "gameModel")
    shutil.copytree(src, model_dir)

    # training data in the imported model's vocabulary
    rng = np.random.default_rng(9)
    records = []
    for i in range(240):
        feats = [{"name": "u", "term": str(int(rng.integers(1, 3))),
                  "value": float(rng.normal())},
                 {"name": "s", "term": str(int(rng.integers(0, 2))),
                  "value": float(rng.normal())}]
        records.append({"uid": i, "response": float(rng.normal()),
                        "label": None, "features": feats, "weight": None,
                        "offset": None, "metadataMap": None})
    data_path = str(tmp_path / "train.avro")
    avro_io.write_container(data_path, TRAINING_EXAMPLE, records)

    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", data_path,
        "--feature-shards", "all",
        "--task", "LINEAR_REGRESSION",
        "--coordinate", "name=globalShard,feature.shard=all,reg.weights=10",
        "--model-input-dir", model_dir,
        "--model-input-format", "reference",
        "--lock-coordinates", "globalShard",
        "--output-dir", out,
    ])
    assert rc == 0
    # the locked coordinate must come out EXACTLY as imported (re-scored,
    # never re-trained): original-space intercept coefficient preserved
    imap = load_index(os.path.join(out, "all.idx"))
    model, _ = load_game_model(os.path.join(out, "best"), {"all": imap}, {})
    ii = imap.get_index("(INTERCEPT)", "")
    np.testing.assert_allclose(model["globalShard"].coefficients.means[ii],
                               3.5525033712866567)


def test_index_driver_from_reference_feature_lists(tmp_path):
    """Index driver consuming the reference's own feature-list files
    (NameAndTermFeatureBagsDriver output, 'name<TAB>term' lines —
    GameIntegTest/input/feature-lists), exactly what its
    FeatureIndexingDriver turns into PalDB stores."""
    from photon_ml_tpu.cli import index as index_cli
    from photon_ml_tpu.data.index_map import load_index

    lists_dir = ("/root/reference/photon-client/src/integTest/resources/"
                 "GameIntegTest/input/feature-lists")
    out = str(tmp_path / "idx")
    rc = index_cli.run([
        "--feature-shards", "globalShard,userShard",
        "--feature-lists",
        f"globalShard={lists_dir}/features,userShard={lists_dir}/userFeatures",
        "--output-dir", out,
    ])
    assert rc == 0
    imap = load_index(os.path.join(out, "globalShard.idx"))
    n_lines = len({line.rstrip("\n") for line in open(f"{lists_dir}/features")
                   if line.strip("\n")})
    assert imap.size == n_lines + 1  # + intercept
    assert imap.intercept_index == 0
    # a known feature from the list resolves
    first = next(line for line in open(f"{lists_dir}/features")
                 if line.strip("\n"))
    name, _, term = first.rstrip("\n").partition("\t")
    assert imap.get_index(name, term) >= 0


def test_train_warm_start_subset_migration(tmp_path):
    """Subset migration: warm-starting only SOME of the reference model's
    coordinates — unconfigured coordinate dirs are skipped entirely (never
    decoded), not errors."""
    import shutil

    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import BAYESIAN_LINEAR_MODEL, TRAINING_EXAMPLE

    src = ("/root/reference/photon-client/src/integTest/resources/"
           "GameIntegTest/gameModel")
    mdir = str(tmp_path / "m")
    shutil.copytree(src, mdir)
    # populate an RE coordinate the training run will NOT configure
    avro_io.write_container(
        os.path.join(mdir, "random-effect", "userId-userShard", "part-00000.avro"),
        BAYESIAN_LINEAR_MODEL,
        [{"modelId": "alice", "modelClass": "x", "lossFunction": "",
          "means": [{"name": "u", "term": "1", "value": 0.5}],
          "variances": None}])

    rng = np.random.default_rng(2)
    records = [{"uid": i, "response": float(rng.normal()), "label": None,
                "features": [{"name": "u", "term": "1",
                              "value": float(rng.normal())}],
                "weight": None, "offset": None, "metadataMap": None}
               for i in range(80)]
    dp = str(tmp_path / "d.avro")
    avro_io.write_container(dp, TRAINING_EXAMPLE, records)

    rc = train_cli.run([
        "--train-data", dp, "--feature-shards", "all",
        "--task", "LINEAR_REGRESSION",
        "--coordinate", "name=globalShard,feature.shard=all,reg.weights=1",
        "--model-input-dir", mdir, "--model-input-format", "reference",
        "--output-dir", str(tmp_path / "out"),
    ])
    assert rc == 0


def test_export_reference_layout_round_trip(tmp_path):
    """Bidirectional migration: a model trained HERE exports to the
    reference's on-disk layout and round-trips through the importer with
    coefficients intact (the Spark side reads the same layout)."""
    from photon_ml_tpu.cli import train as train_cli
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.index_map import load_index
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
    from photon_ml_tpu.storage.model_io import (export_reference_game_model,
                                                import_reference_game_model,
                                                load_game_model)

    rng = np.random.default_rng(4)
    records = []
    for i in range(240):
        u = int(rng.integers(0, 5))
        xg = rng.normal(size=2)
        y = float(rng.random() < 1 / (1 + np.exp(-(xg[0] - xg[1]))))
        records.append({"uid": i, "response": y, "label": None,
                        "features": [
                            {"name": "f0", "term": "", "value": float(xg[0])},
                            {"name": "f1", "term": "a", "value": float(xg[1])}],
                        "weight": None, "offset": None,
                        "metadataMap": {"userId": f"user{u}"}})
    dp = str(tmp_path / "d.avro")
    avro_io.write_container(dp, TRAINING_EXAMPLE, records)
    out = str(tmp_path / "out")
    assert train_cli.run([
        "--train-data", dp, "--feature-shards", "all",
        "--coordinate", "name=g,feature.shard=all,reg.weights=1",
        "--coordinate", "name=u,random.effect.type=userId,feature.shard=all,"
                        "reg.weights=1",
        "--id-tags", "userId",
        "--output-dir", out]) == 0

    imap = load_index(os.path.join(out, "all.idx"))
    from photon_ml_tpu.data.reader import EntityIndex
    eidx = EntityIndex.load(os.path.join(out, "userId.entities.json"))
    model, task = load_game_model(os.path.join(out, "best"), {"all": imap},
                                  {"userId": eidx})

    ref_dir = str(tmp_path / "ref_layout")
    export_reference_game_model(model, ref_dir, {"all": imap},
                                {"userId": eidx}, task)

    # The exported layout must match what the REFERENCE's loader requires —
    # not merely what our own (glob-tolerant) importer accepts:
    # random-effect records under coefficients/ (ModelProcessingUtils.scala:229
    # AvroConstants.COEFFICIENTS) and a JVM modelClass that Class.forName can
    # resolve (AvroUtils.scala:382-413).
    fe_avro = os.path.join(ref_dir, "fixed-effect", "g", "coefficients",
                           "part-00000.avro")
    re_avro = os.path.join(ref_dir, "random-effect", "u", "coefficients",
                           "part-00000.avro")
    assert os.path.isfile(fe_avro)
    assert os.path.isfile(re_avro)
    fe_rec = next(iter(avro_io.read_container(fe_avro)))
    assert fe_rec["modelClass"] == (
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel")
    re_recs = list(avro_io.read_container(re_avro))
    assert len(re_recs) == len(model["u"].slot_of)
    assert all(r["modelClass"].startswith("com.linkedin.photon.ml.supervised.")
               for r in re_recs)

    # round trip through the importer (fresh index maps from stored names)
    back, task2, imaps2, eidx2 = import_reference_game_model(ref_dir)
    assert task2 == task
    # fixed coefficients identical feature-by-feature
    re_imap = imaps2["all"]
    for j in range(imap.size):
        name, term = imap.get_feature_name(j)
        j2 = re_imap.get_index(name, term)
        orig = model["g"].coefficients.means[j]
        if orig != 0.0:
            np.testing.assert_allclose(back["g"].coefficients.means[j2], orig)
    # per-entity: one entity's vector matches through the name remap
    u0 = eidx.get("user0")
    slot = model["u"].slot_of[u0]
    u0b = eidx2["userId"].get("user0")
    slot2 = back["u"].slot_of[u0b]
    for j in range(imap.size):
        name, term = imap.get_feature_name(j)
        j2 = re_imap.get_index(name, term)
        orig = model["u"].w_stack[slot, j]
        if orig != 0.0:
            np.testing.assert_allclose(back["u"].w_stack[slot2, j2], orig)
