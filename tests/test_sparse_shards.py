"""Sparse feature shards: row-padded COO end-to-end (the huge-vocabulary
path — reference scale story, SURVEY §2.7)."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.index_map import build_index_maps_from_avro
from photon_ml_tpu.data.reader import read_game_data_avro
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.game.config import FixedEffectConfig, GameConfig
from photon_ml_tpu.game.data import SparseShard
from photon_ml_tpu.game.estimator import GameEstimator
from photon_ml_tpu.types import TaskType


def _write(path, n=300, vocab=40, k=5, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=vocab) * 0.7
    records = []
    for i in range(n):
        js = rng.choice(vocab, size=k, replace=False)
        vs = rng.normal(size=k)
        logit = float(vs @ w[js])
        yv = float(rng.random() < 1 / (1 + np.exp(-logit)))
        feats = [{"name": f"f{j}", "term": "", "value": float(v)}
                 for j, v in zip(js, vs)]
        records.append({"uid": i, "response": yv, "label": None,
                        "features": feats, "weight": None, "offset": None,
                        "metadataMap": {}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)


@pytest.fixture(scope="module")
def sparse_setup(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("sp") / "train.avro")
    _write(path)
    imap = build_index_maps_from_avro([path], {"all": []})["all"]
    return path, imap


def test_sparse_load_layout(sparse_setup):
    path, imap = sparse_setup
    data, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    shard = data.features["all"]
    assert isinstance(shard, SparseShard)
    n, d = shard.shape
    assert n == 300 and d == imap.size
    assert shard.indices.shape == shard.values.shape
    assert shard.indices.shape[1] <= 5 + 1  # k features + intercept slot
    # intercept slot present on every row
    ii = imap.intercept_index
    assert np.all(np.any((shard.indices == ii) & (shard.values == 1.0), axis=1))


def test_sparse_dense_margin_parity(sparse_setup):
    path, imap = sparse_setup
    dense, _ = read_game_data_avro([path], {"all": imap})
    sparse, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    w = np.random.default_rng(1).normal(size=imap.size)
    dense_margins = np.asarray(dense.features["all"]) @ w
    sh = sparse.features["all"]
    sparse_margins = np.einsum("nk,nk->n", sh.values, w[sh.indices])
    np.testing.assert_allclose(sparse_margins, dense_margins, rtol=1e-5)


@pytest.mark.parametrize("opt", ["LBFGS", "TRON"])
def test_sparse_dense_solve_parity(sparse_setup, opt):
    """The fixed-effect solve must reach the same optimum either layout."""
    from photon_ml_tpu.types import OptimizerType

    path, imap = sparse_setup
    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(feature_shard="all",
                                   optimizer=OptimizerType[opt],
                                   reg=Regularization(l2=0.1))})
    out = {}
    for mode, sparse_set in (("dense", set()), ("sparse", {"all"})):
        data, _ = read_game_data_avro([path], {"all": imap},
                                      sparse_shards=sparse_set)
        res = GameEstimator().fit(data, [cfg])[0]
        out[mode] = np.asarray(res.model["fixed"].coefficients.means)
    # different computation orders (matmul vs gather/scatter) -> optima agree
    # only to solver-tolerance scale in f32; the approximate-Wolfe slack
    # (opt/linesearch.py) lets each stop anywhere in the working-precision
    # plateau, so ill-conditioned coordinates wander a few e-3 at equal
    # objective value
    np.testing.assert_allclose(out["sparse"], out["dense"], atol=5e-3)


def test_sparse_fallback_records_path(sparse_setup, monkeypatch):
    """The Python-codec fallback builds the same SparseShard."""
    import photon_ml_tpu.data.native_avro as na

    path, imap = sparse_setup
    fast, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    monkeypatch.setattr(na, "_lib", None)
    monkeypatch.setattr(na, "_lib_tried", True)
    slow, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    f, s = fast.features["all"], slow.features["all"]
    assert isinstance(s, SparseShard)
    w = np.random.default_rng(2).normal(size=imap.size)
    np.testing.assert_allclose(np.einsum("nk,nk->n", f.values, w[f.indices]),
                               np.einsum("nk,nk->n", s.values, w[s.indices]),
                               rtol=1e-5)


def test_huge_vocab_memory(tmp_path):
    """100k-feature shard: sparse layout is O(n*k); dense would be 4.8GB at
    this n — the load itself is the test."""
    path = str(tmp_path / "wide.avro")
    n, vocab = 1200, 100_000
    _write(path, n=n, vocab=vocab, k=8, seed=3)
    imap = build_index_maps_from_avro([path], {"all": []})["all"]
    assert imap.size == vocab + 1 or imap.size > 8  # observed features + intercept
    data, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    shard = data.features["all"]
    assert isinstance(shard, SparseShard)
    assert shard.values.nbytes < 10 * n * 16  # O(n*k), nowhere near n*d

    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(feature_shard="all", reg=Regularization(l2=1.0))})
    res = GameEstimator().fit(data, [cfg])[0]
    w = np.asarray(res.model["fixed"].coefficients.means)
    assert w.shape == (shard.dim,) and np.all(np.isfinite(w))


def test_sparse_cli_end_to_end(tmp_path):
    from photon_ml_tpu.cli import score as score_cli
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write(train_path, n=400, vocab=60, seed=4)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=0.1",
        "--sparse-threshold", "10",  # vocab 60 > 10 -> sparse
        "--output-dir", out])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6

    score_out = str(tmp_path / "scores")
    rc = score_cli.run(["--data", train_path, "--model-dir", out,
                        "--output-dir", score_out, "--evaluators", "auc"])
    assert rc == 0
    assert json.load(open(os.path.join(score_out, "metrics.json")))["auc"] > 0.6


def test_sparse_feature_sharded_estimator_parity(sparse_setup):
    """feature.sharded through the estimator on a (data=2, feature=4) mesh:
    blocked-w solve must match the replicated-w solve (the CLI-reachable form
    of the 1M-vocabulary scale path)."""
    import jax

    from photon_ml_tpu.parallel.mesh import make_mesh

    path, imap = sparse_setup
    data, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})

    def fit(cfg, mesh=None):
        res = GameEstimator(mesh=mesh).fit(data, [cfg])[0]
        return np.asarray(res.model["fixed"].coefficients.means)

    base = FixedEffectConfig(feature_shard="all", reg=Regularization(l2=0.5))
    plain = fit(GameConfig(task=TaskType.LOGISTIC_REGRESSION,
                           coordinates={"fixed": base}))
    mesh = make_mesh(n_data=2, n_feature=4, devices=jax.devices())
    sharded_cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(feature_shard="all", reg=Regularization(l2=0.5),
                                   feature_sharded=True)})
    sharded = fit(sharded_cfg, mesh)
    assert sharded.shape == plain.shape  # padding trimmed
    np.testing.assert_allclose(sharded, plain, atol=2e-3)


def test_sparse_feature_sharded_cli(tmp_path):
    """--mesh feature=4 + feature.sharded=true end-to-end through the CLI."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write(train_path, n=400, vocab=60, seed=5)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--coordinate",
        "name=fixed,feature.shard=all,reg.weights=0.1,feature.sharded=true",
        "--sparse-threshold", "10",
        "--mesh", "data=2,feature=4",
        "--output-dir", out])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6


def test_sparse_re_round4_combos_cli(tmp_path):
    """The round-4 carve-outs are CLI-REACHABLE on sparse shards: the train
    driver no longer forces dense for FULL variances, RANDOM projection, or
    STANDARDIZATION on random-effect coordinates (game/coordinate supports
    them under compaction since round 4)."""
    import json as _json

    from photon_ml_tpu.cli import train as train_cli

    rng = np.random.default_rng(9)
    path = str(tmp_path / "train.avro")
    n, vocab, k, n_users = 400, 60, 5, 10
    w = rng.normal(size=vocab) * 0.7
    records = []
    for i in range(n):
        js = rng.choice(vocab, size=k, replace=False)
        vs = rng.normal(size=k)
        yv = float(rng.random() < 1 / (1 + np.exp(-float(vs @ w[js]))))
        records.append({"uid": i, "response": yv, "label": None,
                        "features": [{"name": f"f{j}", "term": "",
                                      "value": float(v)}
                                     for j, v in zip(js, vs)],
                        "weight": None, "offset": None,
                        "metadataMap": {"userId": str(i % n_users)}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)

    cases = {
        "full_var": "name=user,random.effect.type=userId,feature.shard=all,"
                    "reg.weights=1,variance.type=FULL",
        "random_proj": "name=user,random.effect.type=userId,"
                       "feature.shard=all,reg.weights=1,projector=RANDOM,"
                       "projected.dim=4",
    }
    for label, coord in cases.items():
        out = str(tmp_path / label)
        rc = train_cli.run([
            "--train-data", path, "--validation-data", path,
            "--feature-shards", "all", "--evaluators", "auc",
            "--id-tags", "userId",
            "--coordinate", coord,
            "--sparse-threshold", "10",  # vocab 60 > 10 -> sparse
            "--output-dir", out])
        assert rc == 0, label
        summary = _json.load(open(os.path.join(out, "training-summary.json")))
        assert summary["validation"]["auc"] > 0.5, label

    # STANDARDIZATION over a sparse RE shard (per-lane projected contexts;
    # the intercept id auto-fills from the index map)
    out = str(tmp_path / "standardized")
    rc = train_cli.run([
        "--train-data", path, "--validation-data", path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--id-tags", "userId",
        "--normalization", "STANDARDIZATION",
        "--coordinate",
        "name=fixed,feature.shard=all,reg.weights=0.1",
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,"
        "reg.weights=1",
        "--sparse-threshold", "10",
        "--output-dir", out])
    assert rc == 0
    summary = _json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6


def test_sparse_feature_sharded_bf16_storage():
    """feature.sharded x sparse x bf16 storage compose: the blocked-w
    sharded objective reads storage-width values and widens in-register
    (ShardSparseObjective._local_margins vals.astype(blk.dtype)), so the
    solve tracks the f32 twin to bf16 input resolution."""
    import jax

    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(5)
    n, d, k = 512, 97, 6
    idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    w = rng.normal(size=d) * 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(
        -np.einsum("nk,nk->n", vals, w[idx])))).astype(np.float32)
    from photon_ml_tpu.game.data import GameData

    gd = GameData(y=y, features={"g": SparseShard(indices=idx, values=vals,
                                                  dim=d)})
    mesh = make_mesh(n_data=2, n_feature=4, devices=jax.devices())
    out = {}
    from photon_ml_tpu.game.coordinate import build_coordinate

    for sd in (None, "bfloat16"):
        cfg = FixedEffectConfig(feature_shard="g",
                                solver=__import__("photon_ml_tpu.opt.types",
                                                  fromlist=["SolverConfig"]
                                                  ).SolverConfig(max_iters=40),
                                reg=Regularization(l2=0.5),
                                feature_sharded=True, storage_dtype=sd)
        c = build_coordinate("fixed", gd, cfg, TaskType.LOGISTIC_REGRESSION,
                             mesh)
        m, _ = c.update(np.zeros(n, np.float32))
        out[sd or "f32"] = np.asarray(m.coefficients.means)
        assert out[sd or "f32"].shape == (d,)
        assert np.all(np.isfinite(out[sd or "f32"]))
    np.testing.assert_allclose(out["bfloat16"], out["f32"], atol=1.5e-2)


def test_sparse_feature_sharded_fused_sweep_matches_host():
    """A fused sweep CONTAINING a feature.sharded=true coordinate: the
    coordinate's state stays P("feature")-sharded [d_pad] inside the scanned
    program and the residual fold consumes its feature-axis-reduced [n]
    scores.  Must match the host-paced loop on the same coordinates, be
    invariant to the mesh factorization (chip-count invariance), and agree
    with the replicated-w fused sweep — one descent path for every model
    size, like the reference (CoordinateDescent.scala:93-107)."""
    import jax

    from photon_ml_tpu.game import CoordinateDescent
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(7)
    # vocab 97 is NOT a multiple of any feature-axis size used below, so the
    # padded-slot trim on publish is exercised
    n, d, k, n_users = 768, 97, 6, 16
    idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    xu = rng.normal(size=(n, 3)).astype(np.float32)
    uids = np.repeat(np.arange(n_users), n // n_users)
    w = rng.normal(size=d) * 0.5
    wu = rng.normal(size=(n_users, 3)) * 0.8
    logit = (np.einsum("nk,nk->n", vals, w[idx])
             + np.einsum("nd,nd->n", xu, wu[uids]))
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    data = GameData(y=y,
                    features={"g": SparseShard(indices=idx, values=vals, dim=d),
                              "u": xu},
                    id_tags={"userId": uids})
    solver = SolverConfig(max_iters=30)

    def coords(mesh):
        cfgs = {
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0),
                                       feature_sharded=mesh is not None),
            "per-user": RandomEffectConfig(random_effect_type="userId",
                                           feature_shard="u", solver=solver,
                                           reg=Regularization(l2=1.0)),
        }
        return {cid: build_coordinate(cid, data, c,
                                      TaskType.LOGISTIC_REGRESSION, mesh)
                for cid, c in cfgs.items()}

    mesh = make_mesh(n_data=2, n_feature=4, devices=jax.devices())
    cs = coords(mesh)
    fused_model, fused_scores = FusedSweep(cs, num_iterations=2).run()
    host_model, _, _ = CoordinateDescent(cs, num_iterations=2).run()

    wf = np.asarray(fused_model["fixed"].coefficients.means)
    assert wf.shape == (d,)  # padded slots trimmed on publish
    np.testing.assert_allclose(wf, host_model["fixed"].coefficients.means,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(fused_model["per-user"].w_stack,
                               host_model["per-user"].w_stack,
                               rtol=2e-3, atol=2e-3)
    # the in-program sharded re-scoring equals the model's own re-scoring
    np.testing.assert_allclose(
        fused_scores["fixed"],
        np.asarray(cs["fixed"].score(fused_model["fixed"])),
        rtol=1e-5, atol=1e-5)

    # chip-count invariance: a different mesh factorization, same optimum
    mesh2 = make_mesh(n_data=4, n_feature=2, devices=jax.devices())
    alt_model, _ = FusedSweep(coords(mesh2), num_iterations=2).run()
    np.testing.assert_allclose(alt_model["fixed"].coefficients.means, wf,
                               atol=2e-3)

    # replicated-w fused sweep (no mesh) reaches the same optimum
    rep_model, _ = FusedSweep(coords(None), num_iterations=2).run()
    np.testing.assert_allclose(rep_model["fixed"].coefficients.means, wf,
                               atol=2e-3)


# ---------------------------------------------------------------------------
# Sparse per-entity random-effect shards (reference LocalDataset holds sparse
# Breeze vectors per entity, data/LocalDataset.scala:35-247 — wide sparse RE
# feature bags must train WITHOUT densifying to the vocabulary)
# ---------------------------------------------------------------------------

def _sparse_re_data(seed=5, n=1024, d=2048, k=8, n_users=32):
    """Row-sparse per-user bag + its densified twin (same samples)."""
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.2] = 0.0  # padded COO slots (value 0)
    uids = np.repeat(np.arange(n_users), n // n_users)
    rng.shuffle(uids)
    w_true = (rng.normal(size=(n_users, d)) * 0.3).astype(np.float32)
    margins = np.array([vals[i] @ w_true[uids[i], idx[i]] for i in range(n)])
    y = (rng.random(n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (np.repeat(np.arange(n), k), idx.ravel()), vals.ravel())
    return idx, vals, dense, uids, y, d


def _re_coordinate(features, uids, y, d, norm=None, **cfg_kw):
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.game.config import RandomEffectConfig

    cfg = RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                             solver=SolverConfig(max_iters=25),
                             reg=Regularization(l2=1.0), **cfg_kw)
    gd = GameData(y=y, features={"u": features}, id_tags={"userId": uids})
    return build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION,
                            norm=norm), gd


def test_sparse_re_parity_vs_densified_and_hbm():
    """Sparse-shard RE fit == densified-shard RE fit (coefficients, scores),
    with the compact bucket design blocks a small fraction of the densified
    ones (the HBM claim: observed-columns width, not vocabulary width)."""
    idx, vals, dense, uids, y, d = _sparse_re_data()
    cs, _ = _re_coordinate(SparseShard(indices=idx, values=vals, dim=d),
                           uids, y, d)
    cd, _ = _re_coordinate(dense, uids, y, d)
    off = np.zeros(len(y), np.float32)
    ms, _ = cs.update(off)
    md, _ = cd.update(off)
    assert ms.w_stack.shape == md.w_stack.shape == (32, d)
    np.testing.assert_allclose(ms.w_stack, md.w_stack, atol=5e-4)
    np.testing.assert_allclose(cs.score(ms), cd.score(md), atol=5e-3)
    sparse_bytes = sum(b.x.nbytes for b in cs.buckets.buckets)
    dense_bytes = sum(b.x.nbytes for b in cd.buckets.buckets)
    # 1024 rows * 8 nnz / 32 users -> <=256 observed columns vs d=2048:
    # compact blocks must be at least 4x smaller here (8x at these shapes)
    assert dense_bytes >= 4 * sparse_bytes, (sparse_bytes, dense_bytes)


def test_sparse_re_fused_sweep_matches_host():
    """A GAME descent (fixed + sparse RE) agrees between the fused program
    and the host loop, and validation scoring consumes the sparse shard."""
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import CoordinateDescent
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.game.estimator import GameEstimator, GameTransformer
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.opt.types import SolverConfig

    idx, vals, dense, uids, y, d = _sparse_re_data(n=1024, d=1024, n_users=32)
    rng = np.random.default_rng(0)
    xg = rng.normal(size=(len(y), 8)).astype(np.float32)
    cut = 768
    def gd(sl):
        return GameData(y=y[sl], features={
            "g": xg[sl],
            "u": SparseShard(indices=idx[sl], values=vals[sl], dim=d)},
            id_tags={"userId": uids[sl]})
    tr, va = gd(slice(None, cut)), gd(slice(cut, None))
    solver = SolverConfig(max_iters=25)
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": __import__("photon_ml_tpu.game.config", fromlist=["RandomEffectConfig"]).RandomEffectConfig(
                random_effect_type="userId", feature_shard="u", solver=solver,
                reg=Regularization(l2=1.0))})
    est = GameEstimator(validation_suite=EvaluationSuite.from_specs(["auc"]))
    coords = {cid: est.build_one_coordinate(cid, tr, c, config.task, 0)
              for cid, c in config.coordinates.items()}
    model_f, _ = FusedSweep(coords, num_iterations=2).run()
    model_h, _, _ = CoordinateDescent(coords, num_iterations=2).run()
    suite = est.validation_suite
    auc_f = GameTransformer(model_f, config.task).evaluate(va, suite).values["auc"]
    auc_h = GameTransformer(model_h, config.task).evaluate(va, suite).values["auc"]
    assert abs(auc_f - auc_h) < 2e-3
    # fused and host run the same math but reassociate float32 reductions
    # differently, and 25 warm-started solver iterations amplify the last
    # bits — coefficients agree to ~1e-3, the AUC guard above is the
    # functional check
    np.testing.assert_allclose(model_f["per-user"].w_stack,
                               model_h["per-user"].w_stack, atol=5e-3)


def test_sparse_re_pearson_ratio_and_normalization():
    """INDEX_MAP + features_to_samples_ratio prunes each entity to its top-k
    |Pearson| observed columns (intercept pinned); factor normalization rides
    the per-lane compact space and round-trips to original-space models."""
    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import ProjectorType

    # small vocabulary so every observed column has MANY nonzero rows per
    # entity — near-tied Pearson scores (e.g. single-observation columns)
    # break differently under float32 between the two paths, which is
    # tie-order ambiguity, not a correctness signal
    idx, vals, dense, uids, y, d = _sparse_re_data(n=512, d=64, n_users=8)
    # intercept column d-1 on every row
    idx[:, -1] = d - 1
    vals[:, -1] = 1.0
    dense[:, :] = 0.0
    np.add.at(dense, (np.repeat(np.arange(len(y)), idx.shape[1]),
                      idx.ravel()), vals.ravel())
    cfg = dict(random_effect_type="userId", feature_shard="u",
               solver=SolverConfig(max_iters=25), reg=Regularization(l2=1.0),
               projector=ProjectorType.INDEX_MAP,
               features_to_samples_ratio=0.25, intercept_index=d - 1)
    gd_s = GameData(y=y, features={"u": SparseShard(indices=idx, values=vals,
                                                    dim=d)},
                    id_tags={"userId": uids})
    gd_d = GameData(y=y, features={"u": dense}, id_tags={"userId": uids})
    cs = build_coordinate("u", gd_s, RandomEffectConfig(**cfg),
                          TaskType.LOGISTIC_REGRESSION)
    cd = build_coordinate("u", gd_d, RandomEffectConfig(**cfg),
                          TaskType.LOGISTIC_REGRESSION)
    # identical per-entity observed-column selections (sparse builds them
    # straight from COO rows; dense scans the densified block)
    for ps, pd in zip(cs._proj.projections, cd._proj.projections):
        sel_s = [set(r[r >= 0].tolist()) for r in ps.indices]
        sel_d = [set(r[r >= 0].tolist()) for r in pd.indices]
        assert sel_s == sel_d
        assert all(d - 1 in s for s, lanes in zip(sel_s, ps.indices)
                   if lanes[0] >= 0)  # intercept survives on real lanes
    off = np.zeros(len(y), np.float32)
    ms, _ = cs.update(off)
    md, _ = cd.update(off)
    np.testing.assert_allclose(ms.w_stack, md.w_stack, atol=5e-4)

    # factor-only normalization: sparse matches the densified INDEX_MAP path
    fac = np.linspace(0.5, 2.0, d).astype(np.float32)
    norm = NormalizationContext(factors=fac, shifts=None)
    cs_n = build_coordinate("u", gd_s, RandomEffectConfig(**cfg),
                            TaskType.LOGISTIC_REGRESSION, norm=norm)
    cd_n = build_coordinate("u", gd_d, RandomEffectConfig(**cfg),
                            TaskType.LOGISTIC_REGRESSION, norm=norm)
    ms_n, _ = cs_n.update(off)
    md_n, _ = cd_n.update(off)
    np.testing.assert_allclose(ms_n.w_stack, md_n.w_stack, atol=5e-4)


def test_sparse_re_unsupported_configs_raise():
    from photon_ml_tpu.game.config import RandomEffectConfig
    from photon_ml_tpu.types import ProjectorType, VarianceComputationType

    idx, vals, dense, uids, y, d = _sparse_re_data(n=256, d=256, n_users=8)
    shard = SparseShard(indices=idx, values=vals, dim=d)
    # RANDOM of a sparse shard needs projected_dim, like the dense path
    with pytest.raises(ValueError, match="projected_dim"):
        _re_coordinate(shard, uids, y, d, projector=ProjectorType.RANDOM)
    # BOTH variance kinds are exact under compaction and BUILD (the
    # full-space Hessian is block-diagonal; see _expand_compact_variances)
    for kind in (VarianceComputationType.SIMPLE, VarianceComputationType.FULL):
        c, _ = _re_coordinate(shard, uids, y, d, variance=kind)
        assert c._compact_variances


def test_sparse_re_random_projection_matches_densified():
    """RANDOM projection of a SPARSE shard (the round-3 refusal at the old
    game/coordinate.py:654): gathering the shared Gaussian matrix's rows
    through each lane's observed-column map computes exactly the densified
    x @ A (unobserved columns contribute zero either way), so with the same
    seed the two fits share one projected problem — coefficient AND score
    parity, while the sparse path never builds [E, S, d_full] tensors."""
    from photon_ml_tpu.types import ProjectorType

    idx, vals, dense, uids, y, d = _sparse_re_data(n=768, d=1024, n_users=16)
    cs, _ = _re_coordinate(SparseShard(indices=idx, values=vals, dim=d),
                           uids, y, d, projector=ProjectorType.RANDOM,
                           projected_dim=16)
    cd, _ = _re_coordinate(dense, uids, y, d,
                           projector=ProjectorType.RANDOM, projected_dim=16)
    off = np.zeros(len(y), np.float32)
    ms, _ = cs.update(off)
    md, _ = cd.update(off)
    assert ms.w_stack.shape == md.w_stack.shape == (16, d)
    # the projected designs are bitwise-different f32 reductions (dense x@A
    # vs compact gather-einsum), and warm solver iterations amplify the last
    # bits — same tolerance class as the other sparse/dense parity tests
    np.testing.assert_allclose(ms.w_stack, md.w_stack, atol=5e-3)
    np.testing.assert_allclose(cs.score(ms), cd.score(md), atol=1e-2)
    # the projected design blocks are small: d_proj(+intercept slot) wide,
    # nowhere near the 1024-wide densified blocks
    assert all(b.x.shape[2] <= 17 for b in cs._proj.buckets)


def test_sparse_re_box_constraints_match_densified():
    """Box constraints on a SPARSE random-effect shard: per-lane bounds
    gathered through each entity's observed-column map (the compact twin of
    the reference's full-space projectCoefficientsToSubspace,
    OptimizationUtils.scala) must reproduce the densified IDENTITY
    full-space constrained solve — including unobserved constrained
    features, which publish clip(0, lo, hi), on the host path AND through
    the fused program."""
    import jax.numpy as jnp

    idx, vals, dense, uids, y, d = _sparse_re_data(n=512, d=512, n_users=16)
    cons = ((0, -0.05, 0.05), (2, 0.01, 0.5), (5, -0.5, 0.5))
    cs, _ = _re_coordinate(SparseShard(indices=idx, values=vals, dim=d),
                           uids, y, d, constraints=cons)
    cd, _ = _re_coordinate(dense, uids, y, d, constraints=cons)
    off = np.zeros(len(y), np.float32)
    ms, _ = cs.update(off)
    md, _ = cd.update(off)
    for j, lo, hi in cons:
        assert np.all(ms.w_stack[:, j] >= lo - 1e-6)
        assert np.all(ms.w_stack[:, j] <= hi + 1e-6)
    np.testing.assert_allclose(ms.w_stack, md.w_stack, atol=1e-3)

    # an entity NOT observing constrained feature 2 (lo=0.01 > 0) publishes
    # the box projection of zero, exactly like the full-space solve
    hit = False
    for eid, (bi, lane) in cs.buckets.lane_of.items():
        obs = set(cs._proj.projections[bi].indices[lane].tolist()) - {-1}
        if 2 not in obs:
            np.testing.assert_allclose(ms.w_stack[ms.slot_of[eid], 2], 0.01,
                                       atol=1e-6)
            hit = True
    assert hit, "test data unexpectedly has every entity observing feature 2"

    # fused-path parity: one trace_update+publish == one host update
    state = cs.init_sweep_state()
    sdata = cs.sweep_data()
    state, _ = cs.trace_update(state, jnp.zeros(len(y), jnp.float32),
                               data=sdata)
    w_stack = np.asarray(cs.trace_publish(state, data=sdata))
    np.testing.assert_allclose(w_stack, ms.w_stack, atol=1e-5)


def test_sparse_re_box_with_factor_normalization_matches_densified():
    """Box constraints COMPOSED with factor-only normalization on a sparse
    shard: original-space bounds divide by each lane's gathered factor rows
    inside the vmapped solve (game/coordinate._one), which must agree with
    the densified IDENTITY path, where the full-space bounds divide by the
    full factor vector at build time (_box_from_constraints)."""
    from photon_ml_tpu.core.normalization import NormalizationContext
    import jax.numpy as jnp

    idx, vals, dense, uids, y, d = _sparse_re_data(n=512, d=256, n_users=16)
    cons = ((0, -0.05, 0.05), (3, 0.02, 0.4))
    fac = np.full(d, 0.5, np.float32)
    norm = NormalizationContext(factors=jnp.asarray(fac), shifts=None)
    cs_n, _ = _re_coordinate(SparseShard(indices=idx, values=vals, dim=d),
                             uids, y, d, constraints=cons, norm=norm)
    cd_n, _ = _re_coordinate(dense, uids, y, d, constraints=cons, norm=norm)
    off = np.zeros(len(y), np.float32)
    ms_n, _ = cs_n.update(off)
    md_n, _ = cd_n.update(off)
    for j, lo, hi in cons:
        assert np.all(ms_n.w_stack[:, j] >= lo - 1e-6)
        assert np.all(ms_n.w_stack[:, j] <= hi + 1e-6)
    # (no unnormalized comparison: L2 applies in TRANSFORMED space —
    # λ‖w/f‖² — so normalization legitimately moves the optimum; parity
    # target is the densified fit under the SAME context, as everywhere)
    np.testing.assert_allclose(ms_n.w_stack, md_n.w_stack, atol=1e-3)
    # with factor 0.5 the effective penalty is 4λ: bounded features must
    # still reach a binding bound somewhere or the box wasn't applied
    assert np.any(np.isclose(ms_n.w_stack[:, 0], -0.05, atol=1e-5) |
                  np.isclose(ms_n.w_stack[:, 0], 0.05, atol=1e-5))


@pytest.mark.parametrize("kind", ["SIMPLE", "FULL"])
def test_sparse_re_variances_exact_under_compaction(kind):
    """Variances under compaction are EXACT for both kinds: the full-space
    Hessian is block-diagonal (unobserved columns are identically zero for
    the entity), so observed features match the densified IDENTITY
    computation — SIMPLE from the compact diag, FULL from the compact
    Cholesky — and unobserved features carry the prior-only curvature
    1/λ2 — on the host path AND through the fused program."""
    from photon_ml_tpu.types import VarianceComputationType

    idx, vals, dense, uids, y, d = _sparse_re_data(n=512, d=256, n_users=16)
    l2 = 2.5
    def coord(features):
        from photon_ml_tpu.game.config import RandomEffectConfig
        from photon_ml_tpu.game.coordinate import build_coordinate
        from photon_ml_tpu.game.data import GameData
        from photon_ml_tpu.opt.types import SolverConfig

        cfg = RandomEffectConfig(random_effect_type="userId",
                                 feature_shard="u",
                                 solver=SolverConfig(max_iters=25),
                                 reg=Regularization(l2=l2),
                                 variance=VarianceComputationType[kind])
        gd = GameData(y=y, features={"u": features}, id_tags={"userId": uids})
        return build_coordinate("u", gd, cfg, TaskType.LOGISTIC_REGRESSION)

    cs = coord(SparseShard(indices=idx, values=vals, dim=d))
    cd = coord(dense)
    off = np.zeros(len(y), np.float32)
    ms, _ = cs.update(off)
    md, _ = cd.update(off)
    np.testing.assert_allclose(ms.w_stack, md.w_stack, atol=5e-4)
    assert ms.variances is not None and ms.variances.shape == md.variances.shape
    np.testing.assert_allclose(ms.variances, md.variances, rtol=2e-3)
    # unobserved features really are prior-only
    eid0 = sorted(cs.buckets.lane_of)[0]
    bi, lane = cs.buckets.lane_of[eid0]
    obs = set(cs._proj.projections[bi].indices[lane].tolist()) - {-1}
    unobs = [j for j in range(d) if j not in obs][:5]
    slot = ms.slot_of[eid0]
    np.testing.assert_allclose(ms.variances[slot][unobs], 1.0 / l2, rtol=1e-5)

    # fused program publishes the same variances
    import jax.numpy as jnp
    state = cs.init_sweep_state()
    sdata = cs.sweep_data()
    state, _ = cs.trace_update(state, jnp.zeros(len(y), jnp.float32),
                               data=sdata)
    v = cs.trace_variances(state, jnp.zeros(len(y), jnp.float32), data=sdata)
    v_stack = cs.export_variances(v)
    np.testing.assert_allclose(v_stack, ms.variances, rtol=2e-3)


def test_sparse_re_soa_newton_matches_vmapped(monkeypatch):
    """Narrow COMPACT sparse buckets ride the SoA Newton solver (the gate
    keys on SOLVE-space shapes, not the full vocabulary width) and must
    match the generic vmapped path bit-for-tolerance."""
    idx, vals, dense, uids, y, d = _sparse_re_data(
        seed=9, n=128, d=512, k=2, n_users=16)
    sh = SparseShard(indices=idx, values=vals, dim=d)
    cs, _ = _re_coordinate(sh, uids, y, d)
    assert cs._use_soa, (
        "narrow compact sparse buckets should gate onto SoA: shapes "
        + str([b.x.shape for b in cs._proj.buckets]))
    off = np.zeros(len(y), np.float32)
    ms, _ = cs.update(off)

    monkeypatch.setenv("PHOTON_DISABLE_SOA_NEWTON", "1")
    cv, _ = _re_coordinate(sh, uids, y, d)
    assert not cv._use_soa
    mv, _ = cv.update(off)
    np.testing.assert_allclose(ms.w_stack, mv.w_stack, atol=5e-4)
    np.testing.assert_allclose(cs.score(ms), cv.score(mv), atol=5e-3)
