"""Sparse feature shards: row-padded COO end-to-end (the huge-vocabulary
path — reference scale story, SURVEY §2.7)."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.index_map import build_index_maps_from_avro
from photon_ml_tpu.data.reader import read_game_data_avro
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.game.config import FixedEffectConfig, GameConfig
from photon_ml_tpu.game.data import SparseShard
from photon_ml_tpu.game.estimator import GameEstimator
from photon_ml_tpu.types import TaskType


def _write(path, n=300, vocab=40, k=5, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=vocab) * 0.7
    records = []
    for i in range(n):
        js = rng.choice(vocab, size=k, replace=False)
        vs = rng.normal(size=k)
        logit = float(vs @ w[js])
        yv = float(rng.random() < 1 / (1 + np.exp(-logit)))
        feats = [{"name": f"f{j}", "term": "", "value": float(v)}
                 for j, v in zip(js, vs)]
        records.append({"uid": i, "response": yv, "label": None,
                        "features": feats, "weight": None, "offset": None,
                        "metadataMap": {}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)


@pytest.fixture(scope="module")
def sparse_setup(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("sp") / "train.avro")
    _write(path)
    imap = build_index_maps_from_avro([path], {"all": []})["all"]
    return path, imap


def test_sparse_load_layout(sparse_setup):
    path, imap = sparse_setup
    data, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    shard = data.features["all"]
    assert isinstance(shard, SparseShard)
    n, d = shard.shape
    assert n == 300 and d == imap.size
    assert shard.indices.shape == shard.values.shape
    assert shard.indices.shape[1] <= 5 + 1  # k features + intercept slot
    # intercept slot present on every row
    ii = imap.intercept_index
    assert np.all(np.any((shard.indices == ii) & (shard.values == 1.0), axis=1))


def test_sparse_dense_margin_parity(sparse_setup):
    path, imap = sparse_setup
    dense, _ = read_game_data_avro([path], {"all": imap})
    sparse, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    w = np.random.default_rng(1).normal(size=imap.size)
    dense_margins = np.asarray(dense.features["all"]) @ w
    sh = sparse.features["all"]
    sparse_margins = np.einsum("nk,nk->n", sh.values, w[sh.indices])
    np.testing.assert_allclose(sparse_margins, dense_margins, rtol=1e-5)


@pytest.mark.parametrize("opt", ["LBFGS", "TRON"])
def test_sparse_dense_solve_parity(sparse_setup, opt):
    """The fixed-effect solve must reach the same optimum either layout."""
    from photon_ml_tpu.types import OptimizerType

    path, imap = sparse_setup
    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(feature_shard="all",
                                   optimizer=OptimizerType[opt],
                                   reg=Regularization(l2=0.1))})
    out = {}
    for mode, sparse_set in (("dense", set()), ("sparse", {"all"})):
        data, _ = read_game_data_avro([path], {"all": imap},
                                      sparse_shards=sparse_set)
        res = GameEstimator().fit(data, [cfg])[0]
        out[mode] = np.asarray(res.model["fixed"].coefficients.means)
    # different computation orders (matmul vs gather/scatter) -> optima agree
    # only to solver-tolerance scale in f32
    np.testing.assert_allclose(out["sparse"], out["dense"], atol=2e-3)


def test_sparse_fallback_records_path(sparse_setup, monkeypatch):
    """The Python-codec fallback builds the same SparseShard."""
    import photon_ml_tpu.data.native_avro as na

    path, imap = sparse_setup
    fast, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    monkeypatch.setattr(na, "_lib", None)
    monkeypatch.setattr(na, "_lib_tried", True)
    slow, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    f, s = fast.features["all"], slow.features["all"]
    assert isinstance(s, SparseShard)
    w = np.random.default_rng(2).normal(size=imap.size)
    np.testing.assert_allclose(np.einsum("nk,nk->n", f.values, w[f.indices]),
                               np.einsum("nk,nk->n", s.values, w[s.indices]),
                               rtol=1e-5)


def test_huge_vocab_memory(tmp_path):
    """100k-feature shard: sparse layout is O(n*k); dense would be 4.8GB at
    this n — the load itself is the test."""
    path = str(tmp_path / "wide.avro")
    n, vocab = 1200, 100_000
    _write(path, n=n, vocab=vocab, k=8, seed=3)
    imap = build_index_maps_from_avro([path], {"all": []})["all"]
    assert imap.size == vocab + 1 or imap.size > 8  # observed features + intercept
    data, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})
    shard = data.features["all"]
    assert isinstance(shard, SparseShard)
    assert shard.values.nbytes < 10 * n * 16  # O(n*k), nowhere near n*d

    cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(feature_shard="all", reg=Regularization(l2=1.0))})
    res = GameEstimator().fit(data, [cfg])[0]
    w = np.asarray(res.model["fixed"].coefficients.means)
    assert w.shape == (shard.dim,) and np.all(np.isfinite(w))


def test_sparse_cli_end_to_end(tmp_path):
    from photon_ml_tpu.cli import score as score_cli
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write(train_path, n=400, vocab=60, seed=4)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=0.1",
        "--sparse-threshold", "10",  # vocab 60 > 10 -> sparse
        "--output-dir", out])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6

    score_out = str(tmp_path / "scores")
    rc = score_cli.run(["--data", train_path, "--model-dir", out,
                        "--output-dir", score_out, "--evaluators", "auc"])
    assert rc == 0
    assert json.load(open(os.path.join(score_out, "metrics.json")))["auc"] > 0.6


def test_sparse_feature_sharded_estimator_parity(sparse_setup):
    """feature.sharded through the estimator on a (data=2, feature=4) mesh:
    blocked-w solve must match the replicated-w solve (the CLI-reachable form
    of the 1M-vocabulary scale path)."""
    import jax

    from photon_ml_tpu.parallel.mesh import make_mesh

    path, imap = sparse_setup
    data, _ = read_game_data_avro([path], {"all": imap}, sparse_shards={"all"})

    def fit(cfg, mesh=None):
        res = GameEstimator(mesh=mesh).fit(data, [cfg])[0]
        return np.asarray(res.model["fixed"].coefficients.means)

    base = FixedEffectConfig(feature_shard="all", reg=Regularization(l2=0.5))
    plain = fit(GameConfig(task=TaskType.LOGISTIC_REGRESSION,
                           coordinates={"fixed": base}))
    mesh = make_mesh(n_data=2, n_feature=4, devices=jax.devices())
    sharded_cfg = GameConfig(task=TaskType.LOGISTIC_REGRESSION, coordinates={
        "fixed": FixedEffectConfig(feature_shard="all", reg=Regularization(l2=0.5),
                                   feature_sharded=True)})
    sharded = fit(sharded_cfg, mesh)
    assert sharded.shape == plain.shape  # padding trimmed
    np.testing.assert_allclose(sharded, plain, atol=2e-3)


def test_sparse_feature_sharded_cli(tmp_path):
    """--mesh feature=4 + feature.sharded=true end-to-end through the CLI."""
    from photon_ml_tpu.cli import train as train_cli

    train_path = str(tmp_path / "train.avro")
    _write(train_path, n=400, vocab=60, seed=5)
    out = str(tmp_path / "out")
    rc = train_cli.run([
        "--train-data", train_path, "--validation-data", train_path,
        "--feature-shards", "all", "--evaluators", "auc",
        "--coordinate",
        "name=fixed,feature.shard=all,reg.weights=0.1,feature.sharded=true",
        "--sparse-threshold", "10",
        "--mesh", "data=2,feature=4",
        "--output-dir", out])
    assert rc == 0
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["validation"]["auc"] > 0.6
