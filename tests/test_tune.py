"""Hyperparameter-tuning tests: GP regression quality, slice sampler, search
convergence on closed-form objectives, GAME tuning end-to-end."""

import numpy as np
import pytest

from photon_ml_tpu.tune import (
    GaussianProcess,
    GaussianProcessSearch,
    Matern52,
    RBF,
    RandomSearch,
    SearchDomain,
    expected_improvement,
    slice_sample,
)
from photon_ml_tpu.tune.search import DomainDim


def test_kernels_psd_and_forms(rng):
    x = rng.normal(size=(20, 3))
    for kern in (RBF(), Matern52()):
        k = kern(x, x)
        np.testing.assert_allclose(k, k.T, atol=1e-12)
        w = np.linalg.eigvalsh(k)
        assert w.min() > -1e-9
        np.testing.assert_allclose(np.diagonal(k), kern.amplitude, rtol=1e-10)
    # RBF closed form on a simple pair
    k = RBF()(np.zeros((1, 1)), np.ones((1, 1)))
    np.testing.assert_allclose(k[0, 0], np.exp(-0.5), rtol=1e-12)


def test_gp_interpolates_smooth_function(rng):
    f = lambda x: np.sin(3 * x[:, 0]) + 0.5 * x[:, 0]
    x = rng.random((25, 1))
    y = f(x)
    gp = GaussianProcess().fit(x, y, seed=1)
    xt = rng.random((50, 1))
    mu, sigma = gp.predict(xt)
    err = np.abs(mu - f(xt))
    assert np.mean(err) < 0.1, np.mean(err)
    # posterior mean interpolates the observations
    mu0, _ = gp.predict(x)
    np.testing.assert_allclose(mu0, y, atol=0.05)


def test_slice_sampler_matches_gaussian(rng):
    logp = lambda x: float(-0.5 * ((x[0] - 2.0) / 1.5) ** 2)
    samples = slice_sample(logp, np.zeros(1), 2000, np.random.default_rng(0), burn_in=50)
    assert abs(samples.mean() - 2.0) < 0.15
    assert abs(samples.std() - 1.5) < 0.2


def test_expected_improvement_properties():
    # lower mean -> higher EI; zero sigma at worse point -> 0 EI
    ei = expected_improvement(np.asarray([0.0, 1.0]), np.asarray([0.5, 0.5]), best=0.5)
    assert ei[0] > ei[1]
    ei0 = expected_improvement(np.asarray([1.0]), np.asarray([1e-15]), best=0.5)
    assert ei0[0] < 1e-10


def test_domain_roundtrip_log_and_linear():
    dom = SearchDomain([
        DomainDim("a", 1e-3, 1e3, log_scale=True),
        DomainDim("b", -2.0, 5.0),
    ])
    u = np.asarray([[0.5, 0.5], [0.0, 0.0], [1.0, 1.0]])
    real = dom.to_real(u)
    np.testing.assert_allclose(real[0], [1.0, 1.5], rtol=1e-10)
    np.testing.assert_allclose(dom.to_unit(real), u, atol=1e-12)


@pytest.mark.parametrize("cls", [RandomSearch, GaussianProcessSearch])
def test_search_finds_minimum(cls):
    dom = SearchDomain([DomainDim("x", 0.0, 1.0), DomainDim("y", 0.0, 1.0)])
    f = lambda p: float((p[0] - 0.3) ** 2 + (p[1] - 0.7) ** 2)
    search = cls(dom, minimize=True, seed=0)
    best, val = search.find(f, n=25)
    assert val < 0.05, (best, val)
    # GP search should do at least as well as pure random with same budget
    if cls is GaussianProcessSearch:
        assert val < 0.02, (best, val)


def test_search_maximize_orientation():
    dom = SearchDomain([DomainDim("x", 0.0, 1.0)])
    f = lambda p: float(-((p[0] - 0.6) ** 2))  # max at 0.6
    search = RandomSearch(dom, minimize=False, seed=1)
    best, val = search.find(f, n=30)
    assert abs(best[0] - 0.6) < 0.1
    assert val <= 0.0


def test_game_tuning_end_to_end(rng):
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import FixedEffectConfig, GameData, GameEstimator
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune import tune_game_model
    from photon_ml_tpu.types import TaskType

    n, d = 400, 8
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-x @ w))).astype(float)
    tr = GameData(y=y[:300], features={"g": x[:300]})
    va = GameData(y=y[300:], features={"g": x[300:]})

    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={"fixed": FixedEffectConfig(
            feature_shard="g", solver=SolverConfig(max_iters=50),
            reg=Regularization(l2=1.0))},
    )
    est = GameEstimator(validation_suite=EvaluationSuite.from_specs(["auc"]))
    best, search, tuned = tune_game_model(est, config, tr, va, n_iterations=4,
                                          mode="bayesian", seed=0)
    assert best.evaluation.values["auc"] > 0.7
    assert len(search.observations) == 5  # prior + 4 iterations
    assert len(tuned) == 5 and best in tuned
    tuned_l2 = best.config.coordinates["fixed"].reg.l2
    assert 1e-4 <= tuned_l2 <= 1e4


def test_multi_iteration_fused_tuning_matches_host(rng):
    """num_outer_iterations > 1 tuning fits run through ONE compiled fused
    program (FusedSweep.run_snapshots) whose per-iteration snapshots are
    exactly the full models host best-model retention compares
    (reference CoordinateDescent.scala:163-167) — fused and host paths must
    agree on the selected model's validation metric."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import (FixedEffectConfig, GameData,
                                    GameEstimator, RandomEffectConfig)
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune.game_tuning import GameEstimatorEvaluationFunction
    from photon_ml_tpu.types import TaskType

    n, d_g, d_u, n_users = 512, 6, 3, 16
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = rng.normal(size=(n, d_u)).astype(np.float32)
    uids = np.repeat(np.arange(n_users), n // n_users)
    wu = rng.normal(size=(n_users, d_u))
    logits = xg @ rng.normal(size=d_g) + np.einsum("nd,nd->n", xu, wu[uids])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    # shuffle so every user appears on BOTH sides of the split — otherwise
    # the validation rows belong to unseen entities, which score 0, and the
    # assertion would never see the random-effect snapshots at all
    perm = rng.permutation(n)
    xg, xu, uids, y = xg[perm], xu[perm], uids[perm], y[perm]
    cut = 384
    tr = GameData(y=y[:cut], features={"g": xg[:cut], "u": xu[:cut]},
                  id_tags={"userId": uids[:cut]})
    va = GameData(y=y[cut:], features={"g": xg[cut:], "u": xu[cut:]},
                  id_tags={"userId": uids[cut:]})
    solver = SolverConfig(max_iters=25, tolerance=1e-7)
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": RandomEffectConfig(random_effect_type="userId",
                                           feature_shard="u", solver=solver,
                                           reg=Regularization(l2=1.0))})
    suite = EvaluationSuite.from_specs(["auc"])
    fn_fused = GameEstimatorEvaluationFunction(
        GameEstimator(validation_suite=suite), config, tr, va, seed=0)
    fn_host = GameEstimatorEvaluationFunction(
        GameEstimator(validation_suite=suite, fused=False), config, tr, va, seed=0)
    for params in ([1.0, 1.0], [10.0, 0.1]):
        v_fused = fn_fused(np.asarray(params))
        v_host = fn_host(np.asarray(params))
        # tolerance: the 128-example validation split quantizes AUC in
        # ~1/(n_pos*n_neg) ≈ 2.4e-4 steps, and fused/host are different
        # float32 XLA programs — allow a few flipped score pairs
        assert abs(v_fused - v_host) < 2e-3, params
    # the fused path really did share one sweep (not the host fallback)
    assert fn_fused._sweep not in (None, False)
    sweep, _, _plan = fn_fused._sweep
    snaps = sweep.run_snapshots()
    assert len(snaps) == 2  # one full model per outer iteration
    assert set(snaps[0].models) == {"fixed", "per-user"}


def test_hyperparameter_serialization_roundtrip():
    """Reference HyperparameterSerialization.configFromJson/priorFromJson:
    LOG variables are declared by base-10 exponent; prior records fill
    missing params from defaults."""
    import numpy as np

    from photon_ml_tpu.tune.serialization import (config_from_json,
                                                  config_to_json,
                                                  prior_from_json)

    # the reference's GameHyperparameterDefaults.configDefault shape
    cfg = """
    { "tuning_mode" : "BAYESIAN",
      "variables" : {
        "global_regularizer" : {"type": "FLOAT", "transform": "LOG",
                                "min": -3, "max": 3},
        "member_regularizer" : {"type": "FLOAT", "min": 0.5, "max": 2.0}
      }
    }"""
    mode, domain = config_from_json(cfg)
    assert mode == "BAYESIAN"
    assert domain.d == 2
    g, m = domain.dims
    assert g.log_scale and np.isclose(g.low, 1e-3) and np.isclose(g.high, 1e3)
    assert not m.log_scale and m.low == 0.5 and m.high == 2.0

    mode2, domain2 = config_from_json(config_to_json(mode, domain))
    assert mode2 == mode
    for a, b in zip(domain.dims, domain2.dims):
        assert a.name == b.name and np.isclose(a.low, b.low) and np.isclose(a.high, b.high)

    priors = prior_from_json(
        '{"records": [{"global_regularizer": "10", "evaluationValue": "0.8"},'
        ' {"member_regularizer": "1.5", "evaluationValue": "0.6"}]}',
        {"global_regularizer": "0.0", "member_regularizer": "1.0"},
        ["global_regularizer", "member_regularizer"])
    np.testing.assert_allclose(priors[0][0], [10.0, 1.0])
    assert priors[0][1] == 0.8
    np.testing.assert_allclose(priors[1][0], [0.0, 1.5])
    assert priors[1][1] == 0.6

    import pytest

    with pytest.raises(ValueError):
        config_from_json('{"tuning_mode": "GRID", "variables": {}}')


def test_tuning_with_json_config_and_priors(tmp_path, rng):
    """tune_game_model honors a serialized search domain + prior records."""
    import numpy as np

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import FixedEffectConfig, GameData, GameEstimator
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune import tune_game_model
    from photon_ml_tpu.tune.serialization import config_from_json
    from photon_ml_tpu.types import TaskType

    n, d = 300, 5
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-x @ w))).astype(float)
    tr = GameData(y=y[:220], features={"g": x[:220]})
    va = GameData(y=y[220:], features={"g": x[220:]})
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={"fixed": FixedEffectConfig(
            feature_shard="g", solver=SolverConfig(max_iters=40),
            reg=Regularization(l2=1.0))})

    mode, domain = config_from_json(
        '{"tuning_mode": "RANDOM", "variables": '
        '{"l2:fixed": {"type": "FLOAT", "transform": "LOG", "min": -2, "max": 2}}}')
    est = GameEstimator(validation_suite=EvaluationSuite.from_specs(["auc"]))
    best, search, tuned = tune_game_model(
        est, config, tr, va, n_iterations=3, mode=mode.lower(), seed=0,
        search_domain=domain,
        prior_observations=[(np.asarray([0.5]), 0.55)])
    # 3 evaluated + 1 prior record + 1 base-config warm prior
    assert len(search.observations) == 5
    assert len(tuned) == 4  # prior observations don't retrain
    for obs in search.observations[2:]:
        assert 1e-2 <= obs.params[0] <= 1e2  # respects the JSON domain
    assert best.evaluation.values["auc"] > 0.6


def test_shrink_search_range():
    """Reference ShrinkSearchRange.getBounds:40-100: GP on priors -> best
    Sobol candidate -> [best-r, best+r] box clamped to the original domain."""
    from photon_ml_tpu.tune.search import DomainDim, SearchDomain
    from photon_ml_tpu.tune.shrink import shrink_search_range

    dom = SearchDomain([DomainDim("l2", 1e-3, 1e3, log_scale=True),
                        DomainDim("b", 0.0, 10.0)])
    # quadratic bowl with minimum at l2=1.0 (unit 0.5), b=2.0 (unit 0.2)
    priors = []
    rng = np.random.default_rng(3)
    for _ in range(25):
        u = rng.random(2)
        p = dom.to_real(u)
        v = (np.log10(p[0])) ** 2 + 0.5 * (p[1] - 2.0) ** 2
        priors.append((p, float(v)))

    shrunk = shrink_search_range(dom, priors, radius=0.2, seed=0)
    l2d, bd = shrunk.dims
    assert l2d.log_scale
    # the shrunk box contains the optimum and is genuinely narrower
    assert l2d.low <= 1.0 <= l2d.high
    assert bd.low <= 2.0 <= bd.high
    assert np.log(l2d.high / l2d.low) < 0.5 * np.log(1e3 / 1e-3)
    assert (bd.high - bd.low) < 0.5 * 10.0
    # clamped inside the original domain
    assert l2d.low >= 1e-3 - 1e-12 and l2d.high <= 1e3 + 1e-9
    assert bd.low >= 0.0 and bd.high <= 10.0

    import pytest

    with pytest.raises(ValueError):
        shrink_search_range(dom, [], radius=0.2)


class _RecordingTuner:
    """Custom tuner for the reflection-loading test."""

    calls = []

    def tune(self, estimator, base_config, data, validation_data, **kwargs):
        _RecordingTuner.calls.append(kwargs)
        return None, None, []


def test_tuner_factory_dispatch():
    """Reference HyperparameterTunerFactory.scala:20-48: tuner by name —
    DUMMY no-op, BUILTIN in-tree, module:Class reflection-loaded."""
    import pytest

    from photon_ml_tpu.tune.factory import (BuiltinTuner, DummyTuner,
                                            tuner_factory)

    assert isinstance(tuner_factory("DUMMY"), DummyTuner)
    assert isinstance(tuner_factory("dummy"), DummyTuner)
    assert isinstance(tuner_factory("BUILTIN"), BuiltinTuner)
    assert isinstance(tuner_factory(""), BuiltinTuner)

    t = tuner_factory("test_tune:_RecordingTuner")
    assert isinstance(t, _RecordingTuner)
    assert t.tune(None, None, None, None, n_iterations=3) == (None, None, [])
    assert _RecordingTuner.calls[-1]["n_iterations"] == 3

    assert DummyTuner().tune(None, None, None, None) == (None, None, [])

    with pytest.raises(ValueError):
        tuner_factory("NOPE")
    with pytest.raises(ValueError):
        tuner_factory("no.such.module:Thing")
    with pytest.raises(ValueError):
        tuner_factory("collections:OrderedDict")  # loads but has no tune()


def test_tuning_warm_start_carries_prior_entities(rng):
    """Bayesian tuning with a warm-start model whose random effect covers an
    entity absent from (or under-bound in) the tuning data: every tuned fit
    — through the shared fused program — publishes that entity unchanged,
    and the carried contribution rides each fit's offsets."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import (FixedEffectConfig, GameData,
                                    GameEstimator, RandomEffectConfig)
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.models.game import GameModel, RandomEffectModel
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune import tune_game_model
    from photon_ml_tpu.types import TaskType

    d_g, d_u = 4, 3
    # entity 7's TWO training rows are under the bound (4) and the prior
    # covers it -> the existing-model filter drops it from training and the
    # prior carries; entities 0/1 train normally (rows placed BEFORE the
    # validation cut so the lower-bound path actually fires)
    uids = np.concatenate([np.zeros(24), np.full(2, 7), np.ones(24)]).astype(np.int64)
    n = len(uids)
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = rng.normal(size=(n, d_u)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    cut = n - 10
    tr = GameData(y=y[:cut], features={"g": xg[:cut], "u": xu[:cut]},
                  id_tags={"userId": uids[:cut]})
    va = GameData(y=y[cut:], features={"g": xg[cut:], "u": xu[cut:]},
                  id_tags={"userId": uids[cut:]})
    solver = SolverConfig(max_iters=15)
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "user": RandomEffectConfig(random_effect_type="userId",
                                       feature_shard="u", solver=solver,
                                       reg=Regularization(l2=1.0),
                                       min_active_samples=4)})
    prior_w = (rng.normal(size=(1, d_u)) * 1.5).astype(np.float32)
    prior = GameModel(models={"user": RandomEffectModel(
        w_stack=prior_w, slot_of={7: 0}, random_effect_type="userId",
        feature_shard="u", task=TaskType.LOGISTIC_REGRESSION)})
    est = GameEstimator(validation_suite=EvaluationSuite.from_specs(["auc"]))
    best, _search, tuned = tune_game_model(
        est, config, tr, va, n_iterations=3, mode="bayesian", seed=0,
        initial_model=prior)
    assert len(tuned) == 4 and best in tuned
    for r in tuned:
        m = r.model["user"]
        assert 7 in m.slot_of
        np.testing.assert_array_equal(m.w_stack[m.slot_of[7]], prior_w[0])


def test_batched_grid_tuning_matches_sequential(rng):
    """evaluate_batch (ONE vmapped FusedSweep.run_grid[_snapshots] over a
    reg grid) must reproduce sequential evaluation: same metrics, same
    recorded models, same order — for both the multi-iteration snapshot
    path and the single-iteration run_grid path; and tune_game_model with
    batch_size>1 keeps the total fit count."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import (FixedEffectConfig, GameData,
                                    GameEstimator, RandomEffectConfig)
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune import tune_game_model
    from photon_ml_tpu.tune.game_tuning import GameEstimatorEvaluationFunction
    from photon_ml_tpu.types import TaskType

    n, d_g, d_u, n_users = 512, 6, 3, 16
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = rng.normal(size=(n, d_u)).astype(np.float32)
    uids = np.repeat(np.arange(n_users), n // n_users)
    wu = rng.normal(size=(n_users, d_u))
    logits = xg @ rng.normal(size=d_g) + np.einsum("nd,nd->n", xu, wu[uids])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    perm = rng.permutation(n)
    xg, xu, uids, y = xg[perm], xu[perm], uids[perm], y[perm]
    cut = 384
    tr = GameData(y=y[:cut], features={"g": xg[:cut], "u": xu[:cut]},
                  id_tags={"userId": uids[:cut]})
    va = GameData(y=y[cut:], features={"g": xg[cut:], "u": xu[cut:]},
                  id_tags={"userId": uids[cut:]})
    solver = SolverConfig(max_iters=25, tolerance=1e-7)
    suite = EvaluationSuite.from_specs(["auc"])
    grid = [np.asarray([1.0, 1.0]), np.asarray([10.0, 0.1]),
            np.asarray([0.2, 5.0])]

    for outer in (2, 1):  # snapshots path and run_grid path
        config = GameConfig(
            task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=outer,
            coordinates={
                "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                           reg=Regularization(l2=1.0)),
                "per-user": RandomEffectConfig(
                    random_effect_type="userId", feature_shard="u",
                    solver=solver, reg=Regularization(l2=1.0))})
        fn_seq = GameEstimatorEvaluationFunction(
            GameEstimator(validation_suite=suite), config, tr, va, seed=0)
        fn_bat = GameEstimatorEvaluationFunction(
            GameEstimator(validation_suite=suite), config, tr, va, seed=0)
        seq = [fn_seq(p) for p in grid]
        bat = fn_bat.evaluate_batch(grid)
        np.testing.assert_allclose(bat, seq, atol=2e-3)
        assert len(fn_bat.results) == len(grid)
        for rs, rb in zip(fn_seq.results, fn_bat.results):
            np.testing.assert_allclose(
                np.asarray(rb.model["fixed"].coefficients.means),
                np.asarray(rs.model["fixed"].coefficients.means), atol=2e-3)
            np.testing.assert_allclose(
                np.asarray(rb.model["per-user"].w_stack),
                np.asarray(rs.model["per-user"].w_stack), atol=2e-3)
        assert fn_bat.fit_seconds > 0 and fn_bat.eval_seconds > 0

    # end-to-end batched tuning: same fit count, search records gp time
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": RandomEffectConfig(
                random_effect_type="userId", feature_shard="u",
                solver=solver, reg=Regularization(l2=1.0))})
    est = GameEstimator(validation_suite=suite)
    best, search, tuned = tune_game_model(est, config, tr, va,
                                          n_iterations=6, mode="bayesian",
                                          seed=0, batch_size=3)
    assert len(tuned) == 7  # prior + 6 tuning fits, batched 3 per round
    assert best in tuned


def test_warmup_precompiles_grid_sizes(rng):
    """warmup(grid_sizes=(q,)) must leave no recorded fits and zeroed phase
    counters while having exercised both the single-fit and q-grid fused
    programs (the bench's batched gp_tune relies on this so no XLA compile
    lands inside its measured window)."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.evaluation import EvaluationSuite
    from photon_ml_tpu.game import (FixedEffectConfig, GameData,
                                    GameEstimator, RandomEffectConfig)
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.tune import tune_game_model
    from photon_ml_tpu.tune.game_tuning import GameEstimatorEvaluationFunction
    from photon_ml_tpu.types import TaskType

    n, d_g, d_u, n_users = 256, 4, 2, 8
    xg = rng.normal(size=(n, d_g)).astype(np.float32)
    xu = rng.normal(size=(n, d_u)).astype(np.float32)
    uids = np.repeat(np.arange(n_users), n // n_users)
    y = (rng.random(n) < 0.5).astype(np.float32)
    cut = 192
    tr = GameData(y=y[:cut], features={"g": xg[:cut], "u": xu[:cut]},
                  id_tags={"userId": uids[:cut]})
    va = GameData(y=y[cut:], features={"g": xg[cut:], "u": xu[cut:]},
                  id_tags={"userId": uids[cut:]})
    solver = SolverConfig(max_iters=10, tolerance=1e-6)
    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION, num_outer_iterations=2,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                       reg=Regularization(l2=1.0)),
            "per-user": RandomEffectConfig(
                random_effect_type="userId", feature_shard="u",
                solver=solver, reg=Regularization(l2=1.0))})
    est = GameEstimator(validation_suite=EvaluationSuite.from_specs(["auc"]))
    fn = GameEstimatorEvaluationFunction(est, config, tr, va, seed=0)
    fn.warmup(grid_sizes=(2,))
    assert fn.results == []
    assert fn.fit_seconds == 0.0 and fn.eval_seconds == 0.0
    # the warmed function then drives a batched search normally
    best, search, tuned = tune_game_model(est, config, tr, va,
                                          n_iterations=4, mode="bayesian",
                                          seed=0, evaluation_function=fn,
                                          batch_size=2)
    assert len(tuned) == 5  # prior + 4 tuning fits
    assert best in tuned
