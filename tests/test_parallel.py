"""Distributed-layer tests on the 8-device virtual CPU mesh.

Reference analog (SURVEY.md §4): distributed-vs-local parity tests
(DistributedOptimizationProblemIntegTest) — here: chip-count invariance
(1-device vs 8-device mesh gives identical solutions) and bucketed-vmap
random effects vs per-entity serial solves.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core import GLMObjective, Regularization, losses
from photon_ml_tpu.core.batch import dense_batch, sparse_batch
from photon_ml_tpu.opt import SolverConfig, make_solver
from photon_ml_tpu.parallel import (
    bucket_by_entity,
    fit_fixed_effect,
    fit_random_effects,
    make_mesh,
    score_random_effects,
)
from photon_ml_tpu.parallel.bucketing import (
    gather_entity_coefficients,
    score_samples,
    stacked_coefficients,
)
from photon_ml_tpu.types import OptimizerType

D = 5


def _problem(rng, n=333):  # deliberately not divisible by 8
    x = rng.normal(size=(n, D))
    w = rng.normal(size=D)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-x @ w))).astype(float)
    wt = rng.random(n) + 0.5
    return dense_batch(x, y, weight=wt), GLMObjective(
        loss=losses.logistic_loss, reg=Regularization(l2=0.2)
    )


def test_fixed_effect_chip_count_invariance(rng, devices):
    batch, obj = _problem(rng)
    mesh1 = make_mesh(n_data=1, devices=devices[:1])
    mesh8 = make_mesh(n_data=8, devices=devices)
    r1 = fit_fixed_effect(obj, batch, jnp.zeros(D), mesh1)
    r8 = fit_fixed_effect(obj, batch, jnp.zeros(D), mesh8)
    np.testing.assert_allclose(r1.value, r8.value, rtol=1e-9)
    np.testing.assert_allclose(r1.w, r8.w, rtol=1e-6, atol=1e-9)
    # and both match the plain single-device solver
    plain = jax.jit(make_solver(obj, OptimizerType.LBFGS))(jnp.zeros(D), batch)
    np.testing.assert_allclose(r8.value, plain.value, rtol=1e-9)


def test_fixed_effect_feature_sharded(rng, devices):
    """Feature-axis (model-parallel) sharding: w lives P('feature'); result
    must match the replicated-w solve bit-for-bit up to reduction order, and
    padding (D=5 over 4 feature shards -> pad to 8) must trim cleanly."""
    batch, obj = _problem(rng, n=160)
    mesh = make_mesh(n_data=2, n_feature=4, devices=devices)
    r = fit_fixed_effect(obj, batch, jnp.zeros(D), mesh, feature_sharded=True)
    assert r.w.shape == (D,)
    plain = jax.jit(make_solver(obj, OptimizerType.LBFGS))(jnp.zeros(D), batch)
    np.testing.assert_allclose(r.value, plain.value, rtol=1e-8)
    np.testing.assert_allclose(r.w, plain.w, rtol=1e-5, atol=1e-8)


def test_fixed_effect_feature_sharded_box_and_norm(rng, devices):
    """Padding must extend box bounds and normalization factors/shifts so
    padded slots stay pinned at zero and real slots keep their semantics."""
    from photon_ml_tpu.core.normalization import NormalizationContext

    batch, _ = _problem(rng, n=96)
    factors = rng.random(D) + 0.5
    shifts = rng.normal(size=D) * 0.1
    obj = GLMObjective(
        loss=losses.logistic_loss, reg=Regularization(l2=0.2),
        norm=NormalizationContext(factors=jnp.asarray(factors), shifts=jnp.asarray(shifts)),
    )
    lo, hi = -jnp.ones(D) * 0.5, jnp.ones(D) * 0.5
    mesh = make_mesh(n_data=2, n_feature=4, devices=devices)  # D=5 pads to 8
    r = fit_fixed_effect(obj, batch, jnp.zeros(D), mesh, box=(lo, hi),
                         feature_sharded=True)
    plain = jax.jit(make_solver(obj, OptimizerType.LBFGS, box=(lo, hi)))(
        jnp.zeros(D), batch)
    assert r.w.shape == (D,)
    np.testing.assert_allclose(r.value, plain.value, rtol=1e-8)
    np.testing.assert_allclose(r.w, plain.w, rtol=1e-5, atol=1e-8)


def _sparse_problem(rng, n=120, d=11, k=3, l2=0.1):
    idx = np.stack([rng.choice(d, size=k, replace=False) for _ in range(n)])
    val = rng.normal(size=(n, k))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-np.einsum(
        "nk,nk->n", val, w[idx])))).astype(float)
    sb = sparse_batch(idx, val, y, dim=d)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=l2))
    return sb, obj, d


@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON])
def test_fixed_effect_feature_sharded_sparse(rng, devices, opt):
    """Sparse + feature-axis sharding (the 1M-feature scale path): global-id
    rows against blocked w must match the replicated-w solve, including the
    d=11-over-4-shards padding trim, for both LBFGS and TRON (hvp path)."""
    sb, obj, d = _sparse_problem(rng)
    mesh = make_mesh(n_data=2, n_feature=4, devices=devices)
    r = fit_fixed_effect(obj, sb, jnp.zeros(d), mesh, optimizer=opt,
                         feature_sharded=True)
    assert r.w.shape == (d,)
    plain = jax.jit(make_solver(obj, opt))(jnp.zeros(d), sb)
    np.testing.assert_allclose(r.value, plain.value, rtol=1e-8)
    np.testing.assert_allclose(r.w, plain.w, rtol=1e-5, atol=1e-8)


def test_fixed_effect_sparse_sharded_chip_count_invariance(rng, devices):
    """Same optimum for sparse x (data, feature) meshes of any shape."""
    sb, obj, d = _sparse_problem(rng, n=96)
    r1 = fit_fixed_effect(obj, sb, jnp.zeros(d),
                          make_mesh(n_data=1, devices=devices[:1]),
                          feature_sharded=True)
    for n_data, n_feature in [(1, 8), (4, 2), (2, 2)]:
        mesh = make_mesh(n_data=n_data, n_feature=n_feature,
                         devices=devices[: n_data * n_feature])
        r = fit_fixed_effect(obj, sb, jnp.zeros(d), mesh, feature_sharded=True)
        np.testing.assert_allclose(r.value, r1.value, rtol=1e-9)
        np.testing.assert_allclose(r.w, r1.w, rtol=1e-6, atol=1e-9)


def test_fixed_effect_feature_sharded_sparse_norm_and_variance(rng, devices):
    """Scaling-only normalization flows through the blocked objective
    (effective coefficients + chain rule at GSPMD level) and SIMPLE
    variances (hessian_diag) match the unsharded computation; shift
    normalization must refuse (it would densify sparse margins)."""
    from photon_ml_tpu.core.normalization import NormalizationContext
    from photon_ml_tpu.opt.solve import compute_variances
    from photon_ml_tpu.parallel.fixed import ShardSparseObjective
    from photon_ml_tpu.parallel.mesh import padded_dim, shard_batch, shard_coefficients
    from photon_ml_tpu.types import VarianceComputationType

    sb, _, d = _sparse_problem(rng)
    factors = jnp.asarray(rng.random(d) + 0.5)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.2),
                       norm=NormalizationContext(factors=factors, shifts=None))
    mesh = make_mesh(n_data=2, n_feature=4, devices=devices)
    r = fit_fixed_effect(obj, sb, jnp.zeros(d), mesh, feature_sharded=True)
    plain = jax.jit(make_solver(obj, OptimizerType.LBFGS))(jnp.zeros(d), sb)
    np.testing.assert_allclose(r.value, plain.value, rtol=1e-8)
    np.testing.assert_allclose(r.w, plain.w, rtol=1e-5, atol=1e-8)

    # SIMPLE variances through the blocked hessian_diag
    d_pad = padded_dim(d, mesh)
    padded_obj = obj.replace(norm=obj.norm.replace(
        factors=jnp.pad(factors, (0, d_pad - d), constant_values=1.0)))
    sm = ShardSparseObjective(padded_obj, mesh, d_pad // mesh.shape["feature"])
    w_sh = shard_coefficients(jnp.asarray(plain.w), mesh)
    b_sh = shard_batch(sb, mesh)
    var = jax.jit(lambda w, b: compute_variances(
        sm, w, b, VarianceComputationType.SIMPLE))(w_sh, b_sh)
    var_plain = compute_variances(obj, plain.w, sb, VarianceComputationType.SIMPLE)
    np.testing.assert_allclose(np.asarray(var)[:d], var_plain, rtol=1e-6)

    # shift normalization refuses loudly
    shifted = GLMObjective(
        loss=losses.logistic_loss,
        norm=NormalizationContext(factors=None, shifts=jnp.zeros(d) + 0.1))
    with pytest.raises(ValueError, match="scaling-only"):
        fit_fixed_effect(shifted, sb, jnp.zeros(d), mesh, feature_sharded=True)


def test_fixed_effect_sparse_sharded(rng, devices):
    n, k = 100, 3
    idx = np.stack([rng.choice(D, size=k, replace=False) for _ in range(n)])
    val = rng.normal(size=(n, k))
    y = (rng.random(n) > 0.5).astype(float)
    sb = sparse_batch(idx, val, y, dim=D)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.1))
    mesh8 = make_mesh(n_data=8, devices=devices)
    r8 = fit_fixed_effect(obj, sb, jnp.zeros(D), mesh8)
    plain = jax.jit(make_solver(obj, OptimizerType.LBFGS))(jnp.zeros(D), sb)
    np.testing.assert_allclose(r8.value, plain.value, rtol=1e-9)
    np.testing.assert_allclose(r8.w, plain.w, rtol=1e-6, atol=1e-9)


def _entity_data(rng, n_entities=13, dim=3):
    sizes = rng.integers(2, 40, size=n_entities)
    rows = []
    eids = []
    for e in range(n_entities):
        xe = rng.normal(size=(sizes[e], dim))
        we = rng.normal(size=dim)
        ye = (rng.random(sizes[e]) < 1.0 / (1.0 + np.exp(-xe @ we))).astype(float)
        rows.append((xe, ye))
        eids.extend([e * 7 + 100] * sizes[e])  # non-contiguous ids
    x = np.concatenate([r[0] for r in rows])
    y = np.concatenate([r[1] for r in rows])
    return np.asarray(eids), x, y


def test_bucketing_layout(rng):
    eids, x, y = _entity_data(rng)
    b = bucket_by_entity(eids, x, y, dtype=np.float64)
    # every real sample appears exactly once across buckets
    all_rows = np.concatenate([bk.rows.ravel() for bk in b.buckets])
    real = all_rows[all_rows >= 0]
    assert sorted(real.tolist()) == list(range(len(eids)))
    # capacities are powers of two and counts fit
    for bk in b.buckets:
        assert bk.capacity & (bk.capacity - 1) == 0
        assert np.all(bk.counts <= bk.capacity)
        # padding slots have weight 0
        pad = bk.rows < 0
        assert np.all(bk.weight[pad] == 0.0)


def test_random_effects_match_serial(rng, devices):
    """Bucketed vmapped solves == per-entity serial solves (reference
    RandomEffectCoordinate semantics)."""
    eids, x, y = _entity_data(rng)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.4))
    cfg = SolverConfig(max_iters=100, tolerance=1e-9)
    mesh = make_mesh(n_data=8, devices=devices)
    b = bucket_by_entity(eids, x, y, lane_multiple=8, dtype=np.float64)
    coeffs, results = fit_random_effects(obj, b, mesh=mesh, config=cfg)
    per_entity = gather_entity_coefficients(coeffs, b)

    solve = make_solver(obj, OptimizerType.LBFGS, cfg)
    dim = x.shape[1]
    for eid in np.unique(eids):
        m = eids == eid
        ref = solve(jnp.zeros(dim), dense_batch(x[m], y[m]))
        np.testing.assert_allclose(per_entity[int(eid)], ref.w, rtol=1e-5, atol=1e-7)


def test_reservoir_cap_deterministic_and_rescaled(rng):
    eids = np.zeros(100, np.int64)
    x = rng.normal(size=(100, 2))
    y = (rng.random(100) > 0.5).astype(float)
    b1 = bucket_by_entity(eids, x, y, active_cap=16, seed=3)
    b2 = bucket_by_entity(eids, x, y, active_cap=16, seed=3)
    np.testing.assert_array_equal(b1.buckets[0].rows, b2.buckets[0].rows)
    bk = b1.buckets[0]
    assert int(bk.counts[0]) == 16
    # weight rescale count/cap = 100/16 (reference RandomEffectDataset.scala:408-417)
    np.testing.assert_allclose(bk.weight[0, :16], 100.0 / 16.0)
    # different seed -> different sample (overwhelmingly likely)
    b3 = bucket_by_entity(eids, x, y, active_cap=16, seed=4)
    assert not np.array_equal(b1.buckets[0].rows, b3.buckets[0].rows)


def test_min_active_samples_filter(rng):
    eids = np.asarray([1, 1, 1, 2, 3, 3], np.int64)
    x = rng.normal(size=(6, 2))
    y = np.ones(6)
    b = bucket_by_entity(eids, x, y, min_active_samples=2)
    assert set(b.lane_of) == {1, 3}
    assert b.num_entities == 2


def test_scoring_roundtrip(rng):
    eids, x, y = _entity_data(rng)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.4))
    b = bucket_by_entity(eids, x, y, dtype=np.float64)
    coeffs, _ = fit_random_effects(obj, b, config=SolverConfig(max_iters=50))
    # bucket-layout scoring == gather-based scoring == manual dot
    s_active = np.asarray(score_random_effects(coeffs, b))
    w_stack, slot_of = stacked_coefficients(coeffs, b)
    slots = np.asarray([slot_of.get(int(e), -1) for e in eids], np.int32)
    s_gather = np.asarray(score_samples(w_stack, jnp.asarray(slots), jnp.asarray(x)))
    np.testing.assert_allclose(s_active, s_gather, rtol=1e-9, atol=1e-12)
    per_entity = gather_entity_coefficients(coeffs, b)
    manual = np.asarray([x[i] @ per_entity[int(eids[i])] for i in range(len(eids))])
    np.testing.assert_allclose(s_gather, manual, rtol=1e-9, atol=1e-12)


def test_score_samples_t_matches_row_layout(rng):
    """[d, n] samples-on-lanes scoring (score_samples_t — the narrow-shard
    HBM-padding fix, 32x at d=4 on TPU tiling) agrees with the [n, d]
    gather layout, including -1 slots and bf16 storage against f32
    coefficients."""
    from photon_ml_tpu.parallel.bucketing import score_samples_t

    n, ne, d = 257, 19, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(ne, d)).astype(np.float32)
    slots = jnp.asarray(rng.integers(-1, ne, size=n).astype(np.int32))
    a = np.asarray(score_samples(jnp.asarray(w), slots, jnp.asarray(x)))
    b = np.asarray(score_samples_t(jnp.asarray(w), slots, jnp.asarray(x.T)))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
    assert (np.asarray(slots) < 0).any() and (b[np.asarray(slots) < 0] == 0).all()
    xb = jnp.asarray(x).astype(jnp.bfloat16)
    a16 = np.asarray(score_samples(jnp.asarray(w), slots, xb))
    b16 = np.asarray(score_samples_t(jnp.asarray(w), slots, xb.T))
    np.testing.assert_allclose(a16, b16, rtol=1e-3, atol=1e-3)


def test_transposed_scoring_gate_is_padded_bytes():
    """The [d, n] layout gate keys on the padded-HBM footprint
    (n x 128 lanes x itemsize), not width alone: glmix2-shaped shards
    (524k x 16 f32, 268 MB padded) measured 1.56x FASTER row-major on the
    v5e while glmix_chip's (8.39M x 4 bf16, 2.1 GB padded) OOMs without
    the transpose (TPU_CHECKLIST.json r5b vs run 1)."""
    from photon_ml_tpu.parallel.bucketing import (
        NARROW_SCORE_PAD_BYTES_MIN, use_transposed_scoring)

    assert not use_transposed_scoring(524_288, 16, 4)   # glmix2: row-major
    assert use_transposed_scoring(8_388_608, 4, 2)      # glmix_chip: [d, n]
    assert not use_transposed_scoring(8_388_608, 64, 2)  # wide: never
    n_edge = NARROW_SCORE_PAD_BYTES_MIN // (128 * 4)
    assert use_transposed_scoring(n_edge, 4, 4)
    assert not use_transposed_scoring(n_edge - 1, 4, 4)


def test_scoring_unknown_entity_is_zero(rng):
    eids, x, y = _entity_data(rng, n_entities=3)
    obj = GLMObjective(loss=losses.logistic_loss)
    b = bucket_by_entity(eids, x, y, dtype=np.float64)
    coeffs, _ = fit_random_effects(obj, b, config=SolverConfig(max_iters=20))
    w_stack, slot_of = stacked_coefficients(coeffs, b)
    slots = jnp.asarray([-1, 0], jnp.int32)
    s = score_samples(w_stack, slots, jnp.asarray(np.ones((2, x.shape[1]))))
    assert float(s[0]) == 0.0


def test_fused_sweep_on_mesh_matches_single_device(devices, rng):
    """FusedSweep under an 8-device mesh == FusedSweep single-device
    (chip-count invariance for the fully-jitted descent program)."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    n_users, per_user, dg, du = 16, 32, 6, 3
    n = n_users * per_user
    xg = rng.normal(size=(n, dg))
    xu = rng.normal(size=(n, du))
    uids = np.repeat(np.arange(n_users), per_user)
    y = (rng.random(n) < 0.5).astype(float)
    data = GameData(y=y, features={"g": xg, "u": xu}, id_tags={"userId": uids})
    solver = SolverConfig(max_iters=40, tolerance=1e-9)
    task = TaskType.LOGISTIC_REGRESSION
    cfgs = {
        "fixed": FixedEffectConfig(feature_shard="g", solver=solver,
                                   reg=Regularization(l2=1.0)),
        "user": RandomEffectConfig(random_effect_type="userId",
                                   feature_shard="u", solver=solver,
                                   reg=Regularization(l2=1.0)),
    }

    models = {}
    for label, mesh in (("one", make_mesh(n_data=1, devices=devices[:1])),
                        ("eight", make_mesh(n_data=8, devices=devices))):
        coords = {cid: build_coordinate(cid, data, c, task, mesh=mesh)
                  for cid, c in cfgs.items()}
        m, _ = FusedSweep(coords, num_iterations=2).run()
        models[label] = m

    # psum/reduction order differs across device counts: f32 noise only
    np.testing.assert_allclose(models["one"]["fixed"].coefficients.means,
                               models["eight"]["fixed"].coefficients.means,
                               rtol=2e-3, atol=2e-4)
    assert models["one"]["user"].slot_of == models["eight"]["user"].slot_of
    np.testing.assert_allclose(models["one"]["user"].w_stack,
                               models["eight"]["user"].w_stack,
                               rtol=2e-3, atol=2e-4)


def test_variance_on_mesh_matches_single_device(devices, rng):
    """ShardMapObjective hessian_diag/hessian: variances computed under an
    8-device mesh equal the single-device ones (the L2 term must be added
    once, not once per shard)."""
    import dataclasses

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType, VarianceComputationType

    n, d = 512, 6
    x = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    data = GameData(y=y, features={"g": x})
    for kind in (VarianceComputationType.SIMPLE, VarianceComputationType.FULL):
        cfg = FixedEffectConfig(feature_shard="g",
                                solver=SolverConfig(max_iters=40),
                                reg=Regularization(l2=2.0), variance=kind)
        got = {}
        for label, mesh in (("one", make_mesh(n_data=1, devices=devices[:1])),
                            ("eight", make_mesh(n_data=8, devices=devices))):
            coord = build_coordinate("fixed", data, cfg, TaskType.LOGISTIC_REGRESSION,
                                     mesh=mesh)
            model, _ = coord.update(np.zeros(n))
            assert model.coefficients.variances is not None
            got[label] = model.coefficients.variances
        np.testing.assert_allclose(got["one"], got["eight"], rtol=1e-3, atol=1e-6)


def test_multihost_helpers_single_process(devices):
    """Multi-host helpers in the 1-process degenerate case: row ranges
    tile the dataset, the global mesh covers all devices, and
    host-local -> global assembly yields correctly sharded arrays whose
    psum matches the local computation."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from photon_ml_tpu.parallel.multihost import (global_batch_from_local,
                                                  global_mesh,
                                                  initialize,
                                                  pad_local_rows,
                                                  padded_per_host_rows,
                                                  process_row_range)

    initialize(num_processes=1)  # explicit single-process no-op
    initialize()  # auto-detect falls back to single-process, never raises

    # row split math for a hypothetical 3-host job (ceil split: 35/35/33)
    n = 103
    ranges = [process_row_range(n, pid, 3) for pid in range(3)]
    assert ranges == [(0, 35), (35, 70), (70, 103)]
    assert all(b - a <= 35 for a, b in ranges)
    with pytest.raises(ValueError):
        process_row_range(n, 5, 3)

    mesh = global_mesh(n_feature=2)
    assert mesh.shape["data"] * mesh.shape["feature"] == len(jax.devices())
    with pytest.raises(ValueError):
        global_mesh(n_entity=3)  # 8 not divisible

    # this process owns ALL rows in a 1-process job
    start, stop = process_row_range(n)
    assert (start, stop) == (0, n)

    # balanced padded rows: 103 rows over the 4-device data axis -> 104
    rows = padded_per_host_rows(n, mesh)
    assert rows == 104 and rows % mesh.shape["data"] == 0

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 6))
    y = rng.normal(size=n)
    w = np.ones(n)
    block = pad_local_rows({"x": x, "y": y, "weight": w}, rows)
    assert block["x"].shape == (rows, 6)
    assert block["weight"][n:].sum() == 0  # padding rows are weight-0
    with pytest.raises(ValueError):
        pad_local_rows({"x": x}, n - 1)

    g = global_batch_from_local(block, mesh,
                                specs={"x": P("data", "feature")})
    assert g["x"].shape == (rows, 6) and g["y"].shape == (rows,)
    assert g["x"].sharding.spec == P("data", "feature")
    assert g["y"].sharding.spec == P("data")
    x, y = block["x"], block["y"]

    # a jitted global reduction over the sharded arrays matches numpy
    total = jax.jit(lambda xx, yy: (xx.sum(), (xx.T @ yy)))(g["x"], g["y"])
    np.testing.assert_allclose(np.asarray(total[0]), x.sum(), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(total[1]), x.T @ y, rtol=1e-6)


@pytest.mark.parametrize("variant", ["bf16_storage", "projected"])
def test_fused_sweep_mesh_invariance_new_features(devices, rng, variant):
    """Chip-count invariance extends to the newer fused features: bf16
    design-matrix storage (mixed precision) and projected random effects —
    1-device vs 8-device meshes must agree up to reduction-order noise."""
    import dataclasses

    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.fused import FusedSweep
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import ProjectorType, TaskType

    n_users, per_user, dg, du = 16, 32, 6, 3
    n = n_users * per_user
    xg = rng.normal(size=(n, dg))
    xu = rng.normal(size=(n, du))
    uids = np.repeat(np.arange(n_users), per_user)
    y = (rng.random(n) < 0.5).astype(float)
    data = GameData(y=y, features={"g": xg, "u": xu}, id_tags={"userId": uids})
    solver = SolverConfig(max_iters=30, tolerance=1e-8)
    task = TaskType.LOGISTIC_REGRESSION
    fixed = FixedEffectConfig(feature_shard="g", solver=solver,
                              reg=Regularization(l2=1.0))
    user = RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                              solver=solver, reg=Regularization(l2=1.0))
    if variant == "bf16_storage":
        fixed = dataclasses.replace(fixed, storage_dtype="bfloat16")
        user = dataclasses.replace(user, storage_dtype="bfloat16")
        tol = dict(rtol=3e-2, atol=3e-2)  # bf16 input resolution
    else:
        user = dataclasses.replace(user, projector=ProjectorType.INDEX_MAP)
        tol = dict(rtol=2e-3, atol=2e-4)
    cfgs = {"fixed": fixed, "user": user}

    models = {}
    for label, mesh in (("one", make_mesh(n_data=1, devices=devices[:1])),
                        ("eight", make_mesh(n_data=8, devices=devices))):
        coords = {cid: build_coordinate(cid, data, c, task, mesh=mesh)
                  for cid, c in cfgs.items()}
        m, _ = FusedSweep(coords, num_iterations=2).run()
        models[label] = m

    np.testing.assert_allclose(models["one"]["fixed"].coefficients.means,
                               models["eight"]["fixed"].coefficients.means,
                               **tol)
    assert models["one"]["user"].slot_of == models["eight"]["user"].slot_of
    np.testing.assert_allclose(models["one"]["user"].w_stack,
                               models["eight"]["user"].w_stack, **tol)


def test_multihost_two_processes(tmp_path):
    """TRUE multi-process jax.distributed: 2 processes x 2 CPU devices form a
    4-device global mesh; each host reads only its row range, assembles the
    global batch, and runs the SAME shard_map fixed-effect solve.  Both
    processes must publish the identical replicated optimum, matching a
    single-process solve of the full data (the reference's Spark-cluster
    execution model, SURVEY §5, with no driver process)."""
    import json
    import os

    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {repr(os.getcwd())})
import os, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); out = sys.argv[3]
from photon_ml_tpu.parallel import multihost as mh
mh.initialize(coordinator_address="127.0.0.1:{port}",
              num_processes=nproc, process_id=pid,
              expected_processes=nproc)
assert jax.process_count() == nproc
mesh = mh.global_mesh(n_feature=2)
# ICI/DCN contract: entity/feature axes never cross a process boundary —
# every (entity, feature) cell of the mesh lives inside ONE process
for row in mesh.devices.reshape(mesh.devices.shape[0], -1):
    assert len({{d.process_index for d in row}}) == 1, "feature axis crossed DCN"
# and the data axis DOES span processes (it is the only DCN axis)
assert len({{d.process_index for d in mesh.devices.reshape(-1)}}) == nproc

n, d = 64, 3
rng = np.random.default_rng(0)           # same data on every host
x = rng.normal(size=(n, d)).astype(np.float32)
w_true = np.asarray([0.5, -1.0, 0.25], np.float32)
y = (rng.random(n) < 1 / (1 + np.exp(-x @ w_true))).astype(np.float32)

start, stop = mh.process_row_range(n)
rows = mh.padded_per_host_rows(n, mesh)
block = mh.pad_local_rows(
    dict(x=x[start:stop], y=y[start:stop],
         offset=np.zeros(stop - start, np.float32),
         weight=np.ones(stop - start, np.float32)), rows)
g = mh.global_batch_from_local(block, mesh)

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import logistic_loss
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.parallel.fixed import ShardMapObjective
from photon_ml_tpu.parallel.mesh import replicate

batch = DenseBatch(x=g["x"], y=g["y"], offset=g["offset"], weight=g["weight"])
obj = ShardMapObjective(
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.1)), mesh)
solve = jax.jit(make_solver(obj, config=SolverConfig(max_iters=50)),
                out_shardings=replicate(mesh))
res = solve(jax.numpy.zeros(d, jax.numpy.float32), batch)
w = np.asarray(res.w)

# part 2 (VERDICT r3 #8): a FEATURE-SHARDED sparse solve on the same
# global mesh — w blocked over the within-process feature axis (ICI
# collectives), data striding processes (the one DCN all-reduce).  The
# ICI/DCN tiering is thereby EXECUTED cross-process, not just asserted
# on the mesh layout above.
d2, k2 = 9, 3   # d2 odd: the feature axis pads to 10 and trims on exit
rng3 = np.random.default_rng(1)
idx2 = rng3.integers(0, d2, size=(n, k2)).astype(np.int32)
vals2 = rng3.normal(size=(n, k2)).astype(np.float32)
w2_true = rng3.normal(size=d2).astype(np.float32)
z2 = np.einsum("nk,nk->n", vals2, w2_true[idx2])
y2 = (rng3.random(n) < 1 / (1 + np.exp(-z2))).astype(np.float32)
block2 = mh.pad_local_rows(
    dict(indices=idx2[start:stop], values=vals2[start:stop],
         y=y2[start:stop], offset=np.zeros(stop - start, np.float32),
         weight=np.ones(stop - start, np.float32)), rows)
g2 = mh.global_batch_from_local(block2, mesh)
from photon_ml_tpu.core.batch import SparseBatch
from photon_ml_tpu.parallel.fixed import fit_fixed_effect

sb = SparseBatch(indices=g2["indices"], values=g2["values"], y=g2["y"],
                 offset=g2["offset"], weight=g2["weight"], dim=d2)
res2 = fit_fixed_effect(
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.1)), sb,
    np.zeros(d2, np.float32), mesh, config=SolverConfig(max_iters=50),
    feature_sharded=True, batch_presharded=True)
w2 = np.asarray(res2.w)
assert w2.shape == (d2,)

with open(os.path.join(out, f"w{{pid}}.json"), "w") as f:
    json.dump({{"w": [float(v) for v in w],
               "w2": [float(v) for v in w2]}}, f)
""")

    _launch_workers(worker, 2, tmp_path, timeout=240)

    out0 = json.load(open(tmp_path / "w0.json"))
    out1 = json.load(open(tmp_path / "w1.json"))
    np.testing.assert_allclose(out0["w"], out1["w"], rtol=0, atol=0)
    np.testing.assert_allclose(out0["w2"], out1["w2"], rtol=0, atol=0)
    w0, w2 = out0["w"], out0["w2"]

    # reference: the same solves single-process on the full data
    from photon_ml_tpu.core.batch import dense_batch, sparse_batch
    from photon_ml_tpu.core.losses import logistic_loss
    from photon_ml_tpu.core.objective import GLMObjective
    from photon_ml_tpu.opt.solve import make_solver
    from photon_ml_tpu.opt.types import SolverConfig

    n, d = 64, 3
    rng2 = np.random.default_rng(0)
    x = rng2.normal(size=(n, d)).astype(np.float32)
    w_true = np.asarray([0.5, -1.0, 0.25], np.float32)
    y = (rng2.random(n) < 1 / (1 + np.exp(-x @ w_true))).astype(np.float32)
    obj = GLMObjective(loss=losses.logistic_loss,
                       reg=Regularization(l2=0.1))
    res = jax.jit(make_solver(obj, config=SolverConfig(max_iters=50)))(
        jnp.zeros(d), dense_batch(x.astype(np.float64), y.astype(np.float64)))
    np.testing.assert_allclose(w0, np.asarray(res.w), rtol=2e-3, atol=2e-4)

    # the cross-process feature-sharded sparse solve matches single-process
    d2, k2 = 9, 3
    rng3 = np.random.default_rng(1)
    idx2 = rng3.integers(0, d2, size=(n, k2)).astype(np.int32)
    vals2 = rng3.normal(size=(n, k2)).astype(np.float32)
    w2_true = rng3.normal(size=d2).astype(np.float32)
    z2 = np.einsum("nk,nk->n", vals2, w2_true[idx2])
    y2 = (rng3.random(n) < 1 / (1 + np.exp(-z2))).astype(np.float32)
    res2 = jax.jit(make_solver(obj, config=SolverConfig(max_iters=50)))(
        jnp.zeros(d2), sparse_batch(idx2, vals2, y2, dim=d2))
    np.testing.assert_allclose(w2, np.asarray(res2.w), rtol=2e-3, atol=2e-3)


def test_global_feature_stats_on_sharded_rows(devices, rng):
    """The multihost normalization recipe: each host pads its local rows
    (weight 0) and assembles a globally data-sharded array; a jitted
    compute_feature_stats over it equals the stats of the raw unpadded rows
    on every host — GSPMD inserts the cross-host moment reductions (the
    'sharded variant psums the moments' contract in core/normalization)."""
    from photon_ml_tpu.core.normalization import compute_feature_stats
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.multihost import (global_batch_from_local,
                                                  pad_local_rows)

    n, d = 100, 6  # deliberately NOT divisible by the mesh
    x = rng.normal(size=(n, d)).astype(np.float64) * np.linspace(0.5, 3, d)
    w = rng.random(n).astype(np.float64) + 0.5
    mesh = make_mesh(n_data=8, devices=devices[:8])
    rows = -(-n // 8) * 8
    local = pad_local_rows({"x": x, "weight": w}, rows)
    g = global_batch_from_local(local, mesh)

    stats_sharded = jax.jit(compute_feature_stats)(g["x"], g["weight"])
    stats_host = compute_feature_stats(jnp.asarray(x), jnp.asarray(w))
    for f in ("mean", "variance", "abs_max"):
        np.testing.assert_allclose(np.asarray(getattr(stats_sharded, f)),
                                   np.asarray(getattr(stats_host, f)),
                                   rtol=1e-10, err_msg=f)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_workers(worker, nproc, tmp_path, local_devices=2, timeout=420):
    """Run ``worker`` as nproc jax.distributed processes (argv: pid nproc
    tmp_path) and assert they all exit 0 — the ONE definition of the
    multi-process launch contract (env, device count, failure reporting).
    Skips (not fails) on backends that cannot execute cross-process
    computations at all (conftest capability probe)."""
    import os
    import subprocess
    import sys

    from conftest import require_multiprocess_backend

    require_multiprocess_backend()

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={local_devices}")
    env.pop("PYTEST_CURRENT_TEST", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(nproc), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(nproc)]
    outs = [p.communicate(timeout=timeout) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{se[-3000:]}"


# --- multihost GLMix (fixed + random effects across processes) -------------

_GLMIX_DATAGEN = """
rng = np.random.default_rng(42)
n, n_users, dg, du = {n}, 16, 4, 2
uids = rng.integers(0, n_users, size=n)
xg = rng.normal(size=(n, dg)).astype(np.float32)
xu = rng.normal(size=(n, du)).astype(np.float32)
uw = (rng.normal(size=(n_users, du)) * 1.2).astype(np.float32)
gw = rng.normal(size=dg).astype(np.float32)
z = xg @ gw + np.einsum("nd,nd->n", xu, uw[uids])
y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
"""

_GLMIX_WORKER = """
import sys
sys.path.insert(0, {repo!r})
import os, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); out = sys.argv[3]
from photon_ml_tpu.parallel import multihost as mh
mh.initialize(coordinator_address="127.0.0.1:{port}", num_processes=nproc,
              process_id=pid, expected_processes=nproc)
mesh = mh.global_mesh(n_entity={n_entity})
# entity/feature cells never cross a process (ICI); data strides DCN
for row in mesh.devices.reshape(mesh.devices.shape[0], -1):
    assert len({{d.process_index for d in row}}) == 1, "entity axis crossed DCN"
{datagen}
from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import logistic_loss
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.parallel.bucketing import bucket_by_entity

# fixed side: row-range read (last host short; padding rows weight 0)
start, stop = mh.process_row_range(n)
rows_per = mh.padded_per_host_rows(n, mesh)
blk = mh.pad_local_rows(dict(x=xg[start:stop], y=y[start:stop],
                             offset=np.zeros(stop - start, np.float32),
                             weight=np.ones(stop - start, np.float32)),
                        rows_per)
g = mh.global_batch_from_local(blk, mesh)
fixed_batch = DenseBatch(x=g["x"], y=g["y"], offset=g["offset"],
                         weight=g["weight"])

# random-effect side: entity-hash ownership, host-local bucketing with
# GLOBAL row ids, global lane assembly
rid = mh.local_entity_rows(uids)
assert len(rid) > 0, "hash split starved a host of entities"
n_glob = rows_per * nproc
w1 = np.ones(len(rid), np.float32)
local = bucket_by_entity(uids[rid], xu[rid], y[rid], weight=w1,
                         active_cap=16, seed=5, row_ids=rid,
                         num_samples=n_glob)
gb = mh.global_entity_buckets(local, mesh)
ls = bucket_by_entity(uids[rid], xu[rid], y[rid], weight=w1, seed=5,
                      row_ids=rid, num_samples=n_glob)
scoring = mh.build_re_scoring(gb, ls, mesh)

cfg = SolverConfig(max_iters=60, tolerance=1e-9)
wf, rec, _ = mh.multihost_glmix_sweep(
    mesh, fixed_batch, gb,
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.1)),
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=1.0)),
    num_iterations=2, config=cfg, re_scoring=scoring, num_samples=n)
exported = mh.export_local_random_effects(rec, gb, mesh)
with open(os.path.join(out, f"glmix{{pid}}.json"), "w") as f:
    json.dump({{"wf": [float(v) for v in np.asarray(wf)],
               "re": {{str(k): [float(v) for v in w]
                      for k, w in exported.items()}},
               "n_owned_rows": int(len(rid)),
               "row_space_misaligned": bool(rows_per != -(-n // nproc))}}, f)
"""


def _glmix_reference(n=503, active_cap=16):
    """Single-process framework solve of the same problem (same kept rows:
    reservoir keys mix global row ids, so topology cannot change them)."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.types import TaskType

    ns = {"np": np}
    exec(_GLMIX_DATAGEN.format(n=n), ns)
    data = GameData(y=ns["y"], features={"g": ns["xg"], "u": ns["xu"]},
                    id_tags={"userId": ns["uids"]})
    cfg = SolverConfig(max_iters=60, tolerance=1e-9)
    coords = {
        "fixed": build_coordinate(
            "fixed", data,
            FixedEffectConfig(feature_shard="g", solver=cfg,
                              reg=Regularization(l2=0.1)),
            TaskType.LOGISTIC_REGRESSION, seed=5),
        "user": build_coordinate(
            "user", data,
            RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                               solver=cfg, reg=Regularization(l2=1.0),
                               active_cap=active_cap),
            TaskType.LOGISTIC_REGRESSION, seed=5),
    }
    model, _, _ = CoordinateDescent(coords, order=["fixed", "user"],
                                    num_iterations=2).run(seed=5)
    return model


def _run_glmix_workers(tmp_path, nproc, local_devices, n_entity, n=503):
    import json
    import os

    worker = tmp_path / "glmix_worker.py"
    worker.write_text(_GLMIX_WORKER.format(
        repo=os.getcwd(), port=_free_port(), n_entity=n_entity,
        datagen=_GLMIX_DATAGEN.format(n=n)))
    _launch_workers(worker, nproc, tmp_path, local_devices=local_devices)
    return [json.load(open(tmp_path / f"glmix{pid}.json"))
            for pid in range(nproc)]


def _check_glmix_outputs(outs, nproc, n=503):
    """Replicated fixed coefficients agree bitwise across hosts; the union
    of per-host published random effects matches the single-process
    framework solve to solver tolerance."""
    for o in outs[1:]:
        np.testing.assert_allclose(outs[0]["wf"], o["wf"], rtol=0, atol=0)
    # every entity published by exactly one host
    owners = [set(o["re"]) for o in outs]
    for i in range(nproc):
        for j in range(i + 1, nproc):
            assert not owners[i] & owners[j], "entity published twice"
    merged = {int(k): np.asarray(v) for o in outs for k, v in o["re"].items()}

    model = _glmix_reference(n=n)
    wf_ref = np.asarray(model["fixed"].coefficients.means)
    np.testing.assert_allclose(outs[0]["wf"], wf_ref, atol=5e-4, rtol=1e-3)
    re_ref = model["user"]
    assert set(merged) == set(re_ref.slot_of)
    for eid, w in merged.items():
        np.testing.assert_allclose(
            w, np.asarray(re_ref.w_stack[re_ref.slot_of[eid]]),
            atol=5e-4, rtol=1e-3)


def test_multihost_glmix_two_processes(tmp_path):
    """TRUE 2-process GLMix: entity-sharded random effects + row-sharded
    fixed effect, residual descent with global score vectors; published
    model matches the single-process CoordinateDescent solve.  n=503 leaves
    the last host a SHORT row range — the weight-0 padding contract is
    exercised, not just asserted."""
    outs = _run_glmix_workers(tmp_path, nproc=2, local_devices=2, n_entity=1)
    assert sum(o["n_owned_rows"] for o in outs) == 503
    _check_glmix_outputs(outs, 2)


def test_multihost_glmix_four_processes(tmp_path):
    """4-process GLMix sweep on a (data=4, entity=2) global mesh: the data
    axis strides DCN (4 processes), the entity axis stays on ICI (within
    each process's 2 devices) — the 2x2 interconnect tiering of SURVEY §5
    executed, with the same single-process parity gate."""
    outs = _run_glmix_workers(tmp_path, nproc=4, local_devices=2, n_entity=2)
    assert sum(o["n_owned_rows"] for o in outs) == 503
    _check_glmix_outputs(outs, 4)


def test_multihost_glmix_padded_row_space(tmp_path):
    """Original-vs-padded row-space translation: n=57 over 2 hosts gives
    per-host stride 29 but a padded stride of 30 (2 data devices per host),
    so every bucket-row gather/scatter must translate ids — the silent
    misalignment a size-aligned test can never catch."""
    outs = _run_glmix_workers(tmp_path, nproc=2, local_devices=2, n_entity=1,
                              n=57)
    assert outs[0]["row_space_misaligned"], (
        "test sizes drifted back into alignment; pick n so that "
        "ceil(n/nproc) is not a multiple of the per-host data-device count")
    assert sum(o["n_owned_rows"] for o in outs) == 57
    _check_glmix_outputs(outs, 2, n=57)


def test_multihost_glmix_sparse_compact_two_processes(tmp_path):
    """Wide-vocabulary multihost random effects: sparse (compact,
    observed-column) buckets built per host, compact widths aligned by the
    metadata all-gather, solved in the global sweep, back-projected
    host-locally on export — the multihost twin of the single-process
    sparse coordinate.  Parity vs the single-process framework solve."""
    import json
    import os

    port = _free_port()
    worker = tmp_path / "glmix_sparse_worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {os.getcwd()!r})
import os, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); out = sys.argv[3]
from photon_ml_tpu.parallel import multihost as mh
from photon_ml_tpu.parallel.bucketing import bucket_by_entity_sparse
mh.initialize(coordinator_address="127.0.0.1:{port}", num_processes=nproc,
              process_id=pid, expected_processes=nproc)
mesh = mh.global_mesh()

rng = np.random.default_rng(77)
n, n_users, dg, du, ku = 480, 12, 4, 64, 3
uids = rng.integers(0, n_users, size=n)
xg = rng.normal(size=(n, dg)).astype(np.float32)
idx_u = rng.integers(0, du, size=(n, ku)).astype(np.int32)
vals_u = rng.normal(size=(n, ku)).astype(np.float32)
uw = rng.normal(size=(n_users, du)).astype(np.float32)
gw = rng.normal(size=dg).astype(np.float32)
z = xg @ gw + np.einsum("nk,nk->n", vals_u, uw[uids[:, None], idx_u])
y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import logistic_loss
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.opt.types import SolverConfig

start, stop = mh.process_row_range(n)
rows_per = mh.padded_per_host_rows(n, mesh)
blk = mh.pad_local_rows(dict(x=xg[start:stop], y=y[start:stop],
                             offset=np.zeros(stop - start, np.float32),
                             weight=np.ones(stop - start, np.float32)),
                        rows_per)
g = mh.global_batch_from_local(blk, mesh)
fixed_batch = DenseBatch(x=g["x"], y=g["y"], offset=g["offset"],
                         weight=g["weight"])

rid = mh.local_entity_rows(uids)
assert len(rid) > 0
local, projs = bucket_by_entity_sparse(
    uids[rid], idx_u[rid], vals_u[rid], du, y[rid],
    weight=np.ones(len(rid), np.float32), seed=5,
    row_ids=rid, num_samples=rows_per * nproc)
gb, pp = mh.global_entity_buckets(local, mesh, projections=projs)

cfg = SolverConfig(max_iters=60, tolerance=1e-9)
wf, rec, _ = mh.multihost_glmix_sweep(
    mesh, fixed_batch, gb,
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.1)),
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=1.0)),
    num_iterations=2, config=cfg, num_samples=n)
exported = mh.export_local_random_effects(rec, gb, mesh, projections=pp)
with open(os.path.join(out, f"sp{{pid}}.json"), "w") as f:
    json.dump({{"wf": [float(v) for v in np.asarray(wf)],
               "re": {{str(k): [float(v) for v in w]
                      for k, w in exported.items()}}}}, f)
""")
    _launch_workers(worker, 2, tmp_path)
    res = [json.load(open(tmp_path / f"sp{pid}.json")) for pid in range(2)]
    np.testing.assert_allclose(res[0]["wf"], res[1]["wf"], rtol=0, atol=0)
    merged = {int(k): np.asarray(v) for o in res for k, v in o["re"].items()}

    # single-process framework reference (sparse shard -> compact coordinate)
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.data import SparseShard
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(77)
    n, n_users, dg, du, ku = 480, 12, 4, 64, 3
    uids = rng.integers(0, n_users, size=n)
    xg = rng.normal(size=(n, dg)).astype(np.float32)
    idx_u = rng.integers(0, du, size=(n, ku)).astype(np.int32)
    vals_u = rng.normal(size=(n, ku)).astype(np.float32)
    uw = rng.normal(size=(n_users, du)).astype(np.float32)
    gw = rng.normal(size=dg).astype(np.float32)
    z = xg @ gw + np.einsum("nk,nk->n", vals_u, uw[uids[:, None], idx_u])
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
    data = GameData(y=y, features={
        "g": xg, "u": SparseShard(indices=idx_u, values=vals_u, dim=du)},
        id_tags={"userId": uids})
    cfg = SolverConfig(max_iters=60, tolerance=1e-9)
    coords = {
        "fixed": build_coordinate("fixed", data, FixedEffectConfig(
            feature_shard="g", solver=cfg, reg=Regularization(l2=0.1)),
            TaskType.LOGISTIC_REGRESSION, seed=5),
        "user": build_coordinate("user", data, RandomEffectConfig(
            random_effect_type="userId", feature_shard="u",
            solver=cfg, reg=Regularization(l2=1.0)),
            TaskType.LOGISTIC_REGRESSION, seed=5),
    }
    model, _, _ = CoordinateDescent(coords, order=["fixed", "user"],
                                    num_iterations=2).run(seed=5)
    np.testing.assert_allclose(
        res[0]["wf"], np.asarray(model["fixed"].coefficients.means),
        atol=5e-4, rtol=1e-3)
    re_ref = model["user"]
    assert set(merged) == set(re_ref.slot_of)
    for eid, w in merged.items():
        np.testing.assert_allclose(
            w, np.asarray(re_ref.w_stack[re_ref.slot_of[eid]]),
            atol=5e-4, rtol=1e-3)


def test_multihost_glmix3_two_processes(tmp_path):
    """Three-coordinate multihost GLMix (fixed + per-user + per-item — the
    reference's flagship shape): each RE coordinate has its OWN entity-hash
    ownership and buckets; the residual schedule runs fixed then each RE
    against the residual of all others.  Parity vs the single-process
    3-coordinate CoordinateDescent solve."""
    import json
    import os

    port = _free_port()
    worker = tmp_path / "glmix3_worker.py"
    worker.write_text(f"""
import sys
sys.path.insert(0, {os.getcwd()!r})
import os, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); out = sys.argv[3]
from photon_ml_tpu.parallel import multihost as mh
from photon_ml_tpu.parallel.bucketing import bucket_by_entity
mh.initialize(coordinator_address="127.0.0.1:{port}", num_processes=nproc,
              process_id=pid, expected_processes=nproc)
mesh = mh.global_mesh()

rng = np.random.default_rng(91)
n, n_users, n_items, dg, du, di = 600, 12, 9, 4, 2, 2
uids = rng.integers(0, n_users, size=n)
iids = rng.integers(0, n_items, size=n)
xg = rng.normal(size=(n, dg)).astype(np.float32)
xu = rng.normal(size=(n, du)).astype(np.float32)
xi = rng.normal(size=(n, di)).astype(np.float32)
uw = rng.normal(size=(n_users, du)).astype(np.float32)
iw = rng.normal(size=(n_items, di)).astype(np.float32)
gw = rng.normal(size=dg).astype(np.float32)
z = (xg @ gw + np.einsum("nd,nd->n", xu, uw[uids])
     + np.einsum("nd,nd->n", xi, iw[iids]))
y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import logistic_loss
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.opt.types import SolverConfig

start, stop = mh.process_row_range(n)
rows_per = mh.padded_per_host_rows(n, mesh)
blk = mh.pad_local_rows(dict(x=xg[start:stop], y=y[start:stop],
                             offset=np.zeros(stop - start, np.float32),
                             weight=np.ones(stop - start, np.float32)),
                        rows_per)
g = mh.global_batch_from_local(blk, mesh)
fb = DenseBatch(x=g["x"], y=g["y"], offset=g["offset"], weight=g["weight"])
n_glob = rows_per * nproc

def make_buckets(ids, x):
    rid = mh.local_entity_rows(ids)
    local = bucket_by_entity(ids[rid], x[rid], y[rid],
                             weight=np.ones(len(rid), np.float32),
                             seed=5, row_ids=rid, num_samples=n_glob)
    return mh.global_entity_buckets(local, mesh)

gb = {{"user": make_buckets(uids, xu), "item": make_buckets(iids, xi)}}
cfg = SolverConfig(max_iters=60, tolerance=1e-9)
objs = {{"user": GLMObjective(loss=logistic_loss, reg=Regularization(l2=1.0)),
        "item": GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.7))}}
wf, rec, _ = mh.multihost_glmix_sweep(
    mesh, fb, gb,
    GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.1)),
    objs, num_iterations=2, config=cfg, num_samples=n)
ex = {{cid: mh.export_local_random_effects(rec[cid], gb[cid], mesh)
      for cid in gb}}
with open(os.path.join(out, f"g3_{{pid}}.json"), "w") as f:
    json.dump({{"wf": [float(v) for v in np.asarray(wf)],
               "re": {{cid: {{str(k): [float(v) for v in w]
                            for k, w in d.items()}}
                      for cid, d in ex.items()}}}}, f)
""")
    _launch_workers(worker, 2, tmp_path)
    res = [json.load(open(tmp_path / f"g3_{pid}.json")) for pid in range(2)]
    np.testing.assert_allclose(res[0]["wf"], res[1]["wf"], rtol=0, atol=0)
    merged = {cid: {int(k): np.asarray(v)
                    for o in res for k, v in o["re"][cid].items()}
              for cid in ("user", "item")}

    # single-process 3-coordinate reference
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(91)
    n, n_users, n_items, dg, du, di = 600, 12, 9, 4, 2, 2
    uids = rng.integers(0, n_users, size=n)
    iids = rng.integers(0, n_items, size=n)
    xg = rng.normal(size=(n, dg)).astype(np.float32)
    xu = rng.normal(size=(n, du)).astype(np.float32)
    xi = rng.normal(size=(n, di)).astype(np.float32)
    uw = rng.normal(size=(n_users, du)).astype(np.float32)
    iw = rng.normal(size=(n_items, di)).astype(np.float32)
    gw = rng.normal(size=dg).astype(np.float32)
    z = (xg @ gw + np.einsum("nd,nd->n", xu, uw[uids])
         + np.einsum("nd,nd->n", xi, iw[iids]))
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
    data = GameData(y=y, features={"g": xg, "u": xu, "i": xi},
                    id_tags={"userId": uids, "itemId": iids})
    cfg = SolverConfig(max_iters=60, tolerance=1e-9)
    coords = {
        "fixed": build_coordinate("fixed", data, FixedEffectConfig(
            feature_shard="g", solver=cfg, reg=Regularization(l2=0.1)),
            TaskType.LOGISTIC_REGRESSION, seed=5),
        "user": build_coordinate("user", data, RandomEffectConfig(
            random_effect_type="userId", feature_shard="u", solver=cfg,
            reg=Regularization(l2=1.0)), TaskType.LOGISTIC_REGRESSION,
            seed=5),
        "item": build_coordinate("item", data, RandomEffectConfig(
            random_effect_type="itemId", feature_shard="i", solver=cfg,
            reg=Regularization(l2=0.7)), TaskType.LOGISTIC_REGRESSION,
            seed=5),
    }
    model, _, _ = CoordinateDescent(coords, order=["fixed", "user", "item"],
                                    num_iterations=2).run(seed=5)
    np.testing.assert_allclose(
        res[0]["wf"], np.asarray(model["fixed"].coefficients.means),
        atol=5e-4, rtol=1e-3)
    for cid in ("user", "item"):
        ref = model[cid]
        assert set(merged[cid]) == set(ref.slot_of)
        for e, w in merged[cid].items():
            np.testing.assert_allclose(
                w, np.asarray(ref.w_stack[ref.slot_of[e]]),
                atol=5e-4, rtol=1e-3)


class TestMultihostGuards:
    """The loud-failure contracts of the multihost path (single-process
    degenerates — the errors fire before any cross-process work)."""

    def _mesh(self, devices):
        from photon_ml_tpu.parallel.multihost import global_mesh

        return global_mesh()

    def test_compact_buckets_require_projections(self, devices, rng):
        from photon_ml_tpu.parallel.bucketing import bucket_by_entity_sparse
        from photon_ml_tpu.parallel.multihost import global_entity_buckets

        n, d, k = 40, 16, 3
        uids = np.repeat(np.arange(8), 5)
        local, _projs = bucket_by_entity_sparse(
            uids, rng.integers(0, d, size=(n, k)).astype(np.int32),
            rng.normal(size=(n, k)).astype(np.float32), d,
            (rng.random(n) < 0.5).astype(np.float32))
        assert local.compact
        with pytest.raises(ValueError, match="projections"):
            global_entity_buckets(local, self._mesh(devices))

    def test_sweep_requires_num_samples(self, devices, rng):
        from photon_ml_tpu.core import GLMObjective, Regularization, losses
        from photon_ml_tpu.core.batch import dense_batch
        from photon_ml_tpu.parallel.bucketing import bucket_by_entity
        from photon_ml_tpu.parallel.multihost import (global_entity_buckets,
                                                      multihost_glmix_sweep)

        mesh = self._mesh(devices)
        n = 16
        uids = np.repeat(np.arange(4), 4)
        x = rng.normal(size=(n, 2)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        gb = global_entity_buckets(
            bucket_by_entity(uids, x, y, row_ids=np.arange(n),
                             num_samples=n), mesh)
        obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=1))
        with pytest.raises(ValueError, match="num_samples"):
            multihost_glmix_sweep(mesh, dense_batch(x, y), gb, obj, obj)

    def test_single_process_allgather_has_no_process_axis(self, devices,
                                                          rng):
        """Regression: with one process, ``process_allgather`` returns the
        INPUT shape unchanged — no leading process axis is prepended.  The
        agreement pass in ``global_entity_buckets`` used to index
        ``all_vec[:, log, 0]`` as if the axis were always there and died
        with ``IndexError: too many indices for array``; it must reshape
        to ``[n_proc, ...]`` first."""
        from jax.experimental import multihost_utils

        from photon_ml_tpu.parallel.bucketing import bucket_by_entity
        from photon_ml_tpu.parallel.multihost import global_entity_buckets

        # pin the offending shape: the (MAXLOG, 2) metadata vector comes
        # back (33, 2), NOT (1, 33, 2)
        vec = np.zeros((33, 2), np.int64)
        out = np.asarray(multihost_utils.process_allgather(vec))
        assert out.shape == vec.shape

        # and the end-to-end single-process assembly works on top of it
        mesh = self._mesh(devices)
        n = 16
        uids = np.repeat(np.arange(4), 4)
        x = rng.normal(size=(n, 2)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        gb = global_entity_buckets(
            bucket_by_entity(uids, x, y, row_ids=np.arange(n),
                             num_samples=n), mesh)
        assert gb.num_entities == 4

    def test_unknown_re_scoring_key_fails(self, devices, rng):
        from photon_ml_tpu.core import GLMObjective, Regularization, losses
        from photon_ml_tpu.core.batch import dense_batch
        from photon_ml_tpu.parallel.bucketing import bucket_by_entity
        from photon_ml_tpu.parallel.multihost import (global_entity_buckets,
                                                      multihost_glmix_sweep)

        mesh = self._mesh(devices)
        n = 16
        uids = np.repeat(np.arange(4), 4)
        x = rng.normal(size=(n, 2)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        gb = global_entity_buckets(
            bucket_by_entity(uids, x, y, row_ids=np.arange(n),
                             num_samples=n), mesh)
        obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=1))
        with pytest.raises(ValueError, match="re_scoring keys"):
            multihost_glmix_sweep(mesh, dense_batch(x, y), {"user": gb},
                                  obj, obj, num_samples=n,
                                  re_scoring={"users": None})
