"""GLM objective tests: closed-form vs autodiff, sparse vs dense, normalization
algebra invariants (reference: photon-lib function/glm/*AggregatorTest,
NormalizationContextIntegTest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core import (
    DenseBatch,
    GLMObjective,
    NormalizationContext,
    Regularization,
    losses,
)
from photon_ml_tpu.core.batch import dense_batch, sparse_batch
from photon_ml_tpu.core.normalization import (
    FeatureStats,
    build_normalization,
    compute_feature_stats,
    no_normalization,
)
from photon_ml_tpu.types import NormalizationType

N, D, K = 48, 7, 4


def _data(rng, sparse=False):
    y = (rng.random(N) > 0.5).astype(float)
    offset = rng.normal(size=N) * 0.1
    weight = rng.random(N) + 0.5
    if sparse:
        idx = np.stack([rng.choice(D, size=K, replace=False) for _ in range(N)])
        val = rng.normal(size=(N, K))
        # pad last slot of some rows
        val[::5, -1] = 0.0
        return sparse_batch(idx, val, y, dim=D, offset=offset, weight=weight)
    x = rng.normal(size=(N, D))
    return dense_batch(x, y, offset=offset, weight=weight)


def _norms(rng):
    factors = jnp.asarray(rng.random(D) + 0.5)
    shifts = jnp.asarray(rng.normal(size=D))
    return [
        no_normalization(),
        NormalizationContext(factors=factors, shifts=None),
        NormalizationContext(factors=factors, shifts=shifts),
    ]


@pytest.mark.parametrize("sparse", [False, True], ids=["dense", "sparse"])
@pytest.mark.parametrize("normi", [0, 1, 2], ids=["none", "scale", "affine"])
def test_grad_and_hvp_match_autodiff(rng, sparse, normi):
    batch = _data(rng, sparse)
    norm = _norms(rng)[normi]
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.3), norm=norm)
    w = jnp.asarray(rng.normal(size=D))
    v = jnp.asarray(rng.normal(size=D))

    val, g = obj.value_and_grad(w, batch)
    np.testing.assert_allclose(val, obj.value(w, batch), rtol=1e-12)
    ad_g = jax.grad(obj.value)(w, batch)
    np.testing.assert_allclose(g, ad_g, rtol=1e-9, atol=1e-11)

    hv = obj.hvp(w, batch, v)
    ad_hv = jax.jvp(lambda u: jax.grad(obj.value)(u, batch), (w,), (v,))[1]
    np.testing.assert_allclose(hv, ad_hv, rtol=1e-9, atol=1e-11)


@pytest.mark.parametrize("normi", [0, 1, 2], ids=["none", "scale", "affine"])
def test_hessian_diag_and_full(rng, normi):
    batch = _data(rng)
    norm = _norms(rng)[normi]
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.1), norm=norm)
    w = jnp.asarray(rng.normal(size=D))
    h = jax.hessian(obj.value)(w, batch)
    np.testing.assert_allclose(obj.hessian(w, batch), h, rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(obj.hessian_diag(w, batch), jnp.diagonal(h), rtol=1e-9, atol=1e-11)


def test_sparse_matches_dense(rng):
    sb = _data(rng, sparse=True)
    db = sb.to_dense()
    obj = GLMObjective(loss=losses.poisson_loss)
    obj_d = GLMObjective(loss=losses.poisson_loss)
    w = jnp.asarray(rng.normal(size=D) * 0.3)
    y = jnp.abs(sb.y)
    sb = sb.replace(y=y)
    db = db.replace(y=y)
    v_s, g_s = obj.value_and_grad(w, sb)
    v_d, g_d = obj_d.value_and_grad(w, db)
    np.testing.assert_allclose(v_s, v_d, rtol=1e-12)
    np.testing.assert_allclose(g_s, g_d, rtol=1e-12)


def test_normalization_margin_invariance(rng):
    """Objective on raw X with normalization algebra == objective on explicitly
    transformed X (the whole point of the effective-coef trick,
    ValueAndGradientAggregator.scala:36-49)."""
    batch = _data(rng)
    factors = jnp.asarray(rng.random(D) + 0.5)
    shifts = jnp.asarray(rng.normal(size=D))
    norm = NormalizationContext(factors=factors, shifts=shifts)
    obj = GLMObjective(loss=losses.logistic_loss, norm=norm)
    w = jnp.asarray(rng.normal(size=D))

    x_t = (batch.x - shifts) * factors
    batch_t = DenseBatch(x=x_t, y=batch.y, offset=batch.offset, weight=batch.weight)
    obj_t = GLMObjective(loss=losses.logistic_loss)
    np.testing.assert_allclose(obj.value(w, batch), obj_t.value(w, batch_t), rtol=1e-12)
    np.testing.assert_allclose(
        obj.gradient(w, batch), obj_t.gradient(w, batch_t), rtol=1e-9, atol=1e-11
    )


def test_model_space_roundtrip(rng):
    """modelToOriginalSpace / modelToTransformedSpace are inverse and margin-
    invariant (NormalizationContext.scala:73-124)."""
    x = rng.normal(size=(N, D))
    x[:, 0] = 1.0  # intercept column
    stats = compute_feature_stats(jnp.asarray(x), intercept_index=0)
    norm = build_normalization(NormalizationType.STANDARDIZATION, stats)
    assert norm.factors[0] == 1.0 and norm.shifts[0] == 0.0

    w_t = jnp.asarray(rng.normal(size=D))
    w_o = norm.model_to_original_space(w_t, intercept_index=0)
    np.testing.assert_allclose(
        norm.model_to_transformed_space(w_o, intercept_index=0), w_t, rtol=1e-9, atol=1e-12
    )
    # margin invariance: w_t over transformed x == w_o over raw x
    xj = jnp.asarray(x)
    m_t = ((xj - norm.shifts) * norm.factors) @ w_t
    np.testing.assert_allclose(xj @ w_o, m_t, rtol=1e-9, atol=1e-9)


def test_feature_stats(rng):
    x = rng.normal(size=(N, D))
    s = compute_feature_stats(jnp.asarray(x))
    np.testing.assert_allclose(s.mean, x.mean(0), rtol=1e-12)
    np.testing.assert_allclose(s.variance, x.var(0, ddof=1), rtol=1e-10)
    np.testing.assert_allclose(s.abs_max, np.abs(x).max(0), rtol=1e-12)


def test_weight_zero_padding_is_inert(rng):
    """Padded rows (weight 0) must not affect value/grad — the masking contract
    that every vmapped/sharded path relies on."""
    batch = _data(rng)
    obj = GLMObjective(loss=losses.logistic_loss, reg=Regularization(l2=0.2))
    w = jnp.asarray(rng.normal(size=D))
    # append garbage rows with weight 0
    pad = 5
    xg = jnp.concatenate([batch.x, jnp.full((pad, D), 1e6)], 0)
    yg = jnp.concatenate([batch.y, jnp.ones(pad)])
    og = jnp.concatenate([batch.offset, jnp.full((pad,), 1e6)])
    wg = jnp.concatenate([batch.weight, jnp.zeros(pad)])
    padded = DenseBatch(x=xg, y=yg, offset=og, weight=wg)
    v0, g0 = obj.value_and_grad(w, batch)
    v1, g1 = obj.value_and_grad(w, padded)
    np.testing.assert_allclose(v0, v1, rtol=1e-12)
    np.testing.assert_allclose(g0, g1, rtol=1e-12)


def test_weight_zero_padding_unbounded_loss(rng):
    """0-weight rows must not poison reductions via 0*inf (poisson exp)."""
    x = np.concatenate([rng.normal(size=(8, D)), np.full((2, D), 1e4)])
    y = np.concatenate([rng.poisson(2.0, size=8).astype(float), np.zeros(2)])
    w8 = np.concatenate([np.ones(8), np.zeros(2)])
    batch = dense_batch(x, y, weight=w8)
    obj = GLMObjective(loss=losses.poisson_loss)
    w = jnp.asarray(rng.normal(size=D) * 0.1)
    v, g = obj.value_and_grad(w, batch)
    assert np.isfinite(v) and np.all(np.isfinite(g))
    ref = GLMObjective(loss=losses.poisson_loss).value(w, dense_batch(x[:8], y[:8]))
    np.testing.assert_allclose(v, ref, rtol=1e-12)
    assert np.all(np.isfinite(obj.hvp(w, batch, w)))
    assert np.all(np.isfinite(obj.hessian_diag(w, batch)))
    assert np.all(np.isfinite(obj.hessian(w, batch)))


def test_smoothed_hinge_soft_labels_thresholded():
    """Reference thresholds soft labels at 0.5 (SmoothedHingeLossFunction.scala)."""
    l = losses.smoothed_hinge_loss
    z = jnp.asarray([0.3, 0.3])
    np.testing.assert_allclose(l.loss(z, jnp.asarray([0.7, 1.0]))[0], l.loss(z, jnp.ones(2))[1])
    np.testing.assert_allclose(l.d1(z, jnp.asarray([0.2, 0.0]))[0], l.d1(z, jnp.zeros(2))[1])


def test_standardization_requires_intercept(rng):
    from photon_ml_tpu.core.normalization import build_normalization as bn
    stats = compute_feature_stats(jnp.asarray(rng.normal(size=(N, D))))
    with pytest.raises(ValueError, match="intercept"):
        bn(NormalizationType.STANDARDIZATION, stats)


def test_mixed_precision_storage_matches_f32():
    """bf16-stored design matrix with f32 solver state: margins/gradients/Hv
    accumulate at f32 (preferred_element_type), so results track the all-f32
    objective to bf16 input resolution — and outputs are f32, never bf16."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.core.batch import DenseBatch, SparseBatch
    from photon_ml_tpu.core.losses import logistic_loss
    from photon_ml_tpu.core.objective import GLMObjective
    from photon_ml_tpu.core.regularization import Regularization

    rng = np.random.default_rng(3)
    n, d, k = 400, 24, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) * 0.3
    v = rng.normal(size=d).astype(np.float32)
    obj = GLMObjective(loss=logistic_loss, reg=Regularization(l2=0.5))

    f32 = DenseBatch(x=jnp.asarray(x), y=jnp.asarray(y),
                     offset=jnp.zeros(n, jnp.float32), weight=jnp.ones(n, jnp.float32))
    bf16 = f32.replace(x=f32.x.astype(jnp.bfloat16))

    for name, fn in [("value_and_grad", lambda b: obj.value_and_grad(jnp.asarray(w), b)),
                     ("hvp", lambda b: (obj.hvp(jnp.asarray(w), b, jnp.asarray(v)),)),
                     ("hessian_diag", lambda b: (obj.hessian_diag(jnp.asarray(w), b),))]:
        for a, b in zip(fn(f32), fn(bf16)):
            assert jnp.asarray(b).dtype == jnp.float32, name  # accumulation width
            np.testing.assert_allclose(np.asarray(a, np.float64),
                                       np.asarray(b, np.float64),
                                       rtol=3e-2, atol=3e-2, err_msg=name)

    # sparse storage narrowing follows the same contract (indices unique per
    # row, per the SparseBatch contract)
    idx = np.stack([rng.choice(d, size=k, replace=False) for _ in range(n)]) \
        .astype(np.int32)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    sp32 = SparseBatch(indices=jnp.asarray(idx), values=jnp.asarray(vals),
                       y=jnp.asarray(y), offset=jnp.zeros(n, jnp.float32),
                       weight=jnp.ones(n, jnp.float32), dim=d)
    spb = sp32.replace(values=sp32.values.astype(jnp.bfloat16))
    g32 = obj.value_and_grad(jnp.asarray(w), sp32)[1]
    gbf = obj.value_and_grad(jnp.asarray(w), spb)[1]
    assert gbf.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(g32), np.asarray(gbf), rtol=3e-2, atol=3e-2)
