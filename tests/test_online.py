"""photonlearn tests: durable delta log, replicated catch-up, incremental
trainer, and the learn/serve CLI round trip.

The contracts under test (ISSUE 9 / ROADMAP item 3):
  - DeltaLog: append/replay round-trips bitwise; identities are strictly
    monotone or the append raises; a file truncated at EVERY byte offset
    replays exactly the complete-record prefix and never raises; a writer
    re-opening a torn segment truncates the tear before appending.
  - Catch-up: replaying the full log into a fresh identical store is
    bitwise-equal to the live store; replay is idempotent (position skips
    everything already applied); bad records count as rejected, never
    raise; LogFollower resets its position when the served generation
    changes.
  - Trainer: a labeled mini-batch moves the served score with ZERO engine
    recompiles, publishes under ordered identities, and skips unknown
    entities / under-observed entities instead of failing.
  - Swap integration: deltas published before a hot swap survive it
    (replay-before-activate) and the swap compacts the log.
  - CLI: learn.py writes a log a second serve.py --delta-log process
    converges from, for both JSON-lines and Avro inputs.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.online.catchup import LogFollower, replay_into_store
from photon_ml_tpu.online.delta_log import (_MAGIC, DeltaLog, DeltaRecord,
                                            _segment_name)
from photon_ml_tpu.online.trainer import (IncrementalTrainer, TrainerConfig,
                                          example_from_json)
from photon_ml_tpu.serving.batcher import BucketedBatcher, Request
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.types import TaskType

N_ENT = 40
D = 4
NAMES = [f"f{j}" for j in range(D)]


def _model(seed=0):
    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    return GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    }), task


def _engine(seed=0, max_batch=8):
    model, task = _model(seed)
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    metrics = ServingMetrics()
    store = CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=None), version="synthetic",
        metrics=metrics)
    eng = ScoringEngine(store, BucketedBatcher(max_batch), metrics=metrics)
    eng.warm()
    return eng


def _req(rng, uid, user):
    feats = [{"name": n, "term": "", "value": float(v)}
             for n, v in zip(NAMES, rng.normal(size=D))]
    return Request(uid=uid, features=feats, ids={"userId": f"user{user}"})


def _rec(g, v, entity="user1", row=None):
    return DeltaRecord(generation=g, delta_version=v, cid="user",
                       entity=entity,
                       row=tuple(row if row is not None else
                                 np.arange(D, dtype=float) + v))


# ---------------------------------------------------------------------------
# delta log framing
# ---------------------------------------------------------------------------
class TestDeltaLog:
    def test_append_replay_round_trip_across_generations(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="rotate")
        recs = [_rec(1, 1), _rec(1, 2), _rec(2, 1), _rec(2, 2), _rec(3, 1)]
        for r in recs:
            log.append(r)
        log.close()
        assert [g for g, _ in log.segments()] == [1, 2, 3]
        got = list(DeltaLog(str(tmp_path), fsync="never").replay())
        assert got == recs  # frozen dataclass equality: rows bitwise too
        assert log.last_identity() == (3, 1)
        # position replay: strictly after an identity, across a rotation
        assert [r.identity for r in log.replay(after=(1, 2))] == \
            [(2, 1), (2, 2), (3, 1)]

    def test_non_monotone_identity_raises(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="never")
        log.append(_rec(2, 1))
        with pytest.raises(ValueError, match="non-monotone"):
            log.append(_rec(2, 1))
        with pytest.raises(ValueError, match="non-monotone"):
            log.append(_rec(1, 9))
        log.append(_rec(2, 2))  # the failed appends consumed nothing

    def test_reopened_writer_resumes_monotone(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="never")
        log.append(_rec(1, 1))
        log.close()
        log2 = DeltaLog(str(tmp_path), fsync="never")
        with pytest.raises(ValueError, match="non-monotone"):
            log2.append(_rec(1, 1))
        log2.append(_rec(1, 2))

    def test_bad_fsync_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="fsync"):
            DeltaLog(str(tmp_path), fsync="sometimes")

    def test_truncate_at_every_byte_offset(self, tmp_path):
        """THE crash-safety property: whatever prefix of the segment
        survives a crash, replay yields exactly the records whose frames
        lie entirely inside it — and never raises."""
        src = tmp_path / "src"
        log = DeltaLog(str(src), fsync="never")
        recs = [_rec(1, v) for v in range(1, 5)]
        for r in recs:
            log.append(r)
        log.close()
        seg = src / _segment_name(1)
        data = seg.read_bytes()
        # frame end offsets, in order
        ends, pos = [], len(_MAGIC)
        for r in recs:
            pos += len(r.encode())
            ends.append(pos)
        assert ends[-1] == len(data)

        scratch = tmp_path / "cut"
        os.makedirs(scratch)
        cut_seg = scratch / _segment_name(1)
        for cut in range(len(data) + 1):
            cut_seg.write_bytes(data[:cut])
            got = list(DeltaLog(str(scratch), fsync="never").replay())
            expect = sum(1 for e in ends if e <= cut)
            assert len(got) == expect, f"cut at byte {cut}"
            assert got == recs[:expect]

    def test_crc_corruption_ends_segment_cleanly(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="never")
        for v in range(1, 4):
            log.append(_rec(1, v))
        log.close()
        seg = tmp_path / _segment_name(1)
        data = bytearray(seg.read_bytes())
        # flip one payload byte of the second record
        off = len(_MAGIC) + len(_rec(1, 1).encode()) + 10
        data[off] ^= 0xFF
        seg.write_bytes(bytes(data))
        got = list(DeltaLog(str(tmp_path), fsync="never").replay())
        assert [r.identity for r in got] == [(1, 1)]

    def test_torn_tail_truncated_before_append(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="never")
        for v in range(1, 4):
            log.append(_rec(1, v))
        log.close()
        seg = tmp_path / _segment_name(1)
        data = seg.read_bytes()
        seg.write_bytes(data[:-3])  # tear the last record
        log2 = DeltaLog(str(tmp_path), fsync="never")
        assert log2.last_identity() == (1, 2)  # the tear is invisible
        log2.append(_rec(1, 3))  # (1,3) is free again — its frame tore
        log2.close()
        got = list(DeltaLog(str(tmp_path), fsync="never").replay())
        assert [r.identity for r in got] == [(1, 1), (1, 2), (1, 3)]
        # the re-appended record replays intact, not shadowed by garbage
        assert got[-1] == _rec(1, 3)

    def test_compact_drops_only_stale_generations(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="never")
        for g in (1, 2, 3):
            log.append(_rec(g, 1))
        dropped = log.compact(3)
        assert dropped == [1, 2]
        assert [g for g, _ in log.segments()] == [3]
        assert [r.identity for r in log.replay()] == [(3, 1)]


# ---------------------------------------------------------------------------
# catch-up replay
# ---------------------------------------------------------------------------
class TestCatchup:
    def test_full_replay_is_bitwise_and_idempotent(self, tmp_path):
        eng = _engine(seed=3)
        log = DeltaLog(str(tmp_path), fsync="rotate")
        swapper = HotSwapper(eng, delta_log=log)
        rng = np.random.default_rng(0)
        for k, u in enumerate([3, 7, 3, 11]):
            ident = swapper.publish_delta("user", f"user{u}",
                                          rng.normal(size=D))
            assert ident == (eng.store.generation, k + 1)
        assert log.last_identity() == swapper.identity

        fresh = _engine(seed=3)  # same seed -> identical pre-delta model
        stats = replay_into_store(fresh.store, log.replay())
        assert (stats.applied, stats.rejected) == (4, 0)
        assert stats.position == swapper.identity
        live = eng.store.coordinates["user"]
        rep = fresh.store.coordinates["user"]
        assert np.array_equal(np.asarray(live.table), np.asarray(rep.table))
        assert np.array_equal(np.asarray(live._archive),
                              np.asarray(rep._archive))
        # idempotence: a second replay from the recorded position is a no-op
        again = replay_into_store(fresh.store, log.replay(),
                                  position=stats.position)
        assert (again.applied, again.skipped) == (0, 4)
        # ...and replaying everything AGAIN without a position still lands
        # on the same bytes (ordered full-row overwrites)
        replay_into_store(fresh.store, log.replay())
        assert np.array_equal(np.asarray(live.table), np.asarray(rep.table))

    def test_bad_records_rejected_not_raised(self, tmp_path):
        eng = _engine()
        recs = [
            _rec(1, 1, entity="user2"),
            _rec(1, 2, entity="ghost"),                    # unknown entity
            DeltaRecord(1, 3, "nope", "user2", (0.0,) * D),  # unknown cid
            _rec(1, 4, entity="user2", row=[1.0]),         # bad width
            _rec(1, 5, entity="user5"),
        ]
        stats = replay_into_store(eng.store, recs)
        assert stats.applied == 2
        assert stats.rejected == 3
        assert stats.position == (1, 5)

    def test_follower_tails_and_resets_on_generation_change(self, tmp_path):
        eng = _engine(seed=5)
        log = DeltaLog(str(tmp_path), fsync="never")
        swapper = HotSwapper(eng, delta_log=log)
        replica = _engine(seed=5)
        current = {"store": replica.store}
        follower = LogFollower(log, lambda: current["store"],
                               registry=replica.metrics.registry)

        swapper.publish_delta("user", "user1", np.ones(D))
        assert follower.run_once().applied == 1
        assert follower.run_once().applied == 0  # tail: nothing new
        swapper.publish_delta("user", "user2", np.ones(D) * 2)
        assert follower.run_once().applied == 1

        # generation change (a hot swap on the replica): position resets,
        # the WHOLE log replays into the incoming store
        incoming = _engine(seed=5).store
        current["store"] = incoming
        stats = follower.run_once()
        assert stats.applied == 2
        live = eng.store.coordinates["user"]
        assert np.array_equal(np.asarray(live._archive),
                              np.asarray(incoming.coordinates["user"]
                                         ._archive))

    def test_follower_thread_converges(self, tmp_path):
        import time

        eng = _engine(seed=6)
        log = DeltaLog(str(tmp_path), fsync="never")
        swapper = HotSwapper(eng, delta_log=log)
        replica = _engine(seed=6)
        follower = LogFollower(log, lambda: replica.store,
                               poll_interval_s=0.01)
        follower.start()
        try:
            for u in range(8):
                swapper.publish_delta("user", f"user{u}",
                                      np.full(D, float(u)))
            deadline = time.time() + 5.0
            want = np.asarray(eng.store.coordinates["user"]._archive)
            while time.time() < deadline:
                got = np.asarray(replica.store.coordinates["user"]._archive)
                if np.array_equal(got, want):
                    break
                time.sleep(0.01)
            else:
                pytest.fail("follower did not converge within 5s")
        finally:
            follower.stop()


# ---------------------------------------------------------------------------
# incremental trainer
# ---------------------------------------------------------------------------
class TestTrainer:
    def test_refit_moves_score_zero_recompiles(self, tmp_path):
        eng = _engine(seed=1)
        log = DeltaLog(str(tmp_path), fsync="rotate",
                       registry=eng.metrics.registry)
        trainer = IncrementalTrainer(HotSwapper(eng, delta_log=log),
                                     TrainerConfig(coordinates=("user",)))
        rng = np.random.default_rng(2)
        probe = _req(rng, "probe", 3)
        before = float(eng.score_requests([probe])[0])
        compiles = eng.compile_count

        batch = []
        for i in range(24):
            r = _req(rng, i, int(rng.integers(0, 8)))
            batch.append({"uid": i, "features": r.features, "ids": r.ids,
                          "label": float(rng.integers(0, 2))})
        rep = trainer.consume(batch)
        assert rep.examples == 24
        assert rep.entities >= 1
        assert rep.published == rep.entities
        assert rep.rejected == 0
        assert rep.first_identity == (eng.store.generation, 1)
        assert rep.last_identity == (eng.store.generation, rep.published)
        after = float(eng.score_requests([probe])[0])
        assert after != before  # user3 had fresh rows -> new coefficients
        assert eng.compile_count == compiles  # publishes never recompile
        assert log.records_written == rep.published
        # a second batch continues the identity sequence
        rep2 = trainer.consume(batch)
        assert rep2.first_identity == (eng.store.generation,
                                       rep.published + 1)
        assert rep.to_json()["entities"] == rep.entities  # serializable

    def test_consume_accepts_example_objects_and_dicts(self):
        eng = _engine()
        trainer = IncrementalTrainer(HotSwapper(eng))
        rng = np.random.default_rng(0)
        r = _req(rng, 0, 1)
        obj = {"uid": 0, "features": r.features, "ids": r.ids, "label": 1.0}
        rep = trainer.consume([obj, example_from_json(obj)])
        assert rep.examples == 2
        assert rep.published >= 1

    def test_unknown_entities_skipped(self):
        eng = _engine()
        trainer = IncrementalTrainer(HotSwapper(eng),
                                     TrainerConfig(coordinates=("user",)))
        rng = np.random.default_rng(0)
        r = _req(rng, 0, 1)
        rep = trainer.consume([
            {"uid": 0, "features": r.features,
             "ids": {"userId": "stranger"}, "label": 1.0}])
        assert rep.skipped_unknown == 1
        assert rep.published == 0

    def test_min_rows_gate_defers_sparse_entities(self):
        eng = _engine()
        trainer = IncrementalTrainer(
            HotSwapper(eng),
            TrainerConfig(coordinates=("user",), min_rows_per_entity=3))
        rng = np.random.default_rng(0)
        batch = []
        for i in range(4):  # 4 rows for user1, 1 row for user2
            r = _req(rng, i, 1 if i < 4 else 2)
            batch.append({"uid": i, "features": r.features, "ids": r.ids,
                          "label": 1.0})
        r = _req(rng, 9, 2)
        batch.append({"uid": 9, "features": r.features, "ids": r.ids,
                      "label": 0.0})
        rep = trainer.consume(batch)
        assert rep.entities == 1  # only user1 met the gate
        assert rep.published == 1

    def test_label_required(self):
        with pytest.raises(ValueError, match="label"):
            example_from_json({"uid": 0, "features": [],
                               "ids": {"userId": "user1"}})

    def test_response_accepted_as_label(self):
        ex = example_from_json({"uid": 0, "features": [],
                                "ids": {"userId": "user1"},
                                "response": 1.0, "weight": 2.5})
        assert ex.label == 1.0 and ex.weight == 2.5

    def test_bad_explicit_coordinate_raises(self):
        eng = _engine()
        with pytest.raises(ValueError, match="fixed"):
            IncrementalTrainer(HotSwapper(eng),
                               TrainerConfig(coordinates=("fixed",)))
        with pytest.raises(ValueError, match="ghost"):
            IncrementalTrainer(HotSwapper(eng),
                               TrainerConfig(coordinates=("ghost",)))


# ---------------------------------------------------------------------------
# swap integration + the CLI round trip (trained model dir)
# ---------------------------------------------------------------------------
N_USERS = 6
FEATURES = ["g0", "g1", "g2", "ux"]


def _write_fixture(path, n=250, seed=1):
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE

    rng = np.random.default_rng(seed)
    uw = rng.normal(size=(N_USERS, 1)) * 1.5
    gw = np.asarray([0.8, -1.2, 0.5])
    records = []
    for i in range(n):
        u = int(rng.integers(0, N_USERS))
        xg = rng.normal(size=3)
        xu = rng.normal(size=1)
        logit = xg @ gw + xu @ uw[u]
        y = float(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        feats = [{"name": f"g{j}", "term": "", "value": float(xg[j])}
                 for j in range(3)]
        feats.append({"name": "ux", "term": "", "value": float(xu[0])})
        records.append({"uid": i, "response": y, "label": None,
                        "features": feats, "weight": None, "offset": None,
                        "metadataMap": {"userId": f"user{u}"}})
    avro_io.write_container(path, TRAINING_EXAMPLE, records)
    return records


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    from photon_ml_tpu.cli import train as train_cli

    tmp = tmp_path_factory.mktemp("online")
    data = str(tmp / "train.avro")
    _write_fixture(data)
    out = str(tmp / "model")
    rc = train_cli.run([
        "--train-data", data, "--feature-shards", "all",
        "--coordinate", "name=fixed,feature.shard=all,reg.weights=1",
        "--coordinate",
        "name=user,random.effect.type=userId,feature.shard=all,reg.weights=1",
        "--id-tags", "userId", "--coordinate-descent-iterations", "2",
        "--output-dir", out])
    assert rc == 0
    return out


def _probe_line(uid=0, user="user3"):
    return {"uid": uid, "features": [[f, 0.5] for f in FEATURES],
            "ids": {"userId": user}}


class TestSwapIntegration:
    def test_deltas_survive_swap_and_log_compacts(self, model_dir, tmp_path):
        from photon_ml_tpu.cli.serve import build_server
        from photon_ml_tpu.serving.batcher import request_from_json

        log = DeltaLog(str(tmp_path / "log"), fsync="rotate")
        engine, swapper = build_server(model_dir, warm=False,
                                       delta_log=log, log_owner=True)
        probe = request_from_json(_probe_line())
        base = float(engine.score_requests([probe])[0])
        d = engine.store.coordinates["user"].dim
        assert swapper.publish_delta("user", "user3",
                                     np.full(d, 2.0)) is not None
        patched = float(engine.score_requests([probe])[0])
        assert patched != base
        assert len(log.segments()) == 1

        gen_before = engine.store.generation
        assert swapper.swap(model_dir) is True
        assert engine.store.generation > gen_before
        # replay-before-activate: the published row survives the swap
        assert float(engine.score_requests([probe])[0]) == patched
        # ...and the swap compacted every pre-swap segment away
        assert log.segments() == []
        assert swapper.identity == (engine.store.generation, 0)

    def test_follower_role_never_compacts(self, model_dir, tmp_path):
        from photon_ml_tpu.cli.serve import build_server

        owner_log = DeltaLog(str(tmp_path / "log2"), fsync="never")
        _, owner = build_server(model_dir, warm=False,
                                delta_log=owner_log, log_owner=True)
        d = owner.engine.store.coordinates["user"].dim
        owner.publish_delta("user", "user1", np.full(d, 1.5))

        follower_log = DeltaLog(str(tmp_path / "log2"), fsync="never")
        engine2, replica = build_server(model_dir, warm=False,
                                        delta_log=follower_log,
                                        log_owner=False)
        assert replica.swap(model_dir) is True  # replays, must NOT compact
        assert len(follower_log.segments()) == 1
        assert list(follower_log.replay())  # owner's record still there


class TestLearnCli:
    def test_jsonl_round_trip_to_follower(self, model_dir, tmp_path, capsys):
        from photon_ml_tpu.cli import learn as learn_cli
        from photon_ml_tpu.cli import serve as serve_cli

        rng = np.random.default_rng(7)
        lines = []
        for i in range(20):
            u = int(rng.integers(0, N_USERS))
            feats = [[f, float(rng.normal())] for f in FEATURES]
            lines.append(json.dumps({
                "uid": i, "features": feats, "ids": {"userId": f"user{u}"},
                "label": float(rng.integers(0, 2))}))
        lines.insert(10, "")  # blank line: mid-stream flush
        exfile = tmp_path / "examples.jsonl"
        exfile.write_text("\n".join(lines) + "\n")
        logdir = str(tmp_path / "log")

        rc = learn_cli.run(["--model-dir", model_dir, "--examples",
                            str(exfile), "--delta-log", logdir,
                            "--batch-size", "64", "--fsync", "rotate"])
        assert rc == 0
        reports = [json.loads(l) for l in
                   capsys.readouterr().out.strip().splitlines()]
        assert len(reports) == 2  # blank-line flush + EOF flush
        assert all(r["published"] > 0 for r in reports)
        assert reports[1]["first_identity"][1] == \
            reports[0]["last_identity"][1] + 1

        req_file = tmp_path / "req.jsonl"
        req_file.write_text(json.dumps(_probe_line()) + "\n")
        rc = serve_cli.run(["--model-dir", model_dir, "--no-warm",
                            "--requests", str(req_file)])
        assert rc == 0
        plain = json.loads(capsys.readouterr().out.strip()
                           .splitlines()[0])["score"]
        rc = serve_cli.run(["--model-dir", model_dir, "--no-warm",
                            "--requests", str(req_file),
                            "--delta-log", logdir])
        assert rc == 0
        followed = json.loads(capsys.readouterr().out.strip()
                              .splitlines()[0])["score"]
        assert followed != plain  # the log's refits reached the replica

    def test_avro_input(self, model_dir, tmp_path, capsys):
        from photon_ml_tpu.cli import learn as learn_cli

        exfile = str(tmp_path / "fresh.avro")
        _write_fixture(exfile, n=30, seed=9)
        rc = learn_cli.run(["--model-dir", model_dir, "--examples", exfile,
                            "--format", "avro", "--batch-size", "30",
                            "--delta-log", str(tmp_path / "log")])
        assert rc == 0
        reports = [json.loads(l) for l in
                   capsys.readouterr().out.strip().splitlines()]
        assert len(reports) == 1
        assert reports[0]["examples"] == 30
        assert reports[0]["published"] > 0

    def test_restart_resumes_past_logged_generation(self, model_dir,
                                                    tmp_path, capsys):
        from photon_ml_tpu.cli import learn as learn_cli

        rng = np.random.default_rng(3)
        lines = [json.dumps({
            "uid": i, "features": [[f, float(rng.normal())]
                                   for f in FEATURES],
            "ids": {"userId": f"user{i % N_USERS}"},
            "label": float(i % 2)}) for i in range(8)]
        exfile = tmp_path / "ex.jsonl"
        exfile.write_text("\n".join(lines) + "\n")
        logdir = str(tmp_path / "log")

        argv = ["--model-dir", model_dir, "--examples", str(exfile),
                "--delta-log", logdir, "--fsync", "never"]
        assert learn_cli.run(argv) == 0
        first = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert learn_cli.run(argv) == 0
        second = [json.loads(l) for l in
                  capsys.readouterr().out.strip().splitlines()]
        # the restarted writer minted a strictly newer generation, so the
        # log stayed monotone (else its appends would have raised)
        assert second[0]["first_identity"][0] > first[-1]["last_identity"][0]

    def test_bad_example_line_reported_not_fatal(self, model_dir, tmp_path,
                                                 capsys):
        from photon_ml_tpu.cli import learn as learn_cli

        exfile = tmp_path / "ex.jsonl"
        exfile.write_text("this is not json\n" + json.dumps({
            "uid": 0, "features": [[f, 0.1] for f in FEATURES],
            "ids": {"userId": "user0"}, "label": 1.0}) + "\n")
        rc = learn_cli.run(["--model-dir", model_dir, "--examples",
                            str(exfile)])
        assert rc == 0
        out = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
        assert any("error" in o for o in out)
        assert any(o.get("examples") == 1 for o in out)
