"""Pointwise-loss unit tests (reference parity: photon-lib function/glm tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core import losses
from photon_ml_tpu.types import TaskType

ALL = [losses.logistic_loss, losses.squared_loss, losses.poisson_loss, losses.smoothed_hinge_loss]


def _labels_for(loss, rng, n):
    if loss.name == "squared":
        return rng.normal(size=n)
    if loss.name == "poisson":
        return rng.poisson(2.0, size=n).astype(float)
    return (rng.random(n) > 0.5).astype(float)


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_d1_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 3)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    ad = jax.vmap(jax.grad(lambda zi, yi: loss.loss(zi, yi)))(z, y)
    np.testing.assert_allclose(loss.d1(z, y), ad, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_d2_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 3)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    ad = jax.vmap(jax.grad(jax.grad(lambda zi, yi: loss.loss(zi, yi))))(z, y)
    np.testing.assert_allclose(loss.d2(z, y), ad, rtol=1e-10, atol=1e-12)


def test_logistic_stable_at_extremes():
    z = jnp.asarray([-1e4, -50.0, 0.0, 50.0, 1e4])
    y = jnp.asarray([0.0, 1.0, 1.0, 0.0, 1.0])
    l = losses.logistic_loss.loss(z, y)
    assert np.all(np.isfinite(l))
    # log(1+e^z) - y z: at z=1e4,y=1 -> ~0; at z=1e4,y=0 -> ~1e4
    np.testing.assert_allclose(l[3], 50.0, rtol=1e-12)
    np.testing.assert_allclose(l[4], 0.0, atol=1e-12)
    np.testing.assert_allclose(l[2], np.log(2.0), rtol=1e-12)


def test_logistic_value_known():
    # l(0, y) = log 2 regardless of label; l'(0, y) = 0.5 - y
    z = jnp.zeros(2)
    y = jnp.asarray([0.0, 1.0])
    np.testing.assert_allclose(losses.logistic_loss.loss(z, y), np.log(2.0))
    np.testing.assert_allclose(losses.logistic_loss.d1(z, y), [0.5, -0.5])


def test_smoothed_hinge_piecewise():
    # Rennie smoothed hinge with positive label: t=z.
    l = losses.smoothed_hinge_loss
    y = jnp.ones(5)
    z = jnp.asarray([-1.0, 0.0, 0.5, 1.0, 2.0])
    np.testing.assert_allclose(l.loss(z, y), [1.5, 0.5, 0.125, 0.0, 0.0])
    np.testing.assert_allclose(l.d1(z, y), [-1.0, -1.0, -0.5, 0.0, 0.0])
    np.testing.assert_allclose(l.d2(z, y), [0.0, 0.0, 1.0, 0.0, 0.0])
    # negative label mirrors: t=-z
    y0 = jnp.zeros(5)
    np.testing.assert_allclose(l.loss(-z, y0), l.loss(z, y))


def test_poisson_forms():
    z = jnp.asarray([0.0, 1.0, -1.0])
    y = jnp.asarray([1.0, 2.0, 0.0])
    np.testing.assert_allclose(losses.poisson_loss.loss(z, y), np.exp(z) - np.asarray(y) * np.asarray(z))
    np.testing.assert_allclose(losses.poisson_loss.mean(z), np.exp(z))


def test_task_mapping():
    assert losses.loss_for_task(TaskType.LOGISTIC_REGRESSION) is losses.logistic_loss
    assert losses.loss_for_task(TaskType.LINEAR_REGRESSION) is losses.squared_loss
    assert losses.loss_for_task(TaskType.POISSON_REGRESSION) is losses.poisson_loss
    assert losses.loss_for_task(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM) is losses.smoothed_hinge_loss
    with pytest.raises(ValueError):
        losses.loss_for_task(TaskType.NONE)
