"""Property-based round-trip tests for the from-spec Avro codec.

data/avro.py is a fully self-written Avro 1.x binary codec (no library in
the image) — the riskiest kind of code to trust on example-based tests
alone.  Hypothesis drives randomly-shaped schemas (primitives, arrays,
maps, nested records, nullable unions) and conforming values through
write_container -> read_container under both the null and deflate codecs.

Floats are drawn 32-bit-representable so round-trips are exact; int/long
stay inside their zigzag ranges (the schema layer validates ranges, this
tests the wire format).
"""

import os
import sys

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.data import avro as avro_io

_PRIMS = ("boolean", "int", "long", "float", "double", "string", "bytes")


@st.composite
def _schemas(draw, depth=0):
    """A random Avro schema dict (bounded depth, unique record names)."""
    opts = list(_PRIMS)
    if depth < 2:
        opts += ["array", "map", "record", "nullable"]
    kind = draw(st.sampled_from(opts))
    if kind in _PRIMS:
        return kind
    if kind == "array":
        return {"type": "array", "items": draw(_schemas(depth=depth + 1))}
    if kind == "map":
        return {"type": "map", "values": draw(_schemas(depth=depth + 1))}
    if kind == "nullable":
        inner = draw(_schemas(depth=depth + 1))
        if isinstance(inner, list):  # no unions-in-unions (Avro spec)
            inner = "long"
        return ["null", inner]
    n_fields = draw(st.integers(1, 3))
    name = f"R{draw(st.integers(0, 10**9))}_{depth}"
    return {"type": "record", "name": name,
            "fields": [{"name": f"f{i}",
                        "type": draw(_schemas(depth=depth + 1))}
                       for i in range(n_fields)]}


def _values(schema):
    """Strategy for one value conforming to ``schema``."""
    if isinstance(schema, list):  # nullable union
        return st.none() | _values(schema[1])
    if isinstance(schema, str):
        return {
            "boolean": st.booleans(),
            "int": st.integers(-(2**31), 2**31 - 1),
            "long": st.integers(-(2**63), 2**63 - 1),
            "float": st.floats(allow_nan=False, width=32),
            "double": st.floats(allow_nan=False),
            "string": st.text(max_size=20),
            "bytes": st.binary(max_size=20),
        }[schema]
    t = schema["type"]
    if t == "array":
        return st.lists(_values(schema["items"]), max_size=4)
    if t == "map":
        return st.dictionaries(st.text(max_size=8), _values(schema["values"]),
                               max_size=4)
    if t == "record":
        return st.fixed_dictionaries(
            {f["name"]: _values(f["type"]) for f in schema["fields"]})
    raise AssertionError(schema)


@st.composite
def _schema_and_records(draw):
    field_schemas = [draw(_schemas(depth=1)) for _ in range(draw(st.integers(1, 3)))]
    schema = {"type": "record", "name": "Top",
              "fields": [{"name": f"c{i}", "type": s}
                         for i, s in enumerate(field_schemas)]}
    recs = draw(st.lists(_values(schema), min_size=1, max_size=5))
    return schema, recs


@settings(max_examples=60, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(data=_schema_and_records(), codec=st.sampled_from(["null", "deflate"]))
def test_container_roundtrip(tmp_path_factory, data, codec):
    schema, recs = data
    path = str(tmp_path_factory.mktemp("avro") / "t.avro")
    n = avro_io.write_container(path, schema, iter(recs), codec=codec)
    assert n == len(recs)
    got = list(avro_io.read_container(path))
    assert got == recs
    assert avro_io.read_schema(path)["name"] == "Top"


@settings(max_examples=40, deadline=None)
@given(n=st.integers(-(2**63), 2**63 - 1))
def test_long_zigzag_roundtrip(n):
    out = bytearray()
    avro_io._encode_long(n, out)
    val, pos = avro_io._decode_long(memoryview(bytes(out)), 0)
    assert val == n and pos == len(out)


def test_feature_key_rejects_separator_in_name():
    """U+001F is the reserved name/term separator in interned keys; a name
    containing it would decode ambiguously (hypothesis found this via the
    cross-decoder test below).  The ingest paths (reader, index driver) all
    key through feature_key, so the loud raise guards them all."""
    from photon_ml_tpu.data.index_map import feature_key, split_key

    with pytest.raises(ValueError, match="reserved key separator"):
        feature_key("bad\x1fname")
    assert split_key(feature_key("n", "t\x1fstill_fine")) == ("n", "t\x1fstill_fine")


# names exclude the reserved U+001F separator (contract tested above)
_NAMES = st.text(max_size=6).filter(lambda s: "\x1f" not in s)


@settings(max_examples=20, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(rows=st.lists(
    st.tuples(st.floats(allow_nan=False, width=32),          # label
              st.lists(st.tuples(_NAMES,                     # feature name
                                 st.floats(allow_nan=False, width=32)),
                       max_size=4)),
    min_size=1, max_size=6))
def test_training_example_matches_native_decoder(tmp_path_factory, rows):
    """The Python codec and the independent C++ columnar decoder must agree
    on TRAINING_EXAMPLE containers (two implementations, one wire format)."""
    from photon_ml_tpu.data import native_avro
    from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE

    if not native_avro.native_available():
        pytest.skip("native decoder not built")
    path = str(tmp_path_factory.mktemp("avro") / "t.avro")
    recs = [{"uid": str(i), "label": lab, "weight": 1.0, "offset": 0.0,
             "response": float(lab),
             "features": [{"name": nm, "term": "", "value": v}
                          for nm, v in feats],
             "metadataMap": {}}
            for i, (lab, feats) in enumerate(rows)]
    avro_io.write_container(path, TRAINING_EXAMPLE, iter(recs))
    py = list(avro_io.read_container(path))
    assert native_avro.schema_eligible(path)
    native_avro.clear_columnar_cache()
    cols = native_avro.load_columnar(path)
    assert py == recs and cols.n == len(recs)
    np.testing.assert_allclose(
        cols.numeric["label"],
        np.asarray([r["label"] for r in recs], np.float64), rtol=1e-6)
    # feature parity: counts per row and values in row-major order
    np.testing.assert_array_equal(
        cols.feat_counts, [len(r["features"]) for r in recs])
    np.testing.assert_allclose(
        cols.feat_values,
        np.asarray([f["value"] for r in recs for f in r["features"]],
                   np.float64), rtol=1e-6)
    got_names = [cols.feat_table[i].split("\x1f")[0] for i in cols.feat_ids]
    assert got_names == [f["name"] for r in recs for f in r["features"]]
