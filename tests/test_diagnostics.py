"""Diagnostics subsystem tests (reference photon-diagnostics, SURVEY.md §2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import logistic_loss, squared_loss
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.diagnostics import (
    Document, bootstrap_training, expected_magnitude_importance,
    fitting_diagnostic, hosmer_lemeshow, kendall_tau_analysis, render_html,
    render_text, variance_importance)
from photon_ml_tpu.diagnostics.bootstrap import bagged_model, bootstrap_weights
from photon_ml_tpu.diagnostics.reporting import Plot, Table, Text
from photon_ml_tpu.models.glm import Coefficients, GLMModel
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.types import TaskType


def _linear_batch(rng, n=512, d=4, noise=0.05):
    x = rng.normal(size=(n, d)).astype(np.float64)
    w_true = np.arange(1, d + 1, dtype=np.float64)
    y = x @ w_true + noise * rng.normal(size=n)
    return DenseBatch(x=jnp.asarray(x), y=jnp.asarray(y),
                      offset=jnp.zeros(n), weight=jnp.ones(n)), w_true


def _linear_train_fn():
    obj = GLMObjective(loss=squared_loss, reg=Regularization(l2=1e-6))
    solve = jax.jit(make_solver(obj))

    def train(batch):
        res = solve(jnp.zeros(batch.dim, batch.x.dtype), batch)
        return GLMModel(coefficients=Coefficients(means=np.asarray(res.w)),
                        task=TaskType.LINEAR_REGRESSION)

    return train


class TestBootstrap:
    def test_weights_preserve_total_and_padding(self, rng):
        w = np.ones(100)
        w[-10:] = 0.0
        bw = bootstrap_weights(rng, w)
        assert bw[-10:].sum() == 0.0
        assert bw.sum() == pytest.approx(90.0)  # multinomial total = n alive

    def test_intervals_cover_truth(self, rng):
        batch, w_true = _linear_batch(rng)
        report = bootstrap_training(_linear_train_fn(), batch, num_replicates=16,
                                    seed=3)
        lo, hi = report.coefficient_intervals[:, 0], report.coefficient_intervals[:, 1]
        # a 95% CI can marginally miss per-coordinate; allow noise-scale slack
        assert np.all(lo - 0.05 <= w_true) and np.all(w_true <= hi + 0.05)
        assert np.all(lo < hi)
        assert np.all(hi - lo < 0.5)  # tight on easy data
        bag = bagged_model(report, TaskType.LINEAR_REGRESSION)
        np.testing.assert_allclose(bag.coefficients.means, w_true, atol=0.1)

    def test_metric_distributions(self, rng):
        batch, _ = _linear_batch(rng, n=128)

        def rmse_on_train(model):
            pred = np.asarray(model.predict(batch.x))
            return float(np.sqrt(np.mean((pred - np.asarray(batch.y)) ** 2)))

        report = bootstrap_training(_linear_train_fn(), batch, num_replicates=4,
                                    metrics={"rmse": rmse_on_train}, seed=0)
        assert report.metric_distributions["rmse"].shape == (4,)
        mean, std = report.metric_summary()["rmse"]
        assert 0 <= mean < 0.2


class TestFitting:
    def test_learning_curves(self, rng):
        train_batch, _ = _linear_batch(rng, n=400, noise=0.5)
        holdout, _ = _linear_batch(rng, n=200, noise=0.5)

        def rmse(model, batch):
            w = np.asarray(batch.weight)
            pred = np.asarray(model.predict(batch.x))
            err = (pred - np.asarray(batch.y)) ** 2
            return float(np.sqrt((w * err).sum() / w.sum()))

        report = fitting_diagnostic(_linear_train_fn(), {"rmse": rmse},
                                    train_batch, holdout,
                                    fractions=(0.1, 0.5, 1.0), seed=1)
        assert report.train_metrics["rmse"].shape == (3,)
        # more data -> holdout metric should improve (or stay flat)
        h = report.holdout_metrics["rmse"]
        assert h[-1] <= h[0] + 0.05


class TestHosmerLemeshow:
    def test_calibrated_vs_miscalibrated(self, rng):
        n = 20000
        p = rng.uniform(0.05, 0.95, size=n)
        y = (rng.random(n) < p).astype(np.float64)
        good = hosmer_lemeshow(p, y)
        assert good.p_value > 1e-3  # calibrated: cannot reject
        # squash probabilities toward 0.5 -> miscalibrated
        bad = hosmer_lemeshow(0.5 + 0.25 * (p - 0.5), y)
        assert bad.chi_square > good.chi_square * 5
        assert bad.p_value < 1e-6
        assert good.totals.sum() == pytest.approx(n)
        assert "chi2=" in good.summary()

    def test_equal_width_bins(self, rng):
        p = rng.uniform(0, 1, size=1000)
        y = (rng.random(1000) < p).astype(np.float64)
        rep = hosmer_lemeshow(p, y, num_bins=5, equal_mass=False)
        assert len(rep.totals) == 5


class TestFeatureImportance:
    def test_rankings(self):
        w = np.array([0.1, -2.0, 0.5])
        mean_abs = np.array([1.0, 1.0, 1.0])
        var = np.array([1.0, 0.01, 4.0])
        em = expected_magnitude_importance(w, mean_abs, feature_names=["a", "b", "c"])
        assert em.ranked[0][0] == "b"
        vi = variance_importance(w, var)
        # w^2*var: a=0.01, b=0.04, c=1.0
        assert vi.ranked[0][0] == "2"
        assert "\t" in em.summary()


class TestKendallTau:
    def test_independent_vs_dependent(self, rng):
        n = 2000
        pred = rng.normal(size=n)
        indep = kendall_tau_analysis(pred, pred + rng.normal(size=n))
        assert abs(indep.tau) < 0.05
        # monotone residual structure: error grows with prediction
        # (tau is a rank statistic — it detects monotone dependence)
        dep = kendall_tau_analysis(pred, pred * 1.5 + 0.1 * rng.normal(size=n))
        assert abs(dep.tau) > 0.3
        assert indep.num_samples == n

    def test_subsampling(self, rng):
        pred = rng.normal(size=500)
        rep = kendall_tau_analysis(pred, pred + rng.normal(size=500), max_samples=100)
        assert rep.num_samples == 100


class TestReporting:
    def test_render_html_and_text(self):
        doc = Document("GLM diagnostics")
        ch = doc.chapter("Fit quality")
        sec = ch.section("Learning curve")
        sec.add(Text("train vs holdout RMSE"))
        sec.add(Table(["fraction", "rmse"], [["0.1", "1.2"], ["1.0", "0.9"]]))
        sec.add(Plot("rmse", [0.1, 0.5, 1.0],
                     {"train": [1.0, 0.8, 0.7], "holdout": [1.2, 1.0, 0.9]}))
        html_out = render_html(doc)
        assert '<h2 id="s1">1. Fit quality</h2>' in html_out
        # index page links to every chapter/section anchor
        assert '<a href="#s1">' in html_out and '<a href="#s1-1">' in html_out
        assert "<svg" in html_out and "polyline" in html_out
        text_out = render_text(doc)
        assert "1.1. Learning curve" in text_out and "[plot] rmse" in text_out

    def test_nested_sections_numbered_lists_and_references(self):
        """Reference reporting parity: sections NEST with recursive x.y.z
        numbering (NumberingContext), NumberedList renders ordered, and
        Reference items resolve labels to anchors in HTML / section numbers
        in text (ReferencePhysicalReport)."""
        from photon_ml_tpu.diagnostics.reporting import NumberedList, Reference

        doc = Document("Nested")
        ch = doc.chapter("Comparison", label="cmp")
        sec = ch.section("Per coordinate")
        sub = sec.subsection("l2 = 0.1", label="w01")
        sub.add(Text("small lambda"))
        subsub = sub.subsection("details")
        subsub.add(NumberedList(["first", "second"]))
        other = doc.chapter("Appendix")
        other.section("Links").add(Reference("w01", "the small-lambda fit"))
        other.sections[0].add(Reference("missing-label"))

        html_out = render_html(doc)
        # nested numbering + anchors: chapter 1, section 1.1, sub 1.1.1,
        # subsub 1.1.1.1 — all present in body AND in the index
        assert '<h3 id="s1-1">1.1. Per coordinate</h3>' in html_out
        assert '<h4 id="s1-1-1">1.1.1. l2 = 0.1</h4>' in html_out
        assert '<h5 id="s1-1-1-1">1.1.1.1. details</h5>' in html_out
        assert html_out.count('href="#s1-1-1"') == 2  # index + reference
        assert "<ol><li>first</li><li>second</li></ol>" in html_out
        assert "the small-lambda fit" in html_out
        assert "[unresolved reference missing-label]" in html_out

        text_out = render_text(doc)
        assert "1.1.1. l2 = 0.1" in text_out
        assert "1.1.1.1. details" in text_out
        assert "  1. first" in text_out and "  2. second" in text_out
        assert "see §1.1.1 l2 = 0.1 (the small-lambda fit)" in text_out
        assert "[unresolved reference missing-label]" in text_out
