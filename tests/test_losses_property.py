"""Property tests: hand-written loss derivatives vs jax autodiff.

Every solver consumes the closed-form d1/d2 (the reference's
PointwiseLossFunction derivatives); a sign or factor slip there corrupts
every fit while tests on final models may still converge somewhere
plausible.  Hypothesis drives margins/labels through d1 == grad(loss) and
d2 == grad(grad(loss)) for all four losses, plus the log1p_exp overflow
guard at extreme margins.
"""

import os
import sys

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from photon_ml_tpu.core.losses import (log1p_exp, logistic_loss,  # noqa: E402
                                       poisson_loss, smoothed_hinge_loss,
                                       squared_loss)

_Z = st.floats(min_value=-30, max_value=30, allow_nan=False)


def _labels_for(loss):
    if loss in (logistic_loss, smoothed_hinge_loss):
        return st.sampled_from([0.0, 1.0])
    if loss is poisson_loss:
        return st.integers(0, 20).map(float)
    return st.floats(-5, 5, allow_nan=False)


@pytest.mark.parametrize("loss", [logistic_loss, squared_loss, poisson_loss,
                                  smoothed_hinge_loss], ids=lambda l: l.name)
def test_d1_d2_match_autodiff(loss):
    @settings(max_examples=80, deadline=None)
    @given(z=_Z, data=st.data())
    def check(z, data):
        y = data.draw(_labels_for(loss))
        z_j, y_j = jnp.asarray(z), jnp.asarray(y)
        g = jax.grad(lambda zz: loss.loss(zz, y_j))(z_j)
        h = jax.grad(jax.grad(lambda zz: loss.loss(zz, y_j)))(z_j)
        np.testing.assert_allclose(float(loss.d1(z_j, y_j)), float(g),
                                   rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(float(loss.d2(z_j, y_j)), float(h),
                                   rtol=1e-8, atol=1e-10)
        l, d1 = loss.loss_and_d1(z_j, y_j)
        np.testing.assert_allclose(float(l), float(loss.loss(z_j, y_j)),
                                   rtol=1e-12)
        np.testing.assert_allclose(float(d1), float(loss.d1(z_j, y_j)),
                                   rtol=1e-12)

    check()


@settings(max_examples=60, deadline=None)
@given(z=st.floats(min_value=-1e4, max_value=1e4, allow_nan=False))
def test_log1p_exp_finite_and_exact(z):
    """Reference MathUtils log1pExp guard: finite at extreme margins, exact
    against the naive form where the naive form is itself stable."""
    got = float(log1p_exp(jnp.asarray(z)))
    assert np.isfinite(got)
    if abs(z) < 30:
        np.testing.assert_allclose(got, float(np.log1p(np.exp(z))), rtol=1e-12)
    assert got >= max(z, 0.0) - 1e-9  # log(1+e^z) >= max(z, 0)


@settings(max_examples=40, deadline=None)
@given(z=_Z, data=st.data())
def test_d2_nonnegative_convexity(z, data):
    """All four losses are convex in the margin; d2 must never go negative
    (a negative curvature would break TRON's model trust entirely)."""
    for loss in (logistic_loss, squared_loss, poisson_loss,
                 smoothed_hinge_loss):
        y = data.draw(_labels_for(loss))
        assert float(loss.d2(jnp.asarray(z), jnp.asarray(y))) >= 0.0
