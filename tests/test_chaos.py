"""photonchaos tests: the deterministic fault-injection plane and the
health/readiness surfaces it exists to exercise.

The contracts under test (ISSUE 14):
  - FaultInjector: disabled is a single boolean check that counts
    nothing; schedules (Nth hit, seeded probability, timed window,
    max_fires) are deterministic — same arm + same call sequence = same
    fires; ``to_error`` maps kinds onto the exception each seam expects.
  - Schedule: ``build_schedule`` is a pure function of the seed, and its
    coverage pass hits every fault class once.
  - HealthState/Watchdog: push conditions and pull checks aggregate into
    one ready bit; a raising probe counts as failed; a worker wedged
    mid-item (or dead) flips readiness and recovery flips it back.
  - DeltaLog degradation (satellite 2): a failed append — including a
    TORN one that got half a frame onto disk — leaves the segment
    truncated at the last valid frame boundary and appendable in place;
    ``healthy`` flips False and back True on the next landed append.
  - publish_delta degradation: a blocked publish rolls the in-memory
    apply back BITWISE, burns no identity, counts
    ``delta_publish_blocked_total{reason=...}``, and serving continues.
  - /readyz over real HTTP: 503 while a REAL injected fault holds the
    log degraded, 200 again after the heal append — plus the vacuous
    and push-condition paths.
  - LogFollower (satellite 1): failed follow passes are counted
    (``catchup_follow_errors_total``), back off, and reset on success.
  - stream.decode seam: injected corrupt/slow chunks flow through the
    exact on_error raise/skip contract a real bad chunk takes.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.chaos import (FAULT_CLASSES, FaultInjector, HealthState,
                                 InjectedCrash, InjectedFault, Watchdog,
                                 build_schedule, delta_log_check, fault,
                                 follower_staleness_check, get_injector,
                                 set_injector)
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.obs.registry import MetricsRegistry
from photon_ml_tpu.online.catchup import LogFollower
from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord
from photon_ml_tpu.serving.batcher import BucketedBatcher, Request
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.frontend.metrics_http import \
    ThreadedMetricsEndpoint
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.stream.chunks import Chunk
from photon_ml_tpu.stream.pipeline import ChunkPipeline
from photon_ml_tpu.types import TaskType

N_ENT = 16
D = 4
NAMES = [f"f{j}" for j in range(D)]


@pytest.fixture(autouse=True)
def _pristine_injector():
    """The process-wide injector must never leak arms between tests."""
    get_injector().reset()
    yield
    get_injector().reset()


def _rec(g, v, entity="user1", row=None):
    return DeltaRecord(generation=g, delta_version=v, cid="user",
                       entity=entity,
                       row=tuple(row if row is not None else
                                 np.arange(D, dtype=float) + v))


def _engine(seed=0, max_batch=8):
    rng = np.random.default_rng(seed)
    task = TaskType.LOGISTIC_REGRESSION
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(means=rng.normal(size=D)),
            feature_shard="all", task=task),
        "user": RandomEffectModel(
            w_stack=rng.normal(size=(N_ENT, D)) * 0.5,
            slot_of={i: i for i in range(N_ENT)},
            random_effect_type="userId", feature_shard="all", task=task),
    })
    imap = IndexMap({feature_key(n): j for j, n in enumerate(NAMES)})
    eidx = EntityIndex()
    for i in range(N_ENT):
        eidx.get_or_add(f"user{i}")
    metrics = ServingMetrics()
    store = CoefficientStore.from_model(
        model, task, {"userId": eidx}, {"all": imap},
        config=StoreConfig(device_capacity=None), version="synthetic",
        metrics=metrics)
    eng = ScoringEngine(store, BucketedBatcher(max_batch), metrics=metrics)
    eng.warm()
    return eng


def _req(rng, uid, user):
    feats = [{"name": n, "term": "", "value": float(v)}
             for n, v in zip(NAMES, rng.normal(size=D))]
    return Request(uid=uid, features=feats, ids={"userId": f"user{user}"})


def _get(port, path):
    try:
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10)
        return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------------------------------------------------------------------
# injector core
# ---------------------------------------------------------------------------
class TestFaultInjector:
    def test_disabled_is_noop_and_counts_nothing(self):
        inj = get_injector()
        assert inj.enabled is False
        assert fault("never.armed") is None
        # the fast path returned before the lock: not even a hit counted
        assert inj.hits("never.armed") == 0

    def test_default_arm_fires_on_every_hit(self):
        inj = FaultInjector()
        inj.arm("p")
        assert all(inj.check("p") is not None for _ in range(5))
        assert inj.fired("p") == 5
        assert inj.hits("p") == 5

    def test_nth_hit(self):
        inj = FaultInjector()
        inj.arm("p", "drop", nth=3)
        assert [inj.check("p") is not None for _ in range(5)] == \
            [False, False, True, False, False]

    def test_nth_repeat(self):
        inj = FaultInjector()
        inj.arm("p", nth=2, repeat=True)
        assert [inj.check("p") is not None for _ in range(6)] == \
            [False, True, False, True, False, True]

    def test_nth_zero_rejected(self):
        with pytest.raises(ValueError, match="nth"):
            FaultInjector().arm("p", nth=0)

    def test_max_fires_caps_any_schedule(self):
        inj = FaultInjector()
        inj.arm("p", max_fires=2)
        pattern = [inj.check("p") is not None for _ in range(5)]
        assert pattern == [True, True, False, False, False]
        assert inj.fired("p") == 2

    def test_probability_deterministic_per_seed(self):
        def run():
            inj = FaultInjector()
            inj.arm("p", probability=0.5, seed=123)
            return [inj.check("p") is not None for _ in range(40)]

        a, b = run(), run()
        assert a == b
        assert True in a and False in a  # an actual mix, not all-or-none

    def test_window_gates_on_time_since_arm(self):
        inj = FaultInjector()
        inj.arm("p", window=(10.0, 1.0))  # opens 10s from now
        assert inj.check("p") is None
        inj.arm("p", window=(0.0, 30.0))  # open now
        assert inj.check("p") is not None

    def test_rearm_replaces_and_disarm_restores_fast_path(self):
        inj = FaultInjector()
        inj.arm("p", nth=1)
        assert inj.check("p") is not None
        inj.arm("p", "drop")  # replaces: fires counter starts over
        assert inj.fired("p") == 0
        inj.disarm("p")
        assert inj.enabled is False
        # hit counters survive disarm (coverage assertions); reset clears
        assert inj.hits("p") == 1
        inj.reset()
        assert inj.hits("p") == 0

    def test_action_carries_point_kind_data(self):
        inj = FaultInjector()
        inj.arm("p", "stall", data={"stall_s": 0.03})
        act = inj.check("p")
        assert (act.point, act.kind, act.data) == \
            ("p", "stall", {"stall_s": 0.03})

    def test_to_error_mapping(self):
        import errno

        from photon_ml_tpu.chaos import FaultAction
        assert FaultAction("p", "enospc").to_error().errno == errno.ENOSPC
        assert FaultAction("p", "torn").to_error().errno == errno.EIO
        assert isinstance(FaultAction("p", "crash").to_error(),
                          InjectedCrash)
        assert isinstance(FaultAction("p", "drop").to_error(),
                          ConnectionResetError)
        assert isinstance(FaultAction("p", "disconnect").to_error(),
                          ConnectionResetError)
        assert isinstance(FaultAction("p", "garbage").to_error(),
                          InjectedFault)

    def test_fires_counted_in_registry(self):
        reg = MetricsRegistry()
        inj = FaultInjector(registry=reg)
        inj.arm("delta_log.append", "enospc")
        inj.check("delta_log.append")
        assert reg.counter("chaos_faults_fired_total",
                           point="delta_log.append", kind="enospc") == 1

    def test_set_injector_swaps_the_module_entry_point(self):
        mine = FaultInjector()
        mine.arm("p", "drop")
        prev = set_injector(mine)
        try:
            act = fault("p")
            assert act is not None and act.kind == "drop"
        finally:
            set_injector(prev)
        assert fault("p") is None  # the original injector is disabled


# ---------------------------------------------------------------------------
# seeded schedule
# ---------------------------------------------------------------------------
class TestStallDist:
    def test_each_fire_samples_its_own_hold(self):
        inj = FaultInjector()
        inj.arm("p", "stall_dist")
        holds = [inj.check("p").data["stall_s"] for _ in range(8)]
        assert len(set(holds)) > 1          # a distribution, not a pulse
        assert all(0.0 < h <= 0.25 for h in holds)

    def test_seeded_same_call_sequence_same_holds(self):
        a, b = FaultInjector(), FaultInjector()
        a.arm("p", "stall_dist", seed=7)
        b.arm("p", "stall_dist", seed=7)
        assert [a.check("p").data["stall_s"] for _ in range(6)] == \
            [b.check("p").data["stall_s"] for _ in range(6)]
        c = FaultInjector()
        c.arm("p", "stall_dist", seed=8)
        assert [c.check("p").data["stall_s"] for _ in range(6)] != \
            [a.check("p").data["stall_s"] for _ in range(6)]

    def test_cap_and_distribution_overrides(self):
        import math
        inj = FaultInjector()
        inj.arm("p", "stall_dist",
                data={"mu": math.log(10.0), "sigma": 0.1, "cap_s": 0.07})
        # median 10s holds all land on the cap
        assert all(inj.check("p").data["stall_s"] == 0.07
                   for _ in range(5))
        inj.arm("p", "stall_dist", data={"mu": math.log(0.01),
                                         "sigma": 0.05})
        holds = [inj.check("p").data["stall_s"] for _ in range(8)]
        assert all(0.005 < h < 0.02 for h in holds)

    def test_armed_rule_data_not_mutated_by_sampling(self):
        inj = FaultInjector()
        data = {"cap_s": 0.05}
        inj.arm("p", "stall_dist", data=data)
        act = inj.check("p")
        assert "stall_s" in act.data
        assert data == {"cap_s": 0.05}      # per-fire copy, not in place
        assert "stall_s" not in inj.check("p").data or \
            inj.check("p").data is not act.data

    def test_catalog_carries_repl_stall_dist(self):
        assert FAULT_CLASSES["repl_stall_dist"] == \
            ("repl.server.send", "stall_dist")
        assert len(FAULT_CLASSES) == 10

    def test_serve_execute_seam_holds_scoring_and_counts_in_latency(self):
        import math
        eng = _engine()
        rng = np.random.default_rng(0)
        reqs = [_req(rng, i, i % N_ENT) for i in range(8)]
        eng.score_requests(reqs)            # warm + baseline observation
        inj = get_injector()
        inj.arm("serve.execute", "stall_dist", max_fires=1,
                data={"mu": math.log(0.08), "sigma": 0.01, "cap_s": 0.1})
        before = eng.metrics.registry.histogram_state_series(
            "serving_latency_s")
        total_before = sum(st["total"] for st in before.values())
        t0 = time.perf_counter()
        stalled = eng.score_requests(reqs)
        held = time.perf_counter() - t0
        assert inj.fired("serve.execute") == 1
        assert held >= 0.05                 # the hold really blocked
        after = eng.metrics.registry.histogram_state_series(
            "serving_latency_s")
        total_after = sum(st["total"] for st in after.values())
        # the SLO's latency source saw the stall, not just the wall clock
        assert total_after - total_before >= 0.05
        # requests still succeed: a stall degrades, never errors
        inj.reset()
        clean = eng.score_requests(reqs)
        np.testing.assert_allclose(np.asarray(stalled), np.asarray(clean))


class TestSchedule:
    def test_pure_function_of_seed(self):
        assert build_schedule(5, 12) == build_schedule(5, 12)
        assert build_schedule(5, 12) != build_schedule(6, 12)

    def test_coverage_pass_hits_every_class_once(self):
        n = len(FAULT_CLASSES)
        ev = build_schedule(0, n + 3)
        assert sorted(e.fault_class for e in ev[:n]) == \
            sorted(FAULT_CLASSES)
        assert all(e.fault_class in FAULT_CLASSES for e in ev[n:])

    def test_events_match_the_catalog(self):
        for e in build_schedule(3, 14):
            point, kind = FAULT_CLASSES[e.fault_class]
            assert (e.point, e.kind) == (point, kind)
            if kind == "stall":
                assert 0.02 <= e.data["stall_s"] <= 0.10
            else:
                assert e.data == {}


# ---------------------------------------------------------------------------
# health state + watchdog
# ---------------------------------------------------------------------------
class TestHealthState:
    def test_push_conditions_and_pull_checks_aggregate(self):
        reg = MetricsRegistry()
        h = HealthState(registry=reg)
        h.set_condition("warm", True, "compiled")
        ready, checks = h.readyz()
        assert ready and checks["warm"]["ok"]
        h.add_check("probe", lambda: (False, "it hurts"))
        ready, checks = h.readyz()
        assert not ready
        assert checks["probe"] == {"ok": False, "detail": "it hurts"}
        assert reg.gauge("health_ready") == 0.0
        assert reg.gauge("health_check_ok", check="probe") == 0.0
        assert reg.gauge("health_check_ok", check="warm") == 1.0

    def test_raising_probe_counts_as_failed(self):
        h = HealthState()
        h.add_check("boom", lambda: 1 / 0)
        ready, checks = h.readyz()
        assert not ready
        assert "check raised" in checks["boom"]["detail"]

    def test_condition_flip_recovers(self):
        h = HealthState()
        h.set_condition("warm", False, "warming")
        assert h.readyz()[0] is False
        h.set_condition("warm", True, "done")
        assert h.readyz()[0] is True


class TestWatchdog:
    def test_overlong_busy_item_stalls_and_recovers(self):
        reg = MetricsRegistry()
        wd = Watchdog(stall_after_s=0.02, registry=reg)
        w = wd.register("flusher")
        assert wd.check()[0] is True
        with w.busy():
            time.sleep(0.05)
            ok, detail = wd.check()
            assert not ok and "in flight" in detail
            assert reg.gauge("worker_stalled", worker="flusher") == 1.0
        ok, detail = wd.check()
        assert ok and "healthy" in detail
        assert reg.gauge("worker_stalled", worker="flusher") == 0.0

    def test_beat_restamps_a_legitimately_long_item(self):
        wd = Watchdog(stall_after_s=0.04)
        w = wd.register("shipper")
        with w.busy():
            time.sleep(0.03)
            w.beat()  # still making progress
            time.sleep(0.02)
            assert wd.check()[0] is True

    def test_dead_thread_is_stalled(self):
        wd = Watchdog(stall_after_s=10.0)
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        wd.register("worker", t)
        ok, detail = wd.check()
        assert not ok and "not alive" in detail

    def test_per_worker_stall_override(self):
        wd = Watchdog(stall_after_s=10.0)
        w = wd.register("slow-ok", stall_after_s=0.01)
        with w.busy():
            time.sleep(0.03)
            assert wd.check()[0] is False

    def test_health_integration(self):
        h = HealthState()
        wd = Watchdog(stall_after_s=0.01)
        h.add_check("workers", wd.check)
        w = wd.register("w")
        assert h.readyz()[0] is True
        with w.busy():
            time.sleep(0.03)
            assert h.readyz()[0] is False
        assert h.readyz()[0] is True


# ---------------------------------------------------------------------------
# delta-log degradation (satellite 2)
# ---------------------------------------------------------------------------
class TestDeltaLogDegradation:
    def test_torn_append_truncates_to_last_frame_boundary(self, tmp_path):
        """THE regression: a mid-frame write failure must leave the
        segment appendable — half a frame on disk would poison every
        later append for replay."""
        reg = MetricsRegistry()
        log = DeltaLog(str(tmp_path), fsync="never", registry=reg)
        log.append(_rec(1, 1))
        seg_path = log.segments()[0][1]
        size_before = os.path.getsize(seg_path)

        get_injector().arm("delta_log.append", "torn", max_fires=1)
        with pytest.raises(OSError):
            log.append(_rec(1, 2))

        assert log.healthy is False
        assert log.write_errors == 1
        assert reg.counter("delta_log_write_errors_total") == 1
        # the torn half-frame was truncated away, byte for byte
        assert os.path.getsize(seg_path) == size_before
        assert [r.identity for r in log.replay()] == [(1, 1)]

        # the SAME writer resumes in place once the disk heals
        log.append(_rec(1, 2))
        assert log.healthy is True
        assert [r.identity for r in log.replay()] == [(1, 1), (1, 2)]
        log.close()

    def test_enospc_append_leaves_log_appendable(self, tmp_path):
        reg = MetricsRegistry()
        log = DeltaLog(str(tmp_path), fsync="never", registry=reg)
        log.append(_rec(1, 1))
        get_injector().arm("delta_log.append", "enospc", max_fires=1)
        with pytest.raises(OSError) as ei:
            log.append(_rec(1, 2))
        import errno
        assert ei.value.errno == errno.ENOSPC
        assert not log.healthy and log.write_errors == 1
        log.append(_rec(1, 2))
        assert log.healthy
        assert [r.identity for r in log.replay()] == [(1, 1), (1, 2)]
        log.close()

    def test_fsync_failure_degrades_too(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="always")
        get_injector().arm("delta_log.fsync", "enospc", max_fires=1)
        with pytest.raises(OSError):
            log.append(_rec(1, 1))
        assert not log.healthy
        log.append(_rec(1, 1))  # identity was never consumed
        assert log.healthy
        log.close()


class TestPublishDegradation:
    def test_blocked_publish_rolls_back_bitwise_and_heals(self, tmp_path):
        eng = _engine()
        log = DeltaLog(str(tmp_path), fsync="never")
        swapper = HotSwapper(eng, delta_log=log)
        rng = np.random.default_rng(0)
        dim = eng.store.coordinates["user"].dim

        id1 = swapper.publish_delta("user", "user1",
                                    rng.normal(size=dim))
        assert id1 is not None

        store = eng.store
        c = store.coordinates["user"]
        eid = store.entity_id(c.random_effect_type, "user1")
        before = np.array(c.dense_row(eid), copy=True)
        v_before = swapper.delta_version
        probe = [_req(np.random.default_rng(7), 0, 1)]
        score_before = [float(s) for s in eng.score_requests(probe)]

        get_injector().arm("delta_log.append", "enospc", max_fires=1)
        blocked = swapper.publish_delta("user", "user1",
                                        rng.normal(size=dim))
        assert blocked is None
        # the in-memory apply was rolled back bitwise; no identity burned
        assert np.array_equal(before, np.asarray(c.dense_row(eid)))
        assert swapper.delta_version == v_before
        assert eng.metrics.registry.counter(
            "delta_publish_blocked_total", reason="log_append") == 1
        assert not log.healthy
        # serving CONTINUES on the pre-fault coefficients
        assert [float(s) for s in eng.score_requests(probe)] == \
            score_before

        # heal: the next publish lands with the NEXT identity — the
        # blocked one left no gap in the chain
        id2 = swapper.publish_delta("user", "user1",
                                    rng.normal(size=dim))
        assert id2 == (id1[0], id1[1] + 1)
        assert log.healthy
        assert [r.identity for r in log.replay()] == [id1, id2]
        log.close()


# ---------------------------------------------------------------------------
# /healthz + /readyz over real HTTP
# ---------------------------------------------------------------------------
class TestReadyzHttp:
    def test_healthz_always_alive_readyz_vacuous_without_health(self):
        ep = ThreadedMetricsEndpoint(ServingMetrics()).start()
        try:
            code, body = _get(ep.port, "/healthz")
            assert code == 200 and json.loads(body)["alive"] is True
            code, body = _get(ep.port, "/readyz")
            assert code == 200 and json.loads(body)["ready"] is True
        finally:
            ep.stop()

    def test_readyz_follows_push_conditions(self):
        m = ServingMetrics()
        h = HealthState(registry=m.registry)
        h.set_condition("engine_warmed", False, "warming")
        ep = ThreadedMetricsEndpoint(m, health=h).start()
        try:
            code, body = _get(ep.port, "/readyz")
            assert code == 503
            obj = json.loads(body)
            assert obj["ready"] is False
            assert obj["checks"]["engine_warmed"]["ok"] is False
            assert _get(ep.port, "/healthz")[0] == 200  # alive regardless
            h.set_condition("engine_warmed", True, "compiled")
            code, body = _get(ep.port, "/readyz")
            assert code == 200 and json.loads(body)["ready"] is True
        finally:
            ep.stop()

    def test_readyz_degrades_on_real_injected_fault_and_recovers(
            self, tmp_path):
        """The acceptance path: a REAL fault through the injector flips
        /readyz to 503; the heal append flips it back."""
        m = ServingMetrics()
        log = DeltaLog(str(tmp_path), fsync="never", registry=m.registry)
        h = HealthState(registry=m.registry)
        h.add_check("delta_log", delta_log_check(log))
        ep = ThreadedMetricsEndpoint(m, health=h).start()
        try:
            log.append(_rec(1, 1))
            assert _get(ep.port, "/readyz")[0] == 200

            get_injector().arm("delta_log.append", "enospc", max_fires=1)
            with pytest.raises(OSError):
                log.append(_rec(1, 2))
            code, body = _get(ep.port, "/readyz")
            assert code == 503
            checks = json.loads(body)["checks"]
            assert "degraded" in checks["delta_log"]["detail"]

            log.append(_rec(1, 2))  # the disk healed
            assert _get(ep.port, "/readyz")[0] == 200
        finally:
            ep.stop()
            log.close()


# ---------------------------------------------------------------------------
# follower backoff (satellite 1)
# ---------------------------------------------------------------------------
class TestCatchupBackoff:
    def test_follow_errors_counted_backoff_resets_on_success(
            self, tmp_path):
        reg = MetricsRegistry()
        log = DeltaLog(str(tmp_path), fsync="never")
        log.append(_rec(1, 1))
        broken = {"on": True}

        class _Store:
            generation = 1

            def apply_delta(self, cid, entity, row):
                if broken["on"]:
                    raise RuntimeError("injected store failure")
                return True

        store = _Store()
        f = LogFollower(log, lambda: store, poll_interval_s=0.005,
                        registry=reg, backoff_max_s=0.05)
        fsc = follower_staleness_check(f, bound_s=5.0)
        assert fsc() == (False, "catch-up has not completed yet")
        f.start()
        try:
            deadline = time.monotonic() + 10
            while f.errors_total < 3 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert f.errors_total >= 3
            assert f.consecutive_errors >= 3
            assert f.last_success_at is None
            assert reg.counter("catchup_follow_errors_total") >= 3
            assert fsc()[0] is False

            broken["on"] = False  # heal
            deadline = time.monotonic() + 10
            while f.last_success_at is None and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            assert f.last_success_at is not None
            assert f.consecutive_errors == 0  # backoff reset
            assert f.position == (1, 1)
            ok, detail = fsc()
            assert ok and "fresh" in detail
        finally:
            f.stop()
            log.close()

    def test_watch_wraps_follow_passes(self, tmp_path):
        log = DeltaLog(str(tmp_path), fsync="never")
        log.append(_rec(1, 1))

        class _Store:
            generation = 1

            def apply_delta(self, cid, entity, row):
                return True

        wd = Watchdog(stall_after_s=5.0)
        f = LogFollower(log, lambda: _Store(), poll_interval_s=0.005)
        f.watch = wd.register("follower")
        f.start()
        try:
            deadline = time.monotonic() + 10
            while f.last_success_at is None and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            f.watch.set_thread(f.worker_thread)
            assert wd.check()[0] is True
        finally:
            f.stop()
            log.close()
        # stopped follower: its thread is dead -> the watchdog sees it
        assert wd.check()[0] is False


# ---------------------------------------------------------------------------
# stream.decode seam
# ---------------------------------------------------------------------------
class _FakeSource:
    def __init__(self, n=4):
        self.chunks = [Chunk(index=i, path="mem", n_rows=1)
                       for i in range(n)]

    def decode_chunk(self, chunk):
        return [("row", chunk.index)]


class TestStreamSeam:
    def test_corrupt_chunk_raises_under_raise_policy(self):
        get_injector().arm("stream.decode", "corrupt", max_fires=1)
        pl = ChunkPipeline(_FakeSource(), workers=1, depth=0,
                           on_error="raise")
        with pytest.raises(ValueError, match="injected corrupt"):
            list(pl)

    def test_corrupt_chunk_skipped_under_skip_policy(self):
        get_injector().arm("stream.decode", "corrupt", max_fires=1)
        pl = ChunkPipeline(_FakeSource(), workers=1, depth=0,
                           on_error="skip")
        out = list(pl)
        assert len(out) == 4  # order preserved, nothing dropped
        errored = [c.index for c, recs, err in out if err is not None]
        assert errored == [0] and pl.error_count == 1
        good = [recs for _, recs, err in out if err is None]
        assert len(good) == 3

    def test_slow_chunk_decodes_fine_just_late(self):
        inj = get_injector()
        inj.arm("stream.decode", "slow", max_fires=1,
                data={"stall_s": 0.02})
        out = list(ChunkPipeline(_FakeSource(), workers=1, depth=0))
        assert all(err is None for _, _, err in out)
        assert [recs[0][1] for _, recs, _ in out] == [0, 1, 2, 3]
        assert inj.fired("stream.decode") == 1
