"""Metric tests vs sklearn-free references (closed forms + scipy/numpy)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.evaluation import (
    EvaluationSuite,
    auc_pr,
    auc_roc,
    make_evaluator,
    precision_at_k,
    rmse,
)
from photon_ml_tpu.evaluation.evaluator import EvaluatorType, grouped_evaluate
from photon_ml_tpu.evaluation.metrics import logistic_loss_metric


def _np_auc(scores, labels, weights):
    """Reference implementation: probability that a random positive outranks a
    random negative, ties count half (exact, O(n^2))."""
    pos = [(s, w) for s, l, w in zip(scores, labels, weights) if l > 0.5 and w > 0]
    neg = [(s, w) for s, l, w in zip(scores, labels, weights) if l <= 0.5 and w > 0]
    num = den = 0.0
    for sp, wp in pos:
        for sn, wn in neg:
            num += wp * wn * (1.0 if sp > sn else 0.5 if sp == sn else 0.0)
            den += wp * wn
    return num / den if den else 0.5


def test_auc_exact(rng):
    n = 64
    scores = rng.normal(size=n)
    labels = (rng.random(n) > 0.4).astype(float)
    weights = rng.random(n) + 0.1
    got = float(auc_roc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)))
    np.testing.assert_allclose(got, _np_auc(scores, labels, weights), rtol=1e-10)


def test_auc_with_ties(rng):
    scores = np.asarray([1.0, 1.0, 1.0, 0.0, 0.0, 0.0, 0.5, 0.5])
    labels = np.asarray([1.0, 0.0, 1.0, 0.0, 1.0, 0.0, 1.0, 0.0])
    weights = np.ones(8)
    got = float(auc_roc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)))
    np.testing.assert_allclose(got, _np_auc(scores, labels, weights), rtol=1e-12)


def test_auc_degenerate():
    one = jnp.ones(4)
    assert float(auc_roc(jnp.arange(4.0), one, one)) == 0.5
    assert float(auc_roc(jnp.arange(4.0), jnp.zeros(4), one)) == 0.5


def test_auc_padding_inert(rng):
    n = 32
    scores = rng.normal(size=n)
    labels = (rng.random(n) > 0.5).astype(float)
    w = np.ones(n)
    a0 = float(auc_roc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(w)))
    scores2 = np.concatenate([scores, rng.normal(size=7)])
    labels2 = np.concatenate([labels, np.ones(7)])
    w2 = np.concatenate([w, np.zeros(7)])
    a1 = float(auc_roc(jnp.asarray(scores2), jnp.asarray(labels2), jnp.asarray(w2)))
    np.testing.assert_allclose(a0, a1, rtol=1e-12)


def test_aupr_perfect_and_random():
    s = jnp.asarray([3.0, 2.0, 1.0, 0.0])
    l = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    w = jnp.ones(4)
    np.testing.assert_allclose(float(auc_pr(s, l, w)), 1.0, rtol=1e-12)


def test_rmse_weighted(rng):
    s = rng.normal(size=20)
    l = rng.normal(size=20)
    w = rng.random(20) + 0.1
    expect = np.sqrt(np.sum(w * (s - l) ** 2) / np.sum(w))
    np.testing.assert_allclose(
        float(rmse(jnp.asarray(s), jnp.asarray(l), jnp.asarray(w))), expect, rtol=1e-12
    )


def test_precision_at_k():
    s = jnp.asarray([0.9, 0.8, 0.7, 0.6, 0.5])
    l = jnp.asarray([1.0, 0.0, 1.0, 1.0, 0.0])
    w = jnp.ones(5)
    np.testing.assert_allclose(float(precision_at_k(3, s, l, w)), 2.0 / 3.0, rtol=1e-12)
    # padding pushed out of ranking
    w0 = jnp.asarray([0.0, 1.0, 1.0, 1.0, 1.0])
    np.testing.assert_allclose(float(precision_at_k(3, s, l, w0)), 2.0 / 3.0, rtol=1e-12)


def test_grouped_evaluate_matches_per_group(rng):
    n = 60
    gids = rng.integers(0, 5, size=n)
    s = rng.normal(size=n)
    l = (rng.random(n) > 0.5).astype(float)
    w = np.ones(n)
    got = grouped_evaluate(
        lambda a, b, c: auc_roc(a, b, c), gids, jnp.asarray(s), jnp.asarray(l), jnp.asarray(w)
    )
    per = [_np_auc(s[gids == g], l[gids == g], w[gids == g]) for g in np.unique(gids)]
    np.testing.assert_allclose(got, np.mean(per), rtol=1e-9)


def test_evaluator_specs_and_ordering():
    ev = make_evaluator("auc")
    assert ev.kind == EvaluatorType.AUC and ev.larger_is_better
    assert ev.better_than(0.9, 0.8)
    ev2 = make_evaluator("rmse")
    assert not ev2.larger_is_better and ev2.better_than(0.1, 0.2)
    ev3 = make_evaluator("precision@5:userId")
    assert ev3.k == 5 and ev3.group_name == "userId" and ev3.name == "precision_at_k@5:userId"


def test_evaluation_suite(rng):
    n = 40
    s = jnp.asarray(rng.normal(size=n))
    l = jnp.asarray((rng.random(n) > 0.5).astype(float))
    w = jnp.ones(n)
    suite = EvaluationSuite.from_specs(["auc", "logistic_loss"], primary="auc")
    res = suite.evaluate(s, l, w)
    assert set(res.values) == {"auc", "logistic_loss"}
    np.testing.assert_allclose(res.primary, float(auc_roc(s, l, w)), rtol=1e-12)
    np.testing.assert_allclose(res.values["logistic_loss"], float(logistic_loss_metric(s, l, w)), rtol=1e-12)
