"""Native (C++) off-heap index store tests (reference PalDBIndexMap parity)."""

import numpy as np
import pytest

import photon_ml_tpu.data.native_index as ni
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.native_index import StoreIndexMap, build_store


@pytest.fixture(scope="module")
def store_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("idx") / "features.phidx")
    imap = IndexMap.build([feature_key(f"f{i}", f"t{i % 7}") for i in range(1000)])
    build_store(path, imap)
    return path, imap


class TestNativeStore:
    def test_native_lib_compiles(self):
        assert ni._native_lib() is not None, "g++ compile of index_store.cpp failed"

    def test_forward_lookup_parity(self, store_path):
        path, imap = store_path
        with StoreIndexMap(path) as store:
            assert store.size == imap.size
            for k, i in list(imap.items())[::97]:
                assert store.get_key(k) == i
            assert store.get_index("f3", "t3") == imap.get_index("f3", "t3")
            assert store.get_index("nope") == -1
            assert store.intercept_index == imap.intercept_index

    def test_reverse_lookup(self, store_path):
        path, imap = store_path
        with StoreIndexMap(path) as store:
            for i in range(0, imap.size, 131):
                assert store.get_feature_name(i) == imap.get_feature_name(i)
            assert store.get_feature_name(-1) is None
            assert store.get_feature_name(imap.size) is None

    def test_batch_lookup(self, store_path):
        path, imap = store_path
        keys = [feature_key(f"f{i}", f"t{i % 7}") for i in (0, 5, 999)] + ["missing"]
        with StoreIndexMap(path) as store:
            got = store.get_indices(keys)
        want = np.asarray([imap.get_index(*k.split("\x1f")) for k in keys[:3]] + [-1])
        np.testing.assert_array_equal(got, want)

    def test_duplicate_keys_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            build_store(str(tmp_path / "dup.phidx"), ["a", "b", "a"])

    def test_python_fallback_same_file(self, store_path, monkeypatch):
        """The pure-python prober reads files written by the C++ builder."""
        path, imap = store_path
        monkeypatch.setattr(ni, "_lib", None)
        monkeypatch.setattr(ni, "_lib_tried", True)
        with StoreIndexMap(path) as store:
            assert store._handle is None  # really on the fallback
            assert store.size == imap.size
            assert store.get_key(feature_key("f42", "t0")) == imap.get_index("f42", "t0")
            assert store.get_key("missing") == -1
            assert store.get_feature_name(3) == imap.get_feature_name(3)
            got = store.get_indices([feature_key("f1", "t1"), "zzz"])
            np.testing.assert_array_equal(
                got, [imap.get_index("f1", "t1"), -1])

    def test_python_builder_native_reader(self, tmp_path, store_path):
        """And the C++ prober reads files written by the python builder."""
        if ni._native_lib() is None:
            pytest.skip("no native lib")
        path = str(tmp_path / "py.phidx")
        ni._py_build(path, *ni._pack_keys([b"alpha", b"beta"]), 2)
        with StoreIndexMap(path) as store:
            assert store.get_key("alpha") == 0
            assert store.get_key("beta") == 1
            assert store.get_key("gamma") == -1

    def test_empty_store(self, tmp_path):
        path = str(tmp_path / "empty.phidx")
        build_store(path, [])
        with StoreIndexMap(path) as store:
            assert store.size == 0
            assert store.get_key("anything") == -1

    def test_truncated_store_rejected(self, tmp_path, store_path):
        """A file cut mid-write has valid magic; both readers must refuse it
        instead of faulting off the mapping."""
        src, _ = store_path
        data = open(src, "rb").read()
        trunc = str(tmp_path / "trunc.phidx")
        with open(trunc, "wb") as f:
            f.write(data[: len(data) // 3])
        with pytest.raises(ValueError):
            StoreIndexMap(trunc)
        # pure-python reader path too
        import photon_ml_tpu.data.native_index as mod
        orig, orig_tried = mod._lib, mod._lib_tried
        try:
            mod._lib, mod._lib_tried = None, True
            with pytest.raises(ValueError):
                StoreIndexMap(trunc)
        finally:
            mod._lib, mod._lib_tried = orig, orig_tried

    def test_large_store_smoke(self, tmp_path):
        n = 200_000
        path = str(tmp_path / "big.phidx")
        keys = [f"name{i}\x1fterm{i % 13}" for i in range(n)]
        build_store(path, keys)
        with StoreIndexMap(path) as store:
            assert store.size == n
            idx = store.get_indices([keys[0], keys[n // 2], keys[-1], "x"])
            np.testing.assert_array_equal(idx, [0, n // 2, n - 1, -1])
