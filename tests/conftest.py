"""Test harness: force an 8-device virtual CPU mesh BEFORE the jax backend initializes.

Mirrors the reference's test strategy (SURVEY.md §4): the reference exercises
distributed code on Spark local[*] in one JVM; we exercise SPMD code on
xla_force_host_platform_device_count=8 virtual CPU devices in one process.
float64 is enabled so parity tests against scipy/numpy are tight; library code
is dtype-agnostic (TPU runs follow input dtypes, normally bf16/f32).

NOTE: jax is pre-imported at interpreter startup in this image, so env vars are
set via jax.config.update (still effective pre-backend-init); XLA_FLAGS is read
at backend-client creation, which lazily happens at first device use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU even if the ambient environment points at a TPU.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    import numpy as np

    return np.random.default_rng(20260729)
