"""Test harness: force an 8-device virtual CPU mesh BEFORE the jax backend initializes.

Mirrors the reference's test strategy (SURVEY.md §4): the reference exercises
distributed code on Spark local[*] in one JVM; we exercise SPMD code on
xla_force_host_platform_device_count=8 virtual CPU devices in one process.
float64 is enabled so parity tests against scipy/numpy are tight; library code
is dtype-agnostic (TPU runs follow input dtypes, normally bf16/f32).

NOTE: jax is pre-imported at interpreter startup in this image, so env vars are
set via jax.config.update (still effective pre-backend-init); XLA_FLAGS is read
at backend-client creation, which lazily happens at first device use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU even if the ambient environment points at a TPU.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


_MULTIPROCESS_VERDICT = None  # session memo: (supported: bool, reason: str)

_MULTIPROCESS_PROBE = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid, port = int(sys.argv[1]), sys.argv[2]
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=2, process_id=pid)
import jax.numpy as jnp
from jax.experimental import multihost_utils
multihost_utils.process_allgather(jnp.ones(1))  # first cross-process op
"""


def multiprocess_backend_supported():
    """Probe (once per session) whether the backend can run CROSS-PROCESS
    computations: two jax.distributed subprocesses attempt one collective.
    ``jax.distributed.initialize`` itself succeeds everywhere — the CPU
    backend only fails at the first multi-process computation
    ("Multiprocess computations aren't implemented on the CPU backend"),
    so the probe must execute a collective, not just form the cluster.
    Returns ``(supported, reason)``."""
    global _MULTIPROCESS_VERDICT
    if _MULTIPROCESS_VERDICT is not None:
        return _MULTIPROCESS_VERDICT
    import socket
    import subprocess
    import sys
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    env.pop("PYTEST_CURRENT_TEST", None)
    with tempfile.NamedTemporaryFile("w", suffix="_mp_probe.py",
                                     delete=False) as f:
        f.write(_MULTIPROCESS_PROBE)
        probe = f.name
    try:
        procs = [subprocess.Popen(
            [sys.executable, probe, str(pid), port], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
            for pid in range(2)]
        try:
            errs = [p.communicate(timeout=120)[1] for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
                p.communicate()
            _MULTIPROCESS_VERDICT = (False, "multi-process probe timed out")
            return _MULTIPROCESS_VERDICT
        if all(p.returncode == 0 for p in procs):
            _MULTIPROCESS_VERDICT = (True, "")
        else:
            tail = next((e for p, e in zip(procs, errs) if p.returncode),
                        "").strip().splitlines()
            _MULTIPROCESS_VERDICT = (
                False, tail[-1] if tail else "probe worker failed")
        return _MULTIPROCESS_VERDICT
    finally:
        os.unlink(probe)


def require_multiprocess_backend():
    """Skip the calling test when the runtime cannot execute true
    multi-process computations (e.g. the CPU backend, which forms the
    jax.distributed cluster but rejects every cross-process op)."""
    supported, reason = multiprocess_backend_supported()
    if not supported:
        pytest.skip("multi-process computations unavailable on this "
                    f"backend: {reason}")


@pytest.fixture()
def rng():
    import numpy as np

    return np.random.default_rng(20260729)
