"""Data/IO tests: Avro codec roundtrips, index maps, readers, model
persistence, validation, checkpoints."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data import (
    DataValidationType,
    EntityIndex,
    IndexMap,
    feature_key,
    generate_glmix,
    index_map_for_libsvm,
    read_game_data_avro,
    read_libsvm,
    validate_game_data,
)
from photon_ml_tpu.data.schemas import (
    BAYESIAN_LINEAR_MODEL,
    INTERCEPT_NAME,
    TRAINING_EXAMPLE,
)
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.storage import load_game_model, save_game_model, save_glm_text
from photon_ml_tpu.storage.checkpoint import load_checkpoint, save_checkpoint
from photon_ml_tpu.types import TaskType


def _example(uid, y, feats, weight=None, offset=None, meta=None):
    return {
        "uid": uid, "response": y, "label": None,
        "features": [{"name": n, "term": t, "value": v} for n, t, v in feats],
        "weight": weight, "offset": offset, "metadataMap": meta,
    }


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_container_roundtrip(tmp_path, codec):
    path = str(tmp_path / "data.avro")
    records = [
        _example("a", 1.0, [("f1", "", 0.5), ("f2", "t", -2.0)], weight=2.0,
                 meta={"userId": "u1"}),
        _example(17, 0.0, [], offset=0.25),
        _example(None, 1.0, [("f1", "", 1.0)]),
    ]
    n = avro_io.write_container(path, TRAINING_EXAMPLE, records, codec=codec)
    assert n == 3
    back = list(avro_io.read_container(path))
    assert back == records
    assert avro_io.read_schema(path)["name"] == "TrainingExampleAvro"


def test_avro_many_records_blocks(tmp_path):
    path = str(tmp_path / "big.avro")
    records = [_example(i, float(i % 2), [("f", "", float(i))]) for i in range(10000)]
    avro_io.write_container(path, TRAINING_EXAMPLE, records, block_records=512)
    back = list(avro_io.read_container(path))
    assert len(back) == 10000
    assert back[9999]["features"][0]["value"] == 9999.0


def test_avro_corrupt_sync_detected(tmp_path):
    path = str(tmp_path / "x.avro")
    avro_io.write_container(path, TRAINING_EXAMPLE, [_example(1, 1.0, [])], codec="null")
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0xFF  # flip a sync byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="sync"):
        list(avro_io.read_container(path))


def test_index_map_build_save_load(tmp_path):
    m = IndexMap.from_features([("b", ""), ("a", "t1"), ("a", "")], add_intercept=True)
    assert m.intercept_index == 0
    assert m.size == 4
    assert m.get_index("a", "t1") >= 0
    assert m.get_index("nope") == -1
    name, term = m.get_feature_name(m.get_index("a", "t1"))
    assert (name, term) == ("a", "t1")
    path = str(tmp_path / "idx.bin")
    m.save(path)
    m2 = IndexMap.load(path)
    assert dict(m2.items()) == dict(m.items())


def test_index_map_lookup_reserved_separator_is_absent_not_crash():
    # feature_key rejects U+001F in names at keying time, but a data-derived
    # LOOKUP of such a name must follow the reference's absent-key contract
    # (IndexMap.scala:54 NULL_KEY -> -1), not raise mid-scoring.
    m = IndexMap.from_features([("a", "")])
    assert m.get_index("bad\x1fname") == -1
    with pytest.raises(ValueError):
        feature_key("bad\x1fname")


def test_read_game_data_avro(tmp_path):
    path = str(tmp_path / "train.avro")
    records = [
        _example("1", 1.0, [("x", "", 2.0)], meta={"userId": "alice"}),
        _example("2", 0.0, [("y", "", 3.0)], weight=2.0, meta={"userId": "bob"}),
        _example("3", 1.0, [("x", "", -1.0), ("y", "", 1.0)], offset=0.5,
                 meta={"userId": "alice"}),
    ]
    avro_io.write_container(path, TRAINING_EXAMPLE, records)
    imap = IndexMap.from_features([("x", ""), ("y", "")])
    data, eidx = read_game_data_avro([path], {"s": imap}, id_tag_names=["userId"])
    assert data.num_samples == 3
    x = data.features["s"]
    assert x[:, imap.intercept_index].tolist() == [1.0, 1.0, 1.0]
    assert x[0, imap.get_index("x")] == 2.0
    assert x[2, imap.get_index("y")] == 1.0
    assert data.weight[1] == 2.0 and data.offset[2] == 0.5
    # same user -> same entity id
    uids = data.id_tags["userId"]
    assert uids[0] == uids[2] != uids[1]
    assert eidx["userId"].name_of(int(uids[1])) == "bob"


def test_read_libsvm(tmp_path):
    path = str(tmp_path / "a1a.t")
    with open(path, "w") as f:
        f.write("-1 3:1 11:0.5\n+1 1:2\n")
    x, y, ii = read_libsvm(path, num_features=12)
    assert x.shape == (2, 13) and ii == 0
    assert y.tolist() == [0.0, 1.0]
    assert x[0, 3] == 1.0 and x[0, 11] == 0.5 and x[1, 1] == 2.0
    assert np.all(x[:, 0] == 1.0)
    m = index_map_for_libsvm(12)
    assert m.size == 13 and m.intercept_index == 0
    # POSITIONAL, not lexicographic: feature "2" is column 2, "10" column 10
    assert m.get_index("2") == 2
    assert m.get_index("10") == 10
    assert m.get_index("12") == 12


def test_validation(rng):
    data, _ = generate_glmix(n_users=3, per_user=10, d_global=4, d_user=2, seed=1)
    assert validate_game_data(data, TaskType.LOGISTIC_REGRESSION) == []
    bad = GameData(y=np.asarray([0.5, 1.0]), features={"g": np.ones((2, 2))})
    errs = validate_game_data(bad, TaskType.LOGISTIC_REGRESSION)
    assert any("binary" in e for e in errs)
    bad2 = GameData(y=np.asarray([1.0, np.nan]), features={"g": np.ones((2, 2))})
    errs = validate_game_data(bad2, TaskType.LINEAR_REGRESSION)
    assert any("labels" in e for e in errs)
    nw = GameData(y=np.ones(2), features={"g": np.ones((2, 2))},
                  weight=np.asarray([1.0, 0.0]))
    errs = validate_game_data(nw, TaskType.LINEAR_REGRESSION)
    assert any("positive" in e for e in errs)
    assert validate_game_data(bad, TaskType.LOGISTIC_REGRESSION,
                              DataValidationType.VALIDATE_DISABLED) == []


def test_game_model_roundtrip(tmp_path):
    d = 5
    imap = IndexMap.from_features([(f"f{j}", "") for j in range(d - 1)])
    eidx = EntityIndex()
    for name in ("alice", "bob", "carol"):
        eidx.get_or_add(name)
    fixed = FixedEffectModel(
        coefficients=Coefficients(means=np.asarray([0.0, 1.5, -2.0, 0.25, 0.0])),
        feature_shard="s", task=TaskType.LOGISTIC_REGRESSION)
    w = np.arange(15, dtype=np.float64).reshape(3, 5) / 10.0
    re = RandomEffectModel(w_stack=w, slot_of={0: 0, 1: 1, 2: 2},
                           random_effect_type="userId", feature_shard="s",
                           task=TaskType.LOGISTIC_REGRESSION)
    model = GameModel(models={"fixed": fixed, "per-user": re})

    out = str(tmp_path / "model")
    save_game_model(model, out, {"s": imap}, {"userId": eidx})
    assert os.path.exists(os.path.join(out, "fixed-effect", "fixed", "coefficients.avro"))
    assert os.path.exists(os.path.join(out, "random-effect", "per-user", "part-00000.avro"))

    eidx2 = EntityIndex()
    for name in ("alice", "bob", "carol"):
        eidx2.get_or_add(name)
    loaded, task = load_game_model(out, {"s": imap}, {"userId": eidx2})
    assert task == TaskType.LOGISTIC_REGRESSION
    np.testing.assert_allclose(loaded["fixed"].coefficients.means,
                               fixed.coefficients.means, rtol=1e-12)
    lre = loaded["per-user"]
    for eid in range(3):
        np.testing.assert_allclose(lre.w_stack[lre.slot_of[eid]], w[eid], rtol=1e-12)


def test_model_scores_survive_roundtrip(tmp_path, rng):
    """Loaded model must score identically to the in-memory one."""
    data, _ = generate_glmix(n_users=4, per_user=20, d_global=6, d_user=3, seed=2)
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameEstimator, RandomEffectConfig
    from photon_ml_tpu.game.config import GameConfig
    from photon_ml_tpu.opt.types import SolverConfig

    config = GameConfig(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "fixed": FixedEffectConfig(feature_shard="global",
                                       solver=SolverConfig(max_iters=30),
                                       reg=Regularization(l2=1.0)),
            "user": RandomEffectConfig(random_effect_type="userId",
                                       feature_shard="per_user",
                                       solver=SolverConfig(max_iters=30),
                                       reg=Regularization(l2=1.0)),
        },
    )
    res = GameEstimator().fit(data, [config])[0]
    imaps = {
        "global": IndexMap.from_features([(f"g{j}", "") for j in range(5)]),
        "per_user": IndexMap.from_features([(f"u{j}", "") for j in range(2)]),
    }
    out = str(tmp_path / "m")
    save_game_model(res.model, out, imaps, task=TaskType.LOGISTIC_REGRESSION)
    loaded, _ = load_game_model(out, imaps)
    s0 = np.asarray(res.model.score(data))
    s1 = np.asarray(loaded.score(data))
    np.testing.assert_allclose(s0, s1, rtol=1e-6, atol=1e-7)


def test_glm_text_export(tmp_path):
    imap = IndexMap.from_features([("age", ""), ("income", "high")])
    m = FixedEffectModel(
        coefficients=Coefficients(means=np.asarray([0.5, -1.25, 3.0])),
        feature_shard="s")
    path = str(tmp_path / "model.txt")
    save_glm_text(m, imap, path)
    lines = open(path).read().strip().split("\n")
    assert lines[0].startswith("income\thigh\t3")
    assert any(INTERCEPT_NAME in l for l in lines)


def test_checkpoint_roundtrip(tmp_path):
    imap = IndexMap.from_features([("f", "")])
    fixed = FixedEffectModel(
        coefficients=Coefficients(means=np.asarray([1.0, 2.0])), feature_shard="s")
    model = GameModel(models={"fixed": fixed})
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, model, {"s": imap}, {"iteration": 2, "coordinate": 1})
    loaded, task, cursor, best = load_checkpoint(ckpt, {"s": imap})
    assert cursor == {"iteration": 2, "coordinate": 1}
    assert best is None
    np.testing.assert_allclose(loaded["fixed"].coefficients.means, [1.0, 2.0])
    # overwrite with newer state is atomic
    save_checkpoint(ckpt, model, {"s": imap}, {"iteration": 3, "coordinate": 0})
    _, _, cursor, _ = load_checkpoint(ckpt, {"s": imap})
    assert cursor["iteration"] == 3


def test_checkpoint_recovers_from_orphaned_version(tmp_path):
    """Crash between version rename and pointer swap must not wedge saves."""
    import os

    imap = IndexMap.from_features([("f", "")])
    fixed = FixedEffectModel(
        coefficients=Coefficients(means=np.asarray([1.0, 2.0])), feature_shard="s")
    model = GameModel(models={"fixed": fixed})
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, model, {"s": imap}, {"iteration": 1, "coordinate": 0})
    # simulate the crash: an orphaned v2 exists but LATEST still points at v1
    os.makedirs(os.path.join(ckpt, "v2"))
    with open(os.path.join(ckpt, "v2", "junk"), "w") as f:
        f.write("partial")
    save_checkpoint(ckpt, model, {"s": imap}, {"iteration": 2, "coordinate": 0})
    _, _, cursor, _ = load_checkpoint(ckpt, {"s": imap})
    assert cursor["iteration"] == 2
    assert not os.path.exists(os.path.join(ckpt, "v2"))  # orphan pruned


def test_checkpoint_incremental_and_best(tmp_path, monkeypatch):
    """updated_coordinate re-serializes one coordinate (others hard-linked);
    the best-so-far model + evaluation survive the roundtrip."""
    import os

    from photon_ml_tpu.evaluation.evaluator import EvaluationResults

    imap = IndexMap.from_features([("f", "")])
    fixed = FixedEffectModel(
        coefficients=Coefficients(means=np.asarray([1.0, 2.0])), feature_shard="s")
    other = FixedEffectModel(
        coefficients=Coefficients(means=np.asarray([3.0, 4.0])), feature_shard="s")
    model = GameModel(models={"a": fixed, "b": other})
    ev = EvaluationResults(values={"auc": 0.9}, primary_name="auc")
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, model, {"s": imap}, {"iteration": 0, "coordinate": 1},
                    best=(model, ev), fingerprint="fp1")
    # incremental save: only "a" changed -> only "a" re-serialized
    import photon_ml_tpu.storage.checkpoint as ckpt_mod
    serialized = []
    real_save = ckpt_mod.save_coordinate
    monkeypatch.setattr(ckpt_mod, "save_coordinate",
                        lambda cid, *a, **k: (serialized.append(cid),
                                              real_save(cid, *a, **k))[1])
    model2 = GameModel(models={
        "a": FixedEffectModel(coefficients=Coefficients(means=np.asarray([9.0, 9.0])),
                              feature_shard="s"),
        "b": other})
    save_checkpoint(ckpt, model2, {"s": imap}, {"iteration": 1, "coordinate": 0},
                    updated_coordinate="a", best=(model, ev), best_changed=False,
                    fingerprint="fp1")
    assert serialized == ["a"]  # "b" and the best snapshot were linked
    loaded, _, cursor, best = load_checkpoint(ckpt, {"s": imap})
    assert cursor["fingerprint"] == "fp1"
    np.testing.assert_allclose(loaded["a"].coefficients.means, [9.0, 9.0])
    np.testing.assert_allclose(loaded["b"].coefficients.means, [3.0, 4.0])
    assert best is not None
    best_model, best_eval = best
    assert best_eval.primary == 0.9 and best_eval.primary_name == "auc"
    np.testing.assert_allclose(best_model["a"].coefficients.means, [1.0, 2.0])



def test_write_game_data_roundtrip(tmp_path, rng):
    """write_game_data_avro (reference AvroDataWriter.scala:159) round-trips
    through read_game_data_avro: same labels/weights/offsets/features/tags."""
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.data.reader import EntityIndex, read_game_data_avro
    from photon_ml_tpu.data.writer import write_game_data_avro
    from photon_ml_tpu.game.data import GameData

    n, d = 40, 5
    imap = IndexMap.from_features([(f"f{j}", "t") for j in range(d)],
                                  add_intercept=True)
    x = np.zeros((n, imap.size))
    x[:, imap.intercept_index] = 1.0
    dense = rng.normal(size=(n, d)) * (rng.random((n, d)) > 0.4)
    for j in range(d):
        x[:, imap.get_index(f"f{j}", "t")] = dense[:, j]
    eidx = EntityIndex()
    uid_names = [f"user{k}" for k in range(4)]
    tag = np.asarray([eidx.get_or_add(uid_names[i % 4]) for i in range(n)])
    data = GameData(y=(rng.random(n) > 0.5).astype(float),
                    features={"s": x},
                    offset=rng.normal(size=n), weight=rng.random(n) + 0.5,
                    id_tags={"userId": tag},
                    uids=np.asarray([f"u{i}" for i in range(n)], object))

    path = str(tmp_path / "out.avro")
    assert write_game_data_avro(data, path, {"s": imap},
                                {"userId": eidx}) == n

    back, back_idx = read_game_data_avro([path], {"s": imap},
                                         id_tag_names=["userId"],
                                         entity_indexes={"userId": EntityIndex()},
                                         dtype=np.float64)
    np.testing.assert_allclose(back.y, data.y)
    np.testing.assert_allclose(back.offset, data.offset, rtol=1e-12)
    np.testing.assert_allclose(back.weight, data.weight, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(back.features["s"]), x, rtol=1e-12)
    # entity ids survive by NAME through the fresh index
    names_out = [back_idx["userId"].name_of(int(e))
                 for e in back.id_tags["userId"]]
    names_in = [eidx.name_of(int(e)) for e in tag]
    assert names_out == names_in


def test_write_game_data_numpy_uids_and_empty_names(tmp_path, rng):
    """numpy-typed uids must encode; an entity literally named "" must not
    collapse into its integer surrogate."""
    from photon_ml_tpu.data import avro as avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.data.reader import EntityIndex
    from photon_ml_tpu.data.writer import write_game_data_avro
    from photon_ml_tpu.game.data import GameData

    imap = IndexMap.from_features([("a", "")], add_intercept=False)
    eidx = EntityIndex()
    assert eidx.get_or_add("") == 0  # empty-string entity name
    data = GameData(y=np.ones(3), features={"s": np.ones((3, 1))},
                    id_tags={"t": np.zeros(3, np.int64)},
                    uids=np.arange(3))  # np.int64 uids
    path = str(tmp_path / "w.avro")
    write_game_data_avro(data, path, {"s": imap}, {"t": eidx})
    recs = list(avro_io.read_container(path))
    assert [r["uid"] for r in recs] == [0, 1, 2]
    assert all(r["metadataMap"]["t"] == "" for r in recs)


def test_compact_random_effect_columnar_roundtrip(tmp_path):
    """CompactRandomEffectModel saves NATIVELY sparse in the columnar
    format (never materializing [E, d]) and loads back as itself, scoring
    identically; the dense-walking avro format refuses with remediation."""
    from photon_ml_tpu.models.game import (CompactRandomEffectModel,
                                           RandomEffectModel)

    rng = np.random.default_rng(4)
    e, d = 6, 40
    w = np.zeros((e, d), np.float64)
    for i in range(e):
        w[i, rng.choice(d, size=3, replace=False)] = rng.normal(size=3)
    imap = IndexMap.from_features([(f"f{j}", "") for j in range(d - 1)])
    eidx = EntityIndex()
    for i in range(e):
        eidx.get_or_add(f"u{i}")
    dense = RandomEffectModel(w_stack=w, slot_of={i: i for i in range(e)},
                              random_effect_type="userId", feature_shard="s",
                              task=TaskType.LOGISTIC_REGRESSION)
    compact = dense.to_compact()
    model = GameModel(models={"per-user": compact})
    out = str(tmp_path / "model")
    save_game_model(model, out, {"s": imap}, {"userId": eidx},
                    fmt="columnar")
    eidx2 = EntityIndex()
    for i in range(e):
        eidx2.get_or_add(f"u{i}")
    loaded, _ = load_game_model(out, {"s": imap}, {"userId": eidx2})
    lre = loaded["per-user"]
    assert isinstance(lre, CompactRandomEffectModel)
    np.testing.assert_array_equal(lre.to_dense().w_stack, w)

    gd = GameData(y=np.zeros(8),
                  features={"s": np.asarray(
                      np.random.default_rng(1).normal(size=(8, d)))},
                  id_tags={"userId": np.asarray([0, 1, 2, 3, 4, 5, 0, 77])})
    np.testing.assert_allclose(np.asarray(lre.score(gd)),
                               np.asarray(dense.score(gd)),
                               rtol=1e-9, atol=1e-12)

    with pytest.raises(ValueError, match="columnar"):
        save_game_model(model, str(tmp_path / "m2"), {"s": imap},
                        {"userId": eidx})
