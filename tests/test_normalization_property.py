"""Property tests for the normalization algebra.

Every solve runs in transformed space and publishes in original space
(reference NormalizationContext.scala:73-124), so the two maps being exact
inverses — and margins being invariant under the transform — is load-bearing
for every normalized fit.  Hypothesis drives random factors/shifts/models.
"""

import os
import sys

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # not in the image; skip, don't error at collection
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402  (conftest forces cpu + x64)

from photon_ml_tpu.core.normalization import NormalizationContext  # noqa: E402

_D = 6
_II = 0  # intercept index

_finite = st.floats(min_value=-10, max_value=10,
                    allow_nan=False, allow_infinity=False)
_factor = st.floats(min_value=0.05, max_value=20.0)


def _vec(elems, d=_D):
    return st.lists(elems, min_size=d, max_size=d).map(
        lambda v: np.asarray(v, np.float64))


@st.composite
def _contexts(draw):
    factors = draw(st.none() | _vec(_factor))
    shifts = draw(st.none() | _vec(_finite))
    if factors is not None:
        factors[_II] = 1.0
    if shifts is not None:
        shifts[_II] = 0.0
    return NormalizationContext(
        factors=None if factors is None else jnp.asarray(factors),
        shifts=None if shifts is None else jnp.asarray(shifts))


@settings(max_examples=80, deadline=None)
@given(ctx=_contexts(), w=_vec(_finite))
def test_space_maps_are_inverses(ctx, w):
    w = jnp.asarray(w)
    there = ctx.model_to_original_space(w, _II)
    back = ctx.model_to_transformed_space(there, _II)
    np.testing.assert_allclose(np.asarray(back), np.asarray(w),
                               rtol=1e-9, atol=1e-9)
    # and the other direction
    again = ctx.model_to_original_space(back, _II)
    np.testing.assert_allclose(np.asarray(again), np.asarray(there),
                               rtol=1e-9, atol=1e-9)


@settings(max_examples=80, deadline=None)
@given(ctx=_contexts(), w=_vec(_finite), x=_vec(_finite))
def test_margins_invariant_under_transform(ctx, w, x):
    """margin(original-space model, raw x) == margin(transformed model,
    normalized x): dot(w_orig, x) == dot(w, (x - shift) * factor) + intercept
    handling — the identity the effective-coefficients + margin-shift
    optimization (GLMObjective.margins) relies on.  The intercept column of
    raw x is the constant 1."""
    x = np.asarray(x)
    x[_II] = 1.0  # intercept column
    w = jnp.asarray(w)
    w_orig = ctx.model_to_original_space(w, _II)
    lhs = float(jnp.vdot(w_orig, jnp.asarray(x)))

    xn = x.copy()
    if ctx.shifts is not None:
        xn = xn - np.asarray(ctx.shifts)
    if ctx.factors is not None:
        xn = xn * np.asarray(ctx.factors)
    xn[_II] = 1.0  # intercept stays the constant column
    rhs = float(jnp.vdot(w, jnp.asarray(xn)))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-8, atol=1e-8)


@settings(max_examples=80, deadline=None)
@given(ctx=_contexts(), w=_vec(_finite), x=_vec(_finite))
def test_effective_coefficients_margin_shift_identity(ctx, w, x):
    """dot(eff(w), x) + margin_shift(w) == dot(w, (x - shift) * factor):
    the in-solver margins shortcut equals the explicit transform."""
    w = jnp.asarray(w)
    x_j = jnp.asarray(x)
    lhs = float(jnp.vdot(ctx.effective_coefficients(w), x_j)
                + ctx.margin_shift(w))
    xn = np.asarray(x, np.float64)
    if ctx.shifts is not None:
        xn = xn - np.asarray(ctx.shifts)
    if ctx.factors is not None:
        xn = xn * np.asarray(ctx.factors)
    rhs = float(jnp.vdot(w, jnp.asarray(xn)))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-8, atol=1e-8)
